"""SyncBN: cross-replica BatchNorm statistics (torch SyncBatchNorm).

The invariant that names the feature: with --sync-bn, the N-shard DP step
computes IDENTICAL batch statistics to a single device seeing the whole
global batch, so dpN == dp1 holds EXACTLY even for BN models — the claim
tests/test_dp.py explicitly cannot make for per-shard BN (its exactness
test uses a BN-free net). Also the principled fix for the batch-1-per-
shard degeneracy documented in train/loop.py's warning.
"""

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.train import loop


def _cfg(model="resnet18_thin", dp=8, sync_bn=True, **kw) -> TrainConfig:
    base = dict(
        model=model, global_batch_size=16, dtype="float32",
        log_every=10**9, sync_bn=sync_bn,
        parallel=ParallelConfig(data=dp),
        data=DataConfig(synthetic=True, image_size=32, num_classes=10,
                        synthetic_learnable=True),
        optimizer=OptimizerConfig(schedule="constant", learning_rate=0.01))
    base.update(kw)
    return TrainConfig(**base)


def _run_steps(cfg, n=3):
    mesh, model, batch_shd, state, train_step, _, rng = loop.build(cfg, n)
    src = datalib.make_source(cfg, "image", batch_shd)
    losses = []
    for i in range(n):
        state, metrics = train_step(state, src.batch(i), rng)
        losses.append(float(metrics["loss"]))
    return losses, jax.device_get(state.params)


@pytest.mark.core
@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_sync_bn_dp8_matches_dp1_exactly():
    """The defining invariant: global statistics make the whole training
    trajectory mesh-independent — exact to float32 tolerance."""
    l8, p8 = _run_steps(_cfg(dp=8))
    l1, p1 = _run_steps(_cfg(dp=1))
    np.testing.assert_allclose(l8, l1, rtol=1e-5, atol=1e-6)
    for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(p8),
                            jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(
            a, b, rtol=1e-5, atol=1e-6,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_per_shard_bn_differs_from_dp1():
    """Control: WITHOUT sync_bn the same setup diverges (per-shard
    statistics see batch 2, dp1 sees batch 16) — proving the invariant
    above is the flag's doing, not an accident of the data."""
    l8, _ = _run_steps(_cfg(dp=8, sync_bn=False))
    l1, _ = _run_steps(_cfg(dp=1, sync_bn=False))
    # Step 0's loss is computed before any BN-affected update matters to
    # the forward (stats are batch-local from the same global batch but
    # normalized per shard) — by step 2 the trajectories must have split.
    assert abs(l8[2] - l1[2]) > 1e-6


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_sync_bn_rescues_batch1_per_shard():
    """8 shards x 1 image: per-shard BN degenerates (loss pins at ln(10),
    see train/loop.py's warning); sync_bn pools statistics across the
    mesh and training proceeds."""
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        losses, _ = _run_steps(_cfg(dp=8, global_batch_size=8), n=4)
    assert abs(losses[0] - float(np.log(10.0))) > 1e-3 or \
        abs(losses[3] - losses[0]) > 1e-3


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_sync_bn_fused_block_matches_unfused():
    """fused_block's epilogue-sum statistics pmean identically to the
    unfused path's: same trajectory with both flags on."""
    lf, pf = _run_steps(_cfg(model="resnet26_thin", fused_block=True))
    lu, pu = _run_steps(_cfg(model="resnet26_thin", fused_block=False))
    np.testing.assert_allclose(lf, lu, rtol=1e-5, atol=1e-5)
    # Param tolerance is deliberately loose: the two paths compute y with
    # different reduction orders (1x1 conv vs matmul), and near-zero BN
    # inputs amplified by inv = rsqrt(var) turn that rounding into ~5e-5
    # per-step parameter drift (measured) — chaos, not error. The check
    # still catches structural breakage (a dropped pmean diverges at 1e-2+
    # and fails the loss assert above first).
    for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(pf),
                            jax.tree_util.tree_leaves(pu)):
        np.testing.assert_allclose(
            a, b, atol=1e-3,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.usefixtures("devices8")
def test_sync_bn_rejects_gspmd_configs():
    with pytest.raises(ValueError, match="shard_map"):
        loop.build(_cfg(dp=4, parallel=ParallelConfig(data=4, fsdp=2)), 1)


def test_sync_bn_rejects_bn_less_models():
    """--sync-bn with a BN-less image model (ViT) must fail with an
    actionable message, not an internal-kwarg TypeError."""
    with pytest.raises(ValueError, match="no BatchNorm"):
        loop.build(_cfg(model="vit_tiny", dp=1), 1)


def test_sync_bn_rejects_fused_bn():
    from distributeddeeplearning_tpu.models.resnet import resnet18

    model = resnet18(num_classes=10, fused_bn=True, bn_axis_name="data")
    x = np.zeros((2, 32, 32, 3), np.float32)
    with pytest.raises(ValueError, match="sync_bn"):
        model.init(jax.random.key(0), x, train=True)
