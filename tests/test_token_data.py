"""Packed-token MLM pipeline tests (config 4 real-data path)."""

from __future__ import annotations

import os

import numpy as np
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data import tokens
from distributeddeeplearning_tpu.data.synthetic import MASK_TOKEN_ID
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import sharding as shardlib

VOCAB = 2048
SEQ = 32


@pytest.fixture(scope="module")
def token_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("mlm_tokens")
    rng = np.random.default_rng(0)
    for shard in range(2):
        for split, n in (("train", 64), ("validation", 16)):
            ids = rng.integers(1000, VOCAB, (n, SEQ), dtype=np.int32)
            ids[:, 0] = tokens.CLS_ID
            ids[:, -1] = tokens.SEP_ID
            np.save(os.path.join(root, f"{split}-{shard}.npy"), ids)
    return str(root)


def _cfg(token_dir, dp=2, seq_axis=1):
    return TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        parallel=ParallelConfig(data=dp, seq=seq_axis),
        data=DataConfig(dataset="mlm", synthetic=False, data_dir=token_dir,
                        seq_len=SEQ, vocab_size=VOCAB),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="linear", label_smoothing=0.0))


def test_mask_batch_semantics():
    rng = np.random.default_rng(0)
    ids = np.full((4, 128), 1500, np.int32)
    ids[:, 0] = tokens.CLS_ID
    out = tokens.mask_batch(ids, mask_prob=0.15, vocab_size=VOCAB, rng=rng)
    masked = out["labels"] != -1
    # special positions are never masked
    assert not masked[:, 0].any()
    # labels hold original ids at masked positions
    assert (out["labels"][masked] == 1500).all()
    # ~80% of masked inputs became [MASK]
    frac_mask = (out["input_ids"][masked] == MASK_TOKEN_ID).mean()
    assert 0.6 < frac_mask < 0.95
    # unmasked positions unchanged
    assert (out["input_ids"][~masked] == ids[~masked]).all()
    # mask rate near 15%
    assert 0.08 < masked.mean() < 0.25


def test_stream_deterministic_resume(token_dir):
    cfg = _cfg(token_dir)
    mesh = meshlib.make_mesh(cfg.parallel)
    shd = shardlib.batch_sharding(mesh, seq_dim=1)
    a = tokens.make_token_source(cfg, shd)
    for i in range(3):
        b3 = a.batch(i)
    resumed = tokens.make_token_source(cfg, shd, start_step=2)
    r = resumed.batch(2)
    np.testing.assert_array_equal(np.asarray(b3["input_ids"]),
                                  np.asarray(r["input_ids"]))
    np.testing.assert_array_equal(np.asarray(b3["labels"]),
                                  np.asarray(r["labels"]))


def test_bert_end_to_end_real_tokens(token_dir):
    from distributeddeeplearning_tpu.train import loop

    cfg = _cfg(token_dir).replace(log_every=10**9)
    summary = loop.run(cfg, total_steps=3)
    assert summary["final_step"] == 3
    assert np.isfinite(summary["final_metrics"]["loss"])


@pytest.mark.core
def test_mlm_max_predictions_clamped_to_seq_len():
    # An explicit width beyond seq_len is meaningless and used to crash the
    # host pipeline with an opaque broadcast error while the synthetic path
    # silently narrowed (ADVICE r2 #1); both now see the clamped width.
    from distributeddeeplearning_tpu.config import resolve_mlm_max_predictions

    assert resolve_mlm_max_predictions(4096, 128, "mlm") == 128
    assert resolve_mlm_max_predictions(20, 128, "mlm") == 20
    assert resolve_mlm_max_predictions(-1, 128, "mlm") == 19
    assert resolve_mlm_max_predictions(4096, 128, "causal") == 0

    rng = np.random.default_rng(0)
    ids = rng.integers(5, 100, (4, 16)).astype(np.int32)
    out = tokens.gather_mask_batch(
        ids, max_pred=resolve_mlm_max_predictions(64, 16, "mlm"),
        mask_prob=0.15, vocab_size=100, rng=rng)
    assert out["masked_positions"].shape == (4, 16)
