"""Integration smokes (SURVEY.md §4): config-1 loop via the public API, CLI
surface, graft entry points, bench harness contract."""

import json
from pathlib import Path
import subprocess
import sys

import jax
import pytest

from distributeddeeplearning_tpu.config import (
    DataConfig, ParallelConfig, TrainConfig, preset, PRESETS)
from distributeddeeplearning_tpu.train import loop
from distributeddeeplearning_tpu.utils.logging import MetricLogger


def test_presets_construct():
    for name in PRESETS:
        cfg = preset(name)
        assert cfg.global_batch_size > 0
        assert cfg.parallel.num_devices >= 1


@pytest.mark.slow
def test_loop_smoke_resnet():
    cfg = TrainConfig(model="resnet18", global_batch_size=16, dtype="float32",
                      log_every=10**9, parallel=ParallelConfig(data=8),
                      data=DataConfig(image_size=32, num_classes=10))
    summary = loop.run(cfg, total_steps=3, warmup_steps=1,
                       logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 3
    assert "examples_per_sec" in summary
    assert summary["final_metrics"]["loss"] > 0


def test_graft_entry_forward():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == (8, 1000)


@pytest.mark.slow
def test_graft_dryrun_multichip():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


def test_metric_logger_jsonl(tmp_path):
    path = tmp_path / "metrics.jsonl"
    lg = MetricLogger(file_path=str(path), enabled=True,
                      stream=open("/dev/null", "w"))
    lg.log(1, {"loss": 2.5}, examples_per_step=32)
    lg.log(2, {"loss": 2.4}, examples_per_step=32)
    lg.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert lines[0]["step"] == 1 and lines[0]["loss"] == 2.5
    assert "examples_per_sec" in lines[1]


def test_tensorboard_metric_mirror(tmp_path):
    """tensorboard_dir mirrors scalars into TF event files (SURVEY.md §5.5)."""
    tb = tmp_path / "tb"
    lg = MetricLogger(tensorboard_dir=str(tb), enabled=True,
                      stream=open("/dev/null", "w"))
    lg.log(1, {"loss": 3.0}, examples_per_step=8)
    lg.log(2, {"loss": 2.0}, examples_per_step=8)
    lg.close()
    events = list(tb.glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0


@pytest.mark.slow
def test_profiler_trace_capture(tmp_path):
    """profile_steps=(1,2) writes a jax.profiler trace dir (SURVEY.md §5.1)."""
    cfg = TrainConfig(model="resnet18", global_batch_size=8, dtype="float32",
                      log_every=10**9,
                      profile_steps=(1, 2), profile_dir=str(tmp_path / "prof"),
                      data=DataConfig(image_size=32, num_classes=10))
    loop.run(cfg, total_steps=3, logger=MetricLogger(enabled=False))
    produced = list((tmp_path / "prof").rglob("*"))
    assert any(p.is_file() for p in produced), produced


@pytest.mark.slow
def test_train_cli_smoke():
    """End-to-end CLI run on the CPU backend (subprocess, tiny workload)."""
    out = subprocess.run(
        [sys.executable, "train.py", "--model", "resnet18",
         "--batch-size", "8", "--steps", "2", "--backend", "cpu",
         "--synthetic", "--dtype", "float32", "--dp", "1"],
        capture_output=True, text=True, timeout=600, cwd=".")
    assert out.returncode == 0, out.stderr[-2000:]
    last = out.stdout.strip().splitlines()[-1]
    rec = json.loads(last)
    assert rec["summary"]["final_step"] == 2


def test_bench_error_path_emits_parseable_json(tmp_path):
    """A child that cannot even build its model must still produce exactly
    one parseable JSON line with an error record (the driver contract)."""
    proc = subprocess.run(
        [sys.executable, "bench.py", "--platform", "cpu",
         "--model", "no_such_model", "--attempts", "1",
         "--attempt-timeout", "120", "--budget", "180"],
        capture_output=True, text=True, timeout=300,
        cwd=str(Path(__file__).resolve().parent.parent))
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["value"] is None
    assert "no_such_model" in rec["error"] or "unknown model" in rec["error"]
