"""Pipeline parallelism (models/pipeline.py; wires ParallelConfig.pipeline).

Checks: (a) the GPipe and interleaved-1f1b schedules compute exactly what a
sequential pass over the same stacked layer params computes, (b) layer params
actually shard over the ``pipeline`` mesh axis, (c) a pp x dp x tp train step
runs and optimizes, (d) schedule equivalence — gpipe and 1f1b reach the same
final params at identical geometry (SGD-momentum and AdamW), (e) the 1f1b
path composes with ZeRO-2, warm-boots through the AOT executable cache with
zero retraces, and resumes across schedules via the canonical (schedule-
portable) checkpoint layout (docs/pipeline.md).
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokens
from distributeddeeplearning_tpu.models import bert, model_spec
from distributeddeeplearning_tpu.models.pipeline import (
    PipelinedEncoder, build_schedule)
from distributeddeeplearning_tpu.parallel.mesh import make_mesh
from distributeddeeplearning_tpu.train import optim, steps
import pytest


def test_pipeline_matches_sequential():
    """GPipe output == applying the same stacked layers in order."""
    cfg = bert.BertConfig(vocab_size=256, hidden_size=32, num_layers=4,
                          num_heads=2, intermediate_size=64, max_position=64,
                          dropout_rate=0.0)
    enc = PipelinedEncoder(
        layer_factory=functools.partial(bert.EncoderLayer, cfg, jnp.float32),
        num_stages=2, layers_per_stage=2, num_microbatches=4,
        dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (8, 16, 32), jnp.float32)
    mask = jnp.ones((8, 16), bool)
    variables = enc.init({"params": jax.random.key(1)}, x, mask,
                         deterministic=True)
    out = enc.apply(variables, x, mask, deterministic=True)

    layer_params = nn.meta.unbox(variables["params"])["stages"]["layer"]
    ref = x
    layer = bert.EncoderLayer(cfg, jnp.float32)
    for p in range(2):
        for l in range(2):  # noqa: E741
            sliced = jax.tree_util.tree_map(lambda a: a[p, l], layer_params)
            ref = layer.apply({"params": sliced}, ref, mask,
                              deterministic=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_forward_mesh_invariant(devices8):
    """Same params + batch -> bitwise-identical logits on every (data,
    pipeline) mesh factorization. Guards the strided microbatch split: the
    old contiguous (m, mb) reshape of a data-sharded batch dim let XLA SPMD
    propagation (observed on jax 0.4.37) materialize the shard-local
    example grouping under the global grouping's name, so each mesh fed
    each microbatch a *different* set of examples — surfacing as per-step
    trajectory drift whenever the elastic controller re-formed across dp
    (tests/test_elastic_resume.py), not as any visible shape error."""
    from jax.sharding import NamedSharding
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel import sharding as shardlib
    from distributeddeeplearning_tpu.parallel.mesh import use_mesh

    model = get_model("bert_tiny_pp44", vocab_size=1024, dtype=jnp.float32)
    src = SyntheticTokens(8, 16, 1024, seed=0)
    batch = src.batch(2)
    init_rules = list(shardlib.logical_rules(
        ParallelConfig(data=1, pipeline=2)))
    with nn.logical_axis_rules(init_rules):
        params = nn.meta.unbox(model.init(
            {"params": jax.random.key(0), "dropout": jax.random.key(0)},
            batch["input_ids"], train=False))["params"]
    params = jax.tree_util.tree_map(np.asarray, params)

    def logits_under(dp, pp):
        par = ParallelConfig(data=dp, pipeline=pp)
        mesh = make_mesh(par)
        bshd = shardlib.batch_sharding(mesh, seq_dim=1)
        rules = list(shardlib.logical_rules(par))

        def fwd(p, ids, am):
            with nn.logical_axis_rules(rules):
                out, _ = model.apply({"params": p}, ids, attention_mask=am,
                                     train=False, mutable=["moe_losses"])
            return out

        jitted = jax.jit(fwd,
                         in_shardings=(NamedSharding(mesh, P()), bshd, bshd),
                         out_shardings=NamedSharding(mesh, P()))
        with use_mesh(mesh):
            return np.asarray(jitted(params, batch["input_ids"],
                                     batch["attention_mask"]))

    ref = logits_under(1, 2)
    for dp, pp in ((4, 2), (2, 4), (1, 4)):
        got = logits_under(dp, pp)
        np.testing.assert_array_equal(
            got, ref, err_msg=f"pipelined forward differs on dp={dp} pp={pp}")


def _pp_cfg():
    return TrainConfig(
        model="bert_tiny_pp", global_batch_size=8, dtype="float32",
        parallel=ParallelConfig(pipeline=2, data=2, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=1024),
        # reference_batch=8: linear-scaling identity, real learning signal.
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  reference_batch=8,
                                  schedule="linear", label_smoothing=0.0))


def _build():
    cfg = _pp_cfg()
    mesh = make_mesh(cfg.parallel)
    model = model_spec("bert_tiny_pp").build(vocab_size=1024,
                                             dtype=jnp.float32)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticTokens(8, 32, 1024, seed=7)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), jax.random.key(0), "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens")
    return src, state, step


def test_pp_params_shard(devices8):
    _, state, _ = _build()
    qk = (state.params["pipeline"]["stages"]["layer"]["attention"]["query"]
          ["kernel"].value)
    # (stages, layers_per_stage, embed, heads): stages over `pipeline`,
    # heads over `model`.
    assert qk.ndim == 4
    assert qk.sharding.spec == P("pipeline", None, None, "model"), qk.sharding


@pytest.mark.slow
def test_pp_step_trains(devices8):
    src, state, step = _build()
    rng = jax.random.key(42)
    fixed = src.batch(0)
    first = last = None
    for _ in range(8):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first, (first, last)


def test_unconsumed_axis_rejected(devices8):
    """A pipeline/expert mesh axis no model dim maps onto must error, not
    silently duplicate compute across its groups."""
    import pytest
    from distributeddeeplearning_tpu.train import loop

    cfg = _pp_cfg().replace(model="bert_tiny")  # not pipelined
    with pytest.raises(ValueError, match="pipeline_stages"):
        loop.build(cfg, total_steps=1)
    moe_less = _pp_cfg().replace(
        model="bert_tiny",
        parallel=ParallelConfig(data=4, expert=2))
    with pytest.raises(ValueError, match="num_experts"):
        loop.build(moe_less, total_steps=1)


# ---------------------------------------------------------------------------
# Schedule tables (pure Python — no devices)
# ---------------------------------------------------------------------------

@pytest.mark.pipeline
def test_schedule_bubble_matches_analytic():
    """The enumerated table's idle fraction IS the closed form
    (P-1)/(M*V+P-1) whenever P | M — for gpipe (V=1) and interleaved
    1f1b alike. 1f1b with V>1 strictly shrinks the bubble."""
    for name, p, m, v in (("gpipe", 2, 4, 1), ("gpipe", 4, 8, 1),
                          ("1f1b", 2, 4, 2), ("1f1b", 4, 8, 2),
                          ("1f1b", 2, 8, 4)):
        sched = build_schedule(name, num_stages=p, num_microbatches=m,
                               virtual_stages=v)
        assert sched.bubble_fraction() == pytest.approx(
            sched.analytic_bubble_fraction()), (name, p, m, v)
        assert sched.analytic_bubble_fraction() == pytest.approx(
            (p - 1) / (m * v + p - 1))
    gp = build_schedule("gpipe", num_stages=2, num_microbatches=4)
    il = build_schedule("1f1b", num_stages=2, num_microbatches=4,
                        virtual_stages=2)
    assert il.bubble_fraction() < gp.bubble_fraction()


@pytest.mark.pipeline
def test_schedule_conservation():
    """Every microbatch is injected exactly once, emitted exactly once, and
    each stage works each (microbatch, chunk) pair exactly once."""
    for name, p, m, v in (("gpipe", 2, 6, 1), ("1f1b", 4, 8, 2)):
        sched = build_schedule(name, num_stages=p, num_microbatches=m,
                               virtual_stages=v)
        injected = [t.inject_mb for t in sched.ticks
                    if t.inject_mb is not None]
        emitted = [t.emit_mb for t in sched.ticks if t.emit_mb is not None]
        assert sorted(injected) == list(range(m))
        assert sorted(emitted) == list(range(m))
        for k in range(p):
            work = [t.occupancy[k] for t in sched.ticks
                    if t.occupancy[k] is not None]
            assert sorted(work) == sorted(
                (mb, c) for mb in range(m) for c in range(v)), (name, k)


@pytest.mark.pipeline
def test_shift_pairs_forms():
    """The activation shift entering every tick carries the full forward
    ring k -> k+1; the wrap edge P-1 -> 0 (1f1b chunk re-entry / gpipe
    drain) appears exactly on the ticks where stage 0 takes no fresh
    microbatch — the pairing the ddl-lint rule verifies against dataflow."""
    sched = build_schedule("1f1b", num_stages=4, num_microbatches=8,
                           virtual_stages=2)
    p = sched.num_stages
    for tick in sched.ticks:
        pairs = sched.shift_pairs(tick.index)
        fwd = {(k, k + 1) for k in range(p - 1)}
        assert fwd <= set(pairs), tick
        if tick.inject_mb is None:
            assert (p - 1, 0) in pairs, tick
        else:
            assert (p - 1, 0) not in pairs, tick


@pytest.mark.pipeline
def test_build_schedule_rejects():
    with pytest.raises(ValueError, match="unknown"):
        build_schedule("zb-h1", num_stages=2, num_microbatches=4)
    with pytest.raises(ValueError, match="gpipe"):
        build_schedule("gpipe", num_stages=2, num_microbatches=4,
                       virtual_stages=2)
    with pytest.raises(ValueError, match="divisible"):
        build_schedule("1f1b", num_stages=4, num_microbatches=6,
                       virtual_stages=2)


@pytest.mark.pipeline
def test_config_fingerprint_separates_schedules():
    """perf/aot.py: gpipe, 1f1b and each virtual-stage count compile
    different programs, so their AOT/bench fingerprints must differ — two
    records with different schedules are different experiments."""
    from distributeddeeplearning_tpu.perf import aot as aotlib

    base = _pp_cfg().replace(model="bert_tiny_pp4")
    fps = {aotlib.config_fingerprint(
        base.replace(pipeline_schedule=s, pipeline_virtual_stages=v),
        total_steps=10)
        for s, v in (("gpipe", 1), ("1f1b", 1), ("1f1b", 2))}
    assert len(fps) == 3


# ---------------------------------------------------------------------------
# Model equivalence: 1f1b == gpipe == sequential
# ---------------------------------------------------------------------------

def _tiny_encoder(schedule, virtual_stages):
    # Smallest geometry that still exercises V=2 interleaving: 4 layers =
    # P*V chunks of one layer each, M=2 microbatches (1f1b needs P | M).
    # Kept tiny on purpose — three separately-compiled programs ride on it
    # in tier-1, so its compile time is paid three times per run.
    cfg = bert.BertConfig(vocab_size=64, hidden_size=16, num_layers=4,
                          num_heads=2, intermediate_size=32, max_position=16,
                          dropout_rate=0.0)
    return PipelinedEncoder(
        layer_factory=functools.partial(bert.EncoderLayer, cfg, jnp.float32),
        num_stages=2, layers_per_stage=2, num_microbatches=2,
        schedule=schedule, virtual_stages=virtual_stages, dtype=jnp.float32)


@pytest.mark.pipeline
def test_1f1b_matches_gpipe_forward():
    """Interleaved 1f1b output == gpipe output on the SAME params (the init
    tree is schedule-portable, so one init serves both applies); V=1 1f1b
    is bitwise gpipe (identical tick tables)."""
    gp = _tiny_encoder("gpipe", 1)
    x = jax.random.normal(jax.random.key(0), (4, 8, 16), jnp.float32)
    mask = jnp.ones((4, 8), bool)
    variables = gp.init({"params": jax.random.key(1)}, x, mask,
                        deterministic=True)
    out_gp = gp.apply(variables, x, mask, deterministic=True)
    out_v1 = _tiny_encoder("1f1b", 1).apply(variables, x, mask,
                                            deterministic=True)
    np.testing.assert_array_equal(np.asarray(out_v1), np.asarray(out_gp))
    out_v2 = _tiny_encoder("1f1b", 2).apply(variables, x, mask,
                                            deterministic=True)
    np.testing.assert_allclose(np.asarray(out_v2), np.asarray(out_gp),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.pipeline
@pytest.mark.slow
def test_1f1b_matches_gpipe_grads_bitwise():
    """The strong equivalence pin: on one device, the V=2 interleaved
    program backpropagates to BITWISE-identical gradients for every leaf.
    The schedules reorder *when* each (microbatch, chunk) runs, not *what*
    runs — per-leaf gradient accumulation order is fixed by the scan
    structure, so any numeric daylight between the schedules must come
    from a partitioner's resharding choices (which the multi-device parity
    test bounds), never from the schedule itself."""
    gp = _tiny_encoder("gpipe", 1)
    x = jax.random.normal(jax.random.key(0), (4, 8, 16), jnp.float32)
    mask = jnp.ones((4, 8), bool)
    variables = gp.init({"params": jax.random.key(1)}, x, mask,
                        deterministic=True)

    def loss_fn(m):
        def f(params):
            out = m.apply({"params": params}, x, mask, deterministic=True)
            return jnp.sum(out * out)
        return f

    g_gp = jax.grad(loss_fn(gp))(variables["params"])
    g_il = jax.grad(loss_fn(_tiny_encoder("1f1b", 2)))(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(g_gp),
                    jax.tree_util.tree_leaves(g_il)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _pp4_cfg(schedule="gpipe", virtual_stages=1, optimizer="adamw",
             sharding="none"):
    return TrainConfig(
        model="bert_tiny_pp4", global_batch_size=8, dtype="float32",
        optimizer_sharding=sharding,
        pipeline_schedule=schedule, pipeline_virtual_stages=virtual_stages,
        parallel=ParallelConfig(pipeline=2, data=2, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=1024),
        optimizer=OptimizerConfig(name=optimizer, learning_rate=1e-3,
                                  momentum=0.9, reference_batch=8,
                                  schedule="linear", label_smoothing=0.0))


def _build_pp4(cfg):
    mesh = make_mesh(cfg.parallel)
    model = model_spec(cfg.model).build(
        vocab_size=1024, dtype=jnp.float32,
        pipeline_schedule=cfg.pipeline_schedule,
        pipeline_virtual_stages=cfg.pipeline_virtual_stages)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticTokens(8, 32, 1024, seed=7)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), jax.random.key(0), "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens")
    return src, state, step, shardings


@pytest.mark.pipeline
@pytest.mark.slow
@pytest.mark.parametrize("optimizer,rtol,atol",
                         [("sgd", 1e-4, 1e-4), ("adamw", 1e-3, 5e-3)])
def test_final_params_parity_gpipe_vs_1f1b(devices8, optimizer, rtol, atol):
    """Schedule equivalence end to end: gpipe and interleaved 1f1b train to
    the SAME final params at identical geometry — SGD-momentum and AdamW.
    Same seed gives a bitwise-identical init tree (the init path is one
    schedule-independent full-stack call) and the per-leaf gradient math is
    bitwise identical (test_1f1b_matches_gpipe_grads_bitwise), so all the
    daylight here is the GSPMD partitioner resharding the two programs
    differently across the 2x2x2 mesh — ULP-level gradient reassociation,
    not schedule error. SGD integrates that noise linearly (measured
    ~1.5e-5 after 3 steps; bound 1e-4). Adam divides it by sqrt(v), so on
    near-zero-gradient elements a ULP-level sign flip becomes an O(lr)
    update difference per step — its bound is a few lr (5e-3), which still
    catches any real routing bug (wrong-chunk params diverge at the 1e-1
    param scale)."""
    finals = {}
    for schedule, v in (("gpipe", 1), ("1f1b", 2)):
        cfg = _pp4_cfg(schedule, v, optimizer=optimizer)
        src, state, step, _ = _build_pp4(cfg)
        rng = jax.random.key(42)
        fixed = src.batch(0)
        for _ in range(3):
            state, metrics = step(state, fixed, rng)
        assert np.isfinite(float(metrics["loss"]))
        finals[schedule] = jax.device_get(state.params)
    flat_gp = jax.tree_util.tree_leaves(finals["gpipe"])
    flat_il = jax.tree_util.tree_leaves(finals["1f1b"])
    for a, b in zip(flat_gp, flat_il):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)


@pytest.mark.pipeline
@pytest.mark.slow
def test_zero2_composes_with_1f1b(devices8):
    """ZeRO-2 + interleaved 1f1b: optimizer-moment leaves pick up the DP
    axis on top of their stage/tp axes (the reduce-scatter layout) and the
    composed step still optimizes."""
    cfg = _pp4_cfg("1f1b", 2, sharding="zero2")
    src, state, step, shardings = _build_pp4(cfg)
    mu = shardings.opt_state[0].mu["pipeline"]["stages"]["layer"][
        "attention"]["query"]["kernel"]
    assert "data" in jax.tree_util.tree_leaves(
        [mu.spec], is_leaf=lambda x: isinstance(x, P))[0], mu
    rng = jax.random.key(42)
    fixed = src.batch(0)
    first = last = None
    for _ in range(6):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first, (first, last)


# ---------------------------------------------------------------------------
# AOT warm boot + cross-schedule checkpoint resume (loop.run end to end)
# ---------------------------------------------------------------------------

def _loop_cfg(tmp_path, schedule, virtual_stages, **kw):
    base = dict(
        model="bert_tiny_pp4", global_batch_size=8, dtype="float32",
        backend="cpu", log_every=10**9,
        pipeline_schedule=schedule, pipeline_virtual_stages=virtual_stages,
        parallel=ParallelConfig(pipeline=2, data=4),
        data=DataConfig(synthetic=True, dataset="mlm", seq_len=32,
                        vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  reference_batch=8, schedule="constant",
                                  warmup_epochs=0.0))
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.pipeline
@pytest.mark.slow
@pytest.mark.usefixtures("devices8")
@pytest.mark.parametrize("schedule,v", [("gpipe", 1), ("1f1b", 2)])
def test_aot_warm_boot_zero_retrace(tmp_path, monkeypatch, schedule, v):
    """A second boot of the identical pipelined config deserializes the
    gspmd step executable — ZERO retraces of the tick loop — and, because
    the pipeline_tick instants fire only at trace time, the warm summary
    honestly reports bubble_fraction as absent rather than 0."""
    from distributeddeeplearning_tpu.perf import compile_cache
    from distributeddeeplearning_tpu.robustness import faults
    from distributeddeeplearning_tpu.train import loop

    cache = str(tmp_path / "cache")
    monkeypatch.setenv(compile_cache.ENV_CACHE, cache)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", cache)
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_ATTEMPT, raising=False)
    cfg = _loop_cfg(tmp_path, schedule, v, compile_cache_dir=cache)
    try:
        s1 = loop.run(cfg, total_steps=2)
        assert s1["compile_cache"]["sources"]["gspmd_train_step"] == \
            "compiled"
        before = steps.TRACE_COUNTS["gspmd_train_step"]
        s2 = loop.run(cfg, total_steps=2)
        assert steps.TRACE_COUNTS["gspmd_train_step"] == before  # ZERO
        assert s2["compile_cache"]["sources"]["gspmd_train_step"] == \
            "aot_hit"
        assert s1["final_metrics"]["loss"] == s2["final_metrics"]["loss"]
        assert s2["pipeline"]["schedule"] == schedule
        assert s2["pipeline"]["bubble_fraction"] is None  # no trace, no lie
    finally:
        jax.config.update("jax_compilation_cache_dir",
                          compile_cache.default_dir())


@pytest.mark.pipeline
@pytest.mark.slow
@pytest.mark.usefixtures("devices8")
def test_cross_schedule_checkpoint_resume(tmp_path):
    """The canonical (stage-major, schedule-portable) param layout lets a
    gpipe checkpoint resume under interleaved 1f1b: run 1 trains gpipe and
    saves; run 2 restores the same tree under 1f1b and keeps training."""
    from distributeddeeplearning_tpu.train import loop

    ckpt = str(tmp_path / "ckpt")
    cfg_gp = _loop_cfg(tmp_path, "gpipe", 1, checkpoint_dir=ckpt,
                       checkpoint_every_steps=1)
    s1 = loop.run(cfg_gp, total_steps=2)
    assert s1["final_step"] == 2
    cfg_il = _loop_cfg(tmp_path, "1f1b", 2, checkpoint_dir=ckpt,
                       checkpoint_every_steps=1)
    s2 = loop.run(cfg_il, total_steps=4)
    assert s2["start_step"] == 2  # restored, not retrained
    assert s2["final_step"] == 4
    assert np.isfinite(s2["final_metrics"]["loss"])
    assert s2["pipeline"]["schedule"] == "1f1b"
