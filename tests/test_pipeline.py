"""Pipeline parallelism (models/pipeline.py; wires ParallelConfig.pipeline).

Checks: (a) the GPipe schedule computes exactly what a sequential pass over
the same stacked layer params computes, (b) layer params actually shard over
the ``pipeline`` mesh axis, (c) a pp x dp x tp train step runs and optimizes.
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokens
from distributeddeeplearning_tpu.models import bert, model_spec
from distributeddeeplearning_tpu.models.pipeline import PipelinedEncoder
from distributeddeeplearning_tpu.parallel.mesh import make_mesh
from distributeddeeplearning_tpu.train import optim, steps
import pytest


def test_pipeline_matches_sequential():
    """GPipe output == applying the same stacked layers in order."""
    cfg = bert.BertConfig(vocab_size=256, hidden_size=32, num_layers=4,
                          num_heads=2, intermediate_size=64, max_position=64,
                          dropout_rate=0.0)
    enc = PipelinedEncoder(
        layer_factory=functools.partial(bert.EncoderLayer, cfg, jnp.float32),
        num_stages=2, layers_per_stage=2, num_microbatches=4,
        dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (8, 16, 32), jnp.float32)
    mask = jnp.ones((8, 16), bool)
    variables = enc.init({"params": jax.random.key(1)}, x, mask,
                         deterministic=True)
    out = enc.apply(variables, x, mask, deterministic=True)

    layer_params = nn.meta.unbox(variables["params"])["stages"]["layer"]
    ref = x
    layer = bert.EncoderLayer(cfg, jnp.float32)
    for p in range(2):
        for l in range(2):  # noqa: E741
            sliced = jax.tree_util.tree_map(lambda a: a[p, l], layer_params)
            ref = layer.apply({"params": sliced}, ref, mask,
                              deterministic=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def _pp_cfg():
    return TrainConfig(
        model="bert_tiny_pp", global_batch_size=8, dtype="float32",
        parallel=ParallelConfig(pipeline=2, data=2, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=1024),
        # reference_batch=8: linear-scaling identity, real learning signal.
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  reference_batch=8,
                                  schedule="linear", label_smoothing=0.0))


def _build():
    cfg = _pp_cfg()
    mesh = make_mesh(cfg.parallel)
    model = model_spec("bert_tiny_pp").build(vocab_size=1024,
                                             dtype=jnp.float32)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticTokens(8, 32, 1024, seed=7)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), jax.random.key(0), "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens")
    return src, state, step


def test_pp_params_shard(devices8):
    _, state, _ = _build()
    qk = (state.params["pipeline"]["stages"]["layer"]["attention"]["query"]
          ["kernel"].value)
    # (stages, layers_per_stage, embed, heads): stages over `pipeline`,
    # heads over `model`.
    assert qk.ndim == 4
    assert qk.sharding.spec == P("pipeline", None, None, "model"), qk.sharding


@pytest.mark.slow
def test_pp_step_trains(devices8):
    src, state, step = _build()
    rng = jax.random.key(42)
    fixed = src.batch(0)
    first = last = None
    for _ in range(8):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first, (first, last)


def test_unconsumed_axis_rejected(devices8):
    """A pipeline/expert mesh axis no model dim maps onto must error, not
    silently duplicate compute across its groups."""
    import pytest
    from distributeddeeplearning_tpu.train import loop

    cfg = _pp_cfg().replace(model="bert_tiny")  # not pipelined
    with pytest.raises(ValueError, match="pipeline_stages"):
        loop.build(cfg, total_steps=1)
    moe_less = _pp_cfg().replace(
        model="bert_tiny",
        parallel=ParallelConfig(data=4, expert=2))
    with pytest.raises(ValueError, match="num_experts"):
        loop.build(moe_less, total_steps=1)
