"""GSPMD path: tp/sp sharding correctness for the transformer workloads
(config 4). Checks that (a) kernels actually shard per the Megatron rules,
(b) a dp x sp x tp step runs and trains, (c) the sharded forward matches the
unsharded forward numerically (XLA collectives preserve semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokens
from distributeddeeplearning_tpu.models import bert
from distributeddeeplearning_tpu.parallel.mesh import make_mesh
from distributeddeeplearning_tpu.train import optim, steps


def bert_cfg(parallel: ParallelConfig) -> TrainConfig:
    return TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        parallel=parallel,
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=1024),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  schedule="linear", label_smoothing=0.0))


def build_sharded(parallel, devices8):
    cfg = bert_cfg(parallel)
    mesh = make_mesh(cfg.parallel)
    model = bert.tiny_bert_mlm(vocab_size=1024)
    tx, _ = optim.make_optimizer(cfg.optimizer, cfg.global_batch_size, 100)
    src = SyntheticTokens(8, 32, 1024, seed=7)
    rng = jax.random.key(0)
    state, shardings = steps.init_sharded_state(
        model, tx, mesh, cfg, src.batch(0), rng, "tokens")
    step = steps.make_gspmd_train_step(model, tx, mesh, cfg, shardings,
                                       "tokens")
    return cfg, mesh, model, src, state, step, rng


def test_tp_kernel_sharding(devices8):
    _, mesh, _, _, state, _, _ = build_sharded(
        ParallelConfig(data=2, seq=2, model=2), devices8)
    qk = state.params["layer0"]["attention"]["query"]["kernel"].value
    assert qk.sharding.spec == P(None, "model"), qk.sharding
    mlp_in = state.params["layer0"]["intermediate"]["kernel"].value
    assert mlp_in.sharding.spec == P(None, "model")
    mlp_out = state.params["layer0"]["mlp_output"]["kernel"].value
    assert mlp_out.sharding.spec == P("model", None)
    emb = state.params["word_embeddings"].value
    assert emb.sharding.spec == P("model", None)  # vocab-parallel


@pytest.mark.slow
def test_tp_sp_step_trains(devices8):
    _, _, _, src, state, step, rng = build_sharded(
        ParallelConfig(data=2, seq=2, model=2), devices8)
    fixed = src.batch(0)
    first = last = None
    for i in range(8):
        state, metrics = step(state, fixed, rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first, (first, last)


def test_sharded_forward_matches_unsharded(devices8):
    """Forward logits under dp x sp x tp == single-device logits."""
    model = bert.tiny_bert_mlm(vocab_size=1024)
    ids = jax.random.randint(jax.random.key(3), (4, 32), 0, 1024)
    variables = model.init({"params": jax.random.key(0),
                            "dropout": jax.random.key(1)}, ids, train=False)
    ref = model.apply(variables, ids, train=False)

    cfg = bert_cfg(ParallelConfig(data=2, seq=2, model=2))
    mesh = make_mesh(cfg.parallel)
    from distributeddeeplearning_tpu.parallel import sharding as shardlib
    from distributeddeeplearning_tpu.parallel.mesh import use_mesh
    import flax.linen as nn

    with use_mesh(mesh), nn.logical_axis_rules(
            list(shardlib.logical_rules(cfg.parallel))):
        sharded = jax.jit(
            lambda v, x: model.apply(v, x, train=False))(variables, ids)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(sharded),
                               rtol=1e-4, atol=1e-4)


def test_hybrid_shapes_split():
    """Multi-slice DCN factoring: outer (pipeline/data) axes absorb slices."""
    from distributeddeeplearning_tpu.parallel.mesh import _hybrid_shapes

    # MESH_AXES = (pipeline, data, fsdp, expert, seq, model)
    per, dcn = _hybrid_shapes((1, 8, 1, 1, 2, 2), 2)
    assert dcn == (1, 2, 1, 1, 1, 1) and per == (1, 4, 1, 1, 2, 2)
    per, dcn = _hybrid_shapes((2, 8, 1, 1, 1, 4), 4)
    assert dcn == (2, 2, 1, 1, 1, 1) and per == (1, 4, 1, 1, 1, 4)
    import pytest
    with pytest.raises(ValueError, match="slices"):
        _hybrid_shapes((1, 3, 1, 1, 1, 4), 2)  # data=3 not divisible


@pytest.mark.core
def test_emulated_hybrid_mesh_layout(devices8):
    # emulate_slices=2 must arrange each global axis DCN-major/ICI-minor,
    # exactly like create_hybrid_device_mesh on a 2-slice pod: with slices
    # as contiguous device-id halves, data positions {0,1} live on slice 0
    # and {2,3} on slice 1, while the inner model axis stays intra-slice.
    mesh = make_mesh(ParallelConfig(data=4, model=2, emulate_slices=2))
    arr = mesh.devices.reshape(4, 2)  # (data, model); other axes size 1
    ids = np.vectorize(lambda d: d.id)(arr)
    slice_of = ids // 4  # first 4 device ids = emulated slice 0
    assert (slice_of[:2] == 0).all() and (slice_of[2:] == 1).all()
    # model-axis neighbours are always same-slice (tp stays on ICI)
    assert (slice_of[:, 0] == slice_of[:, 1]).all()


@pytest.mark.slow
def test_emulated_hybrid_mesh_trains(devices8):
    # A dp x tp step over the emulated 2-slice mesh compiles and runs.
    cfg = bert_cfg(ParallelConfig(data=4, model=2, emulate_slices=2))
    from distributeddeeplearning_tpu.train import loop

    summary = loop.run(cfg, total_steps=1)
    assert summary["final_step"] == 1
