"""Native C++ loader tests (csrc/ddl_loader.cc via data/native.py).

This is in-tree native code, so it gets real correctness coverage
(SURVEY.md §5.2): determinism, resume positioning, eval-protocol parity with
the tf.data pipeline, corrupt-input robustness, and shutdown cleanliness.
Skipped wholesale when the toolchain can't build the library.
"""

import os

import numpy as np
import pytest

from distributeddeeplearning_tpu.data import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native loader unavailable (no g++/libjpeg)")


@pytest.fixture(scope="module")
def jpeg_dataset(tmp_path_factory):
    """Tiny image-folder tree: 2 classes x 8 train JPEGs (+ val), distinct
    solid colors keyed by (class, index) so content checks are possible."""
    import tensorflow as tf

    root = tmp_path_factory.mktemp("imagenet_folder")
    rng = np.random.default_rng(0)
    for split, per_class in (("train", 8), ("val", 4)):
        for cls_i, wnid in enumerate(["n01440764", "n01443537"]):
            d = root / split / wnid
            d.mkdir(parents=True)
            for i in range(per_class):
                h, w = int(rng.integers(40, 90)), int(rng.integers(40, 90))
                val = np.full((h, w, 3),
                              [40 * (cls_i + 1), 10 + 5 * i, 200 - 6 * i],
                              np.uint8)
                data = tf.io.encode_jpeg(val, quality=95).numpy()
                (d / f"img{i}.JPEG").write_bytes(data)
    return str(root)


def _loader(root, **kw):
    from distributeddeeplearning_tpu.data import imagenet

    split = kw.pop("split", "train")
    paths, labels = imagenet.folder_index(root, split)
    defaults = dict(batch_size=4, image_size=32, train=split == "train",
                    seed=7)
    defaults.update(kw)
    return native.NativeImageLoader(paths, labels, **defaults)


def test_shapes_dtypes_and_labels(jpeg_dataset):
    ld = _loader(jpeg_dataset)
    batch = next(ld)
    assert batch["image"].shape == (4, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].shape == (4,)
    assert set(np.unique(batch["label"])).issubset({0, 1})
    ld.close()


def test_deterministic_stream(jpeg_dataset):
    a = _loader(jpeg_dataset)
    b = _loader(jpeg_dataset)
    for _ in range(5):
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["label"], bb["label"])
        np.testing.assert_array_equal(ba["image"], bb["image"])
    a.close(), b.close()


def test_resume_start_batch(jpeg_dataset):
    ref = _loader(jpeg_dataset)
    skipped = [next(ref) for _ in range(4)]
    resumed = _loader(jpeg_dataset, start_batch=2)
    for want in skipped[2:]:
        got = next(resumed)
        np.testing.assert_array_equal(got["label"], want["label"])
        np.testing.assert_array_equal(got["image"], want["image"])
    ref.close(), resumed.close()


def test_epochs_reshuffle(jpeg_dataset):
    """Per-epoch shuffles differ (train), but content stays in-distribution."""
    ld = _loader(jpeg_dataset)
    e0 = [next(ld)["label"] for _ in range(4)]   # 16 samples = epoch
    e1 = [next(ld)["label"] for _ in range(4)]
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))
    ld.close()


def test_eval_finite_and_ordered(jpeg_dataset):
    ld = _loader(jpeg_dataset, split="val")
    batches = list(ld)
    assert len(batches) == 2  # 8 val images / 4
    # Eval is unshuffled: folder order is class 0 then class 1.
    assert list(batches[0]["label"]) == [0, 0, 0, 0]
    assert list(batches[1]["label"]) == [1, 1, 1, 1]
    ld.close()


def test_eval_matches_tf_pipeline(jpeg_dataset):
    """Center-crop eval protocol: native decode+resize+normalize lands close
    to tf.data's (same crop fraction, both bilinear/half-pixel)."""
    import tensorflow as tf

    from distributeddeeplearning_tpu.data import imagenet

    paths, labels = imagenet.folder_index(jpeg_dataset, "val")
    ld = native.NativeImageLoader(paths, labels, batch_size=4, image_size=32,
                                  train=False, seed=0)
    got = next(ld)
    ld.close()

    tf_images = []
    for p in paths[:4]:
        img = imagenet._decode_and_center_crop(tf, tf.io.read_file(p), 32)
        img = imagenet._normalize(tf, tf.reshape(img, [32, 32, 3]), tf.float32)
        tf_images.append(img.numpy())
    ref = np.stack(tf_images)
    # JPEG decoders (IFAST DCT) + resize kernels differ slightly; images here
    # are near-solid so the tolerance can stay tight in normalized units.
    assert np.mean(np.abs(got["image"] - ref)) < 0.05


def test_corrupt_jpeg_yields_gray_not_crash(jpeg_dataset, tmp_path):
    d = tmp_path / "train" / "n00000000"
    d.mkdir(parents=True)
    for i in range(4):
        (d / f"bad{i}.JPEG").write_bytes(b"not a jpeg at all")
    from distributeddeeplearning_tpu.data import imagenet

    paths, labels = imagenet.folder_index(str(tmp_path), "train")
    ld = native.NativeImageLoader(paths, labels, batch_size=4, image_size=16,
                                  train=True, seed=1)
    batch = next(ld)
    assert np.isfinite(batch["image"]).all()
    ld.close()


def test_make_source_end_to_end(jpeg_dataset):
    """Through the config routing: folder layout + auto loader = native, and
    the train loop runs on it (tiny ResNet, 2 steps, 8-device mesh)."""
    from distributeddeeplearning_tpu.config import (
        DataConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="resnet18", global_batch_size=8, dtype="float32",
        log_every=10**9, parallel=ParallelConfig(data=2),
        data=DataConfig(synthetic=False, data_dir=jpeg_dataset,
                        image_size=32, num_classes=2))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])
