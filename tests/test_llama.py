"""Llama family: canonical param counts (eval_shape — no materialization),
RoPE identity/rotation properties, GQA shapes, causal masking, and
end-to-end training under GSPMD tp sharding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu import models
from distributeddeeplearning_tpu.models import llama


def _abstract_count(name):
    spec = models.model_spec(name)
    model = spec.build(dtype=jnp.float32)
    shapes = jax.eval_shape(
        lambda r: model.init({"params": r, "dropout": r},
                             jnp.zeros((1, 16), jnp.int32), train=False),
        jax.random.key(0))
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(shapes["params"]))


@pytest.mark.parametrize("name,count", [
    ("llama2_7b", 6_738_415_616),     # canonical Llama-2-7B
    ("tinyllama_1b", 1_100_048_384),  # canonical TinyLlama-1.1B
])
def test_param_counts(name, count):
    assert models.model_spec(name).param_count == count
    assert _abstract_count(name) == count


def test_rope_properties():
    x = jax.random.normal(jax.random.key(0), (1, 8, 2, 16), jnp.float32)
    out = llama.apply_rope(x, theta=10000.0)
    # Position 0 is the identity rotation; others preserve pair norms.
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(x[:, 0]),
                               rtol=1e-6)
    d = x.shape[-1]
    norm_in = np.sqrt(np.asarray(x[..., : d // 2]) ** 2
                      + np.asarray(x[..., d // 2:]) ** 2)
    norm_out = np.sqrt(np.asarray(out[..., : d // 2]) ** 2
                       + np.asarray(out[..., d // 2:]) ** 2)
    np.testing.assert_allclose(norm_out, norm_in, rtol=1e-5)


def test_forward_shape_gqa_and_causality():
    model = llama.tiny_llama(vocab_size=256)
    ids = jax.random.randint(jax.random.key(0), (2, 16), 0, 256)
    variables = model.init({"params": jax.random.key(1)}, ids, train=False)
    # GQA: k/v projections are num_kv_heads * head_dim wide.
    from flax.core import meta
    kshape = meta.unbox(
        variables["params"]["layer0"]["attention"]["k_proj"]["kernel"]).shape
    assert kshape == (64, 2 * 16)
    logits = model.apply(variables, ids, train=False)
    assert logits.shape == (2, 16, 256)
    assert bool(jnp.isfinite(logits).all())
    # Causality: changing a future token must not change past logits.
    ids2 = ids.at[:, 10].set((ids[:, 10] + 1) % 256)
    logits2 = model.apply(variables, ids2, train=False)
    np.testing.assert_allclose(np.asarray(logits[:, :10]),
                               np.asarray(logits2[:, :10]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(logits[:, 10:]),
                           np.asarray(logits2[:, 10:]))


@pytest.mark.usefixtures("devices8")
@pytest.mark.slow
def test_llama_trains_gspmd_tp():
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop

    cfg = TrainConfig(
        model="llama_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9,
        parallel=ParallelConfig(data=4, model=2),
        data=DataConfig(dataset="causal", seq_len=32, vocab_size=256),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3,
                                  schedule="constant", warmup_epochs=0.0,
                                  label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=3, eval_batches=2)
    assert summary["final_step"] == 3
    assert np.isfinite(summary["final_metrics"]["loss"])
    assert np.isfinite(summary["eval_loss"])
