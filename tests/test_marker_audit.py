"""Marker audit (tools/marker_audit.py): the tier-1 budget gate itself."""

import json
import subprocess
import sys

from tools.marker_audit import DEFAULT_THRESHOLD_S, find_violations


def _rec(nodeid, duration, slow=False):
    return {"nodeid": nodeid, "duration": duration, "slow": slow}


def test_fast_and_marked_tests_pass():
    records = [
        _rec("tests/test_a.py::fast", 0.5),
        _rec("tests/test_a.py::near_limit", DEFAULT_THRESHOLD_S),  # <=, not <
        _rec("tests/test_b.py::marked_slow", 300.0, slow=True),
    ]
    assert find_violations(records) == []


def test_unmarked_slow_test_flagged_slowest_first():
    records = [
        _rec("tests/test_a.py::bad", 75.0),
        _rec("tests/test_a.py::worse", 120.0),
        _rec("tests/test_a.py::ok", 1.0),
    ]
    got = find_violations(records)
    assert [r["nodeid"] for r in got] == ["tests/test_a.py::worse",
                                          "tests/test_a.py::bad"]


def test_custom_threshold_and_malformed_records_skipped():
    records = [
        _rec("tests/test_a.py::t", 10.0),
        {"nodeid": "tests/test_a.py::no_duration", "slow": False},
        {"duration": "not-a-number", "slow": False, "nodeid": "x"},
    ]
    assert find_violations(records, threshold_s=5.0) == [records[0]]
    assert find_violations(records) == []


def test_cli_exit_codes(tmp_path):
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps([_rec("t::fast", 1.0)]))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([_rec("t::unmarked", 200.0)]))
    cmd = [sys.executable, "tools/marker_audit.py"]
    assert subprocess.run(cmd + [str(ok)]).returncode == 0
    proc = subprocess.run(cmd + [str(bad)], capture_output=True, text=True)
    assert proc.returncode == 1
    assert "t::unmarked" in proc.stdout
    assert subprocess.run(cmd + [str(tmp_path / "missing.json")],
                          capture_output=True).returncode == 2
    # threshold override: 200s is fine under a 600s threshold
    assert subprocess.run(cmd + [str(bad), "600"]).returncode == 0


# --- perf_gate presence audit (ISSUE 6 satellite) ---------------------------

from tools.marker_audit import audit_perf_gate  # noqa: E402


def test_audit_perf_gate_clean_run():
    records = [_rec("t::fast", 1.0),
               {**_rec("t::gate", 5.0), "perf_gate": True},
               {**_rec("t::gate_zero2_overlap", 5.0), "perf_gate": True}]
    assert audit_perf_gate(records) == []


def test_audit_perf_gate_flags_missing_gate():
    problems = audit_perf_gate([_rec("t::fast", 1.0)])
    assert len(problems) == 1
    assert problems[0].startswith("no perf_gate")


def test_audit_perf_gate_flags_missing_zero2_workload():
    """Both gate workloads must run: the headline proxy alone no longer
    counts as full coverage once the sharded-schedule gate exists."""
    problems = audit_perf_gate([{**_rec("t::gate", 5.0), "perf_gate": True}])
    assert len(problems) == 1
    assert "zero2_overlap" in problems[0]


def test_audit_perf_gate_flags_slow_double_marking():
    """perf_gate + slow together silently removes the gate from tier-1
    (-m 'not slow') — the one static mistake that disarms it while every
    individual run still looks green."""
    records = [{**_rec("t::gate_zero2_overlap", 5.0, slow=True),
                "perf_gate": True}]
    problems = audit_perf_gate(records)
    assert len(problems) == 1
    assert "BOTH perf_gate and slow" in problems[0]
    assert "t::gate_zero2_overlap" in problems[0]


def test_cli_expect_perf_gate_flag(tmp_path):
    no_gate = tmp_path / "no_gate.json"
    no_gate.write_text(json.dumps([_rec("t::fast", 1.0)]))
    cmd = [sys.executable, "tools/marker_audit.py"]
    # Partial runs legitimately lack the gate: quiet by default...
    assert subprocess.run(cmd + [str(no_gate)]).returncode == 0
    # ...but the tier-1 chain opts in and must then fail loudly.
    proc = subprocess.run(cmd + [str(no_gate), "--expect-perf-gate"],
                          capture_output=True, text=True)
    assert proc.returncode == 1
    assert "no perf_gate-marked test ran" in proc.stdout
    # With only the headline gate present: quiet by default, but the
    # opt-in run fails — the zero2_overlap workload is part of coverage.
    headline_only = tmp_path / "headline_only.json"
    headline_only.write_text(json.dumps(
        [{**_rec("t::gate", 5.0), "perf_gate": True}]))
    assert subprocess.run(cmd + [str(headline_only)]).returncode == 0
    proc = subprocess.run(cmd + [str(headline_only), "--expect-perf-gate"],
                          capture_output=True, text=True)
    assert proc.returncode == 1
    assert "zero2_overlap" in proc.stdout
    # With both gate workloads present the opt-in run is clean.
    with_gate = tmp_path / "gate.json"
    with_gate.write_text(json.dumps(
        [{**_rec("t::gate", 5.0), "perf_gate": True},
         {**_rec("t::gate_zero2_overlap", 5.0), "perf_gate": True}]))
    assert subprocess.run(
        cmd + [str(with_gate), "--expect-perf-gate"]).returncode == 0
    # slow+perf_gate double-marking fails even WITHOUT the opt-in.
    double = tmp_path / "double.json"
    double.write_text(json.dumps(
        [{**_rec("t::gate", 5.0, slow=True), "perf_gate": True}]))
    proc = subprocess.run(cmd + [str(double)], capture_output=True,
                          text=True)
    assert proc.returncode == 1
    assert "BOTH perf_gate and slow" in proc.stdout


# --- elastic coverage audit (ISSUE 9 satellite) -----------------------------

from tools.marker_audit import audit_elastic  # noqa: E402


def test_audit_elastic_clean_run():
    records = [_rec("t::fast", 1.0),
               {**_rec("t::fast_cross_degree", 20.0), "elastic": True},
               {**_rec("t::test_survivor_selection_grid", 1.0),
                "elastic": True},
               {**_rec("t::test_cross_axis_soak", 300.0, slow=True),
                "elastic": True}]
    assert audit_elastic(records) == []


def test_audit_elastic_flags_no_coverage():
    problems = audit_elastic([_rec("t::fast", 1.0)])
    assert len(problems) == 2
    assert "no elastic-marked test ran" in problems[0]
    assert "survivor-selection" in problems[1]


def test_audit_elastic_flags_all_slow():
    """The soak is legitimately slow, but if EVERY elastic test is slow the
    cross-degree resume path silently leaves tier-1 (-m 'not slow')."""
    records = [{**_rec("t::test_cross_axis_soak", 300.0, slow=True),
                "elastic": True},
               {**_rec("t::test_survivor_selection_grid", 300.0, slow=True),
                "elastic": True}]
    problems = audit_elastic(records)
    assert len(problems) == 1
    assert "every elastic-marked test is also marked slow" in problems[0]


def test_audit_elastic_requires_survivor_grid():
    """Rendezvous extension: the topology-aware shrink's deterministic
    survivor choice must stay pinned in EVERY selection."""
    records = [{**_rec("t::fast_cross_degree", 20.0), "elastic": True}]
    problems = audit_elastic(records)
    assert len(problems) == 1
    assert "survivor-selection" in problems[0]


def test_audit_elastic_requires_cross_axis_when_slow_runs():
    """When the selection includes slow tests at all, the cross-axis soak
    (ZeRO stage + pipeline degree changing mid-run) must be among them."""
    base = [{**_rec("t::fast_cross_degree", 20.0), "elastic": True},
            {**_rec("t::test_survivor_selection_grid", 1.0),
             "elastic": True}]
    # Fast-only selection: the soak is legitimately absent.
    assert audit_elastic(base) == []
    slow_run = base + [_rec("t::unrelated_soak", 200.0, slow=True)]
    problems = audit_elastic(slow_run)
    assert len(problems) == 1
    assert "cross_axis" in problems[0]


def test_cli_expect_elastic_flag(tmp_path):
    cmd = [sys.executable, "tools/marker_audit.py"]
    no_elastic = tmp_path / "no_elastic.json"
    no_elastic.write_text(json.dumps([_rec("t::fast", 1.0)]))
    # Entirely opt-in: partial runs stay quiet...
    assert subprocess.run(cmd + [str(no_elastic)]).returncode == 0
    # ...the tier-1 chain opts in and fails loudly.
    proc = subprocess.run(cmd + [str(no_elastic), "--expect-elastic"],
                          capture_output=True, text=True)
    assert proc.returncode == 1
    assert "no elastic-marked test ran" in proc.stdout
    # Both flags compose on one invocation.
    full = tmp_path / "full.json"
    full.write_text(json.dumps(
        [{**_rec("t::gate", 5.0), "perf_gate": True},
         {**_rec("t::gate_zero2_overlap", 5.0), "perf_gate": True},
         {**_rec("t::fast_cross_degree", 20.0), "elastic": True},
         {**_rec("t::test_survivor_selection_grid", 1.0),
          "elastic": True}]))
    assert subprocess.run(
        cmd + [str(full), "--expect-perf-gate", "--expect-elastic"],
    ).returncode == 0


# --- large-batch recipe audit (ISSUE 20 satellite) --------------------------

from tools.marker_audit import audit_largebatch  # noqa: E402


def test_audit_largebatch_clean_run():
    records = [
        {**_rec("t::test_perf_gate_live_largebatch_bf16", 5.0),
         "perf_gate": True},
        _rec("t::test_loss_scale_overflow_skips_and_halves", 3.0),
        _rec("t::test_ramp_boundary_resume_bitwise", 8.0),
    ]
    assert audit_largebatch(records) == []


def test_audit_largebatch_flags_all_missing():
    problems = audit_largebatch([_rec("t::fast", 1.0)])
    assert len(problems) == 3
    assert any("largebatch_bf16" in p for p in problems)
    assert any("loss-scale" in p for p in problems)
    assert any("batch-ramp" in p for p in problems)


def test_audit_largebatch_gate_must_be_perf_gate_marked():
    """A largebatch-named test WITHOUT the perf_gate marker does not count
    as the gate — the workload check keys on the marker, not the name."""
    records = [
        _rec("t::test_largebatch_helper", 1.0),
        _rec("t::test_loss_scale_x", 1.0),
        _rec("t::test_ramp_y", 1.0),
    ]
    problems = audit_largebatch(records)
    assert len(problems) == 1
    assert "largebatch_bf16" in problems[0]


def test_cli_expect_largebatch_flag(tmp_path):
    cmd = [sys.executable, "tools/marker_audit.py"]
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps([_rec("t::fast", 1.0)]))
    # Opt-in: partial runs stay quiet...
    assert subprocess.run(cmd + [str(partial)]).returncode == 0
    # ...the tier-1 chain opts in and fails loudly.
    proc = subprocess.run(cmd + [str(partial), "--expect-largebatch"],
                          capture_output=True, text=True)
    assert proc.returncode == 1
    assert "largebatch_bf16" in proc.stdout
    full = tmp_path / "full.json"
    full.write_text(json.dumps(
        [{**_rec("t::test_perf_gate_live_largebatch_bf16", 5.0),
          "perf_gate": True},
         _rec("t::test_loss_scale_overflow", 2.0),
         _rec("t::test_ramp_boundary_resume", 2.0)]))
    assert subprocess.run(
        cmd + [str(full), "--expect-largebatch"]).returncode == 0
