"""Pallas flash-attention kernel correctness (ops/flash_attention.py).

Runs in Pallas interpret mode on CPU (the kernels' own fallback on non-TPU
backends), checking the fused forward and the custom-VJP backward against the
dense softmax(QK^T)V reference — the same oracle the ring-attention tests use.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import functools

from distributeddeeplearning_tpu.ops import flash_attention
from tests.attention_refs import dense_reference, random_qkv

random_qkv = functools.partial(random_qkv, s=64, h=2, d=16)


@pytest.mark.parametrize("s,block", [(64, 128), (64, 16), (128, 32)])
def test_forward_matches_dense(s, block):
    q, k, v = random_qkv(jax.random.key(0), s=s)
    out = flash_attention(q, k, v, block_q=block, block_k=block)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v)),
        rtol=1e-5, atol=1e-5)


def test_forward_respects_padding_mask():
    q, k, v = random_qkv(jax.random.key(1))
    b, s = q.shape[:2]
    mask = np.ones((b, s), bool)
    mask[:, -13:] = False
    mask[1, 3] = False
    mask = jnp.asarray(mask)
    out = flash_attention(q, k, v, mask, block_q=16, block_k=16)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v, mask)),
        rtol=1e-5, atol=1e-5)


def test_grads_match_dense():
    q, k, v = random_qkv(jax.random.key(2), s=32)
    mask = jnp.asarray(np.concatenate(
        [np.ones((2, 28), bool), np.zeros((2, 4), bool)], axis=1))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask, block_q=8, block_k=8)
        return (o * o).sum()

    def loss_dense(q, k, v):
        o = dense_reference(q, k, v, mask)
        return (o * o).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_non_power_of_two_seq_padded_not_degenerate():
    """S=197 (ViT-with-CLS shape; prime) used to resolve _block to 1 — a
    degenerate 197-step grid. flash_attention now pads S to a lane multiple
    (256) so blocks stay >= 128, and the padded rows/keys must not leak
    into the result or the gradients."""
    from distributeddeeplearning_tpu.ops.flash_attention import _block

    s = 197
    q, k, v = random_qkv(jax.random.key(4), s=s)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    assert out.shape == q.shape
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_reference(q, k, v)),
        rtol=1e-5, atol=1e-5)
    # causal too (the causal block-skip indexes blocks; padding must not
    # shift the diagonal).
    out_c = flash_attention(q, k, v, block_q=128, block_k=128, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_c),
        np.asarray(dense_reference(q, k, v, causal=True)),
        rtol=1e-5, atol=1e-5)

    gf = jax.grad(lambda *a: (flash_attention(
        *a, block_q=128, block_k=128) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: (dense_reference(*a) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
    # The invariant the pad exists to protect — and the loud warning any
    # future direct kernel caller sees instead of the silent cliff. A
    # modestly-smaller block (48 for target 64) stays silent: that is a
    # working configuration, not a cliff.
    assert _block(256, 128) == 128
    with pytest.warns(UserWarning, match="degenerated"):
        assert _block(197, 128) == 1
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert _block(96, 64) == 48


def test_bfloat16_forward():
    q, k, v = random_qkv(jax.random.key(3), dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    ref = dense_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.slow
def test_bert_flash_end_to_end_sharded():
    """Tiny BERT trains with flash attention on a dp x tp mesh through the
    GSPMD path — the kernel runs per-shard under shard_map."""
    from distributeddeeplearning_tpu.config import (
        DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
    from distributeddeeplearning_tpu.train import loop
    from distributeddeeplearning_tpu.utils.logging import MetricLogger

    cfg = TrainConfig(
        model="bert_tiny", global_batch_size=8, dtype="float32",
        log_every=10**9, attention_impl="flash",
        parallel=ParallelConfig(data=2, model=2),
        data=DataConfig(dataset="mlm", seq_len=32, vocab_size=512),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                                  schedule="constant", label_smoothing=0.0))
    summary = loop.run(cfg, total_steps=2, logger=MetricLogger(enabled=False))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_metrics"]["loss"])


def test_bert_flash_matches_dense_forward():
    """Full-model: BertMLM logits with flash == dense impl (single device)."""
    from distributeddeeplearning_tpu.models import bert

    ids = jax.random.randint(jax.random.key(4), (2, 32), 0, 256)
    mask = jnp.ones((2, 32), jnp.int32).at[:, -5:].set(0)
    dense = bert.tiny_bert_mlm(vocab_size=256)
    flash = bert.tiny_bert_mlm(vocab_size=256, attention_impl="flash")
    variables = dense.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(0)},
        ids, train=False)
    out_d = dense.apply(variables, ids, attention_mask=mask, train=False)
    out_f = flash.apply(variables, ids, attention_mask=mask, train=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)
