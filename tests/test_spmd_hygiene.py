"""SPMD partitioner hygiene (VERDICT r4 Missing #5 / Next #3).

The dp4 x fsdp2 dryrun used to compile with XLA's "Involuntary full
rematerialization" warning: the embedding-table gradient scatter could not
bridge batch-sharded updates and an embed-over-fsdp output, so the
partitioner replicated the whole update activation. ops/embedding.py's
``embedding_lookup`` keeps the scatter on the supported
partial-scatter+allreduce path; the subprocess test here greps a real
compile's stderr so the bad path cannot silently return."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.ops.embedding import embedding_lookup


@pytest.mark.core
def test_embedding_lookup_matches_plain_gather():
    table = jax.random.normal(jax.random.key(0), (32, 8))
    ids = jax.random.randint(jax.random.key(1), (4, 6), 0, 32)

    def loss_new(t):
        return (embedding_lookup(t, ids) ** 2).sum()

    def loss_ref(t):
        return (t[ids] ** 2).sum()

    np.testing.assert_allclose(loss_new(table), loss_ref(table), rtol=1e-6)
    np.testing.assert_allclose(jax.grad(loss_new)(table),
                               jax.grad(loss_ref)(table), rtol=1e-6)


@pytest.mark.core
def test_embedding_lookup_bf16_table_grad_dtype():
    # The bwd casts back to the table dtype after the f32 scatter.
    table = jax.random.normal(jax.random.key(0), (16, 8), jnp.bfloat16)
    ids = jnp.array([[0, 3], [5, 0]])
    g = jax.grad(lambda t: embedding_lookup(t, ids).astype(jnp.float32)
                 .sum())(table)
    assert g.dtype == jnp.bfloat16


_REPRO = """
from distributeddeeplearning_tpu.hostmesh import pin_virtual_cpu_mesh
pin_virtual_cpu_mesh(8)
import json
from distributeddeeplearning_tpu.config import (
    DataConfig, OptimizerConfig, ParallelConfig, TrainConfig)
from distributeddeeplearning_tpu.train import loop
cfg = TrainConfig(
    model="bert_tiny", global_batch_size=16,
    dtype="float32", log_every=10**9,
    parallel=ParallelConfig(data=4, fsdp=2),
    data=DataConfig(dataset="mlm", seq_len=16, vocab_size=512),
    optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4,
                              schedule="linear", label_smoothing=0.0))
print(json.dumps(loop.run(cfg, total_steps=1)))
"""


@pytest.mark.slow
def test_fsdp_compile_has_no_involuntary_rematerialization():
    """Compile+run the exact dp x fsdp config that used to warn, in a
    subprocess (XLA warnings go to the process stderr, not Python's), and
    assert the partitioner never fell back to replicate-then-repartition."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _REPRO],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["final_step"] == 1
    assert "Involuntary full rematerialization" not in proc.stderr, (
        "the SPMD replicate-the-updates path is back:\n"
        + proc.stderr[-3000:])
