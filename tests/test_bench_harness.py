"""bench.py control flow with the measurement stubbed out — the suite
loop, sweep emit-only-if-faster rule, per-row error records, and the
last-good cache are driver-facing contracts that must not depend on a
live chip to be tested."""

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "LAST_GOOD_PATH",
                        str(tmp_path / "last_bench.json"))
    return mod


def _args(bench, extra=()):
    # Parse exactly as main() would, then return the namespace.
    import argparse  # noqa: F401
    argv = ["--run-child", *extra]
    # Reuse main's parser by intercepting _child.
    ns = {}

    def fake_child(a):
        ns["args"] = a
        return 0

    bench._child, orig = fake_child, bench._child
    try:
        bench.main(argv)
    finally:
        bench._child = orig
    return ns["args"]


class FakeJax:
    """Module-level jax stub for _child tests (the real jax import would
    bind the axon platform); one copy so new jax attribute accesses in
    _child get added here exactly once."""

    @staticmethod
    def device_count():
        return 1

    @staticmethod
    def devices():
        class D:
            platform = "cpu"
        return [D()]

    class config:
        @staticmethod
        def update(*a):
            pass


def _run_child_with_fake_jax(bench, args):
    sys.modules.setdefault("jax", FakeJax)
    try:
        return bench._child(args)
    finally:
        if sys.modules.get("jax") is FakeJax:
            del sys.modules["jax"]


def test_suite_rows_reset_flags_and_filter(bench, monkeypatch, capsys):
    seen = []

    def fake_measure(row, emit_quick=True, emit_final=True, deadline=None):
        seen.append((row.model, row.batch_size, row.attention_impl,
                     row.remat))
        if row.model == "densenet121":
            raise RuntimeError("boom")  # must yield an error record
        print(json.dumps({"metric": f"{row.model}_x", "value": 1.0}),
              flush=True)
        return 1.0

    monkeypatch.setattr(bench, "_child_measure", fake_measure)
    monkeypatch.setattr(bench, "jax", None, raising=False)
    args = _args(bench, ["--suite", "--fused-bn", "--remat",
                         "--suite-models",
                         "resnet50,densenet121,bert_base"])

    rc = _run_child_with_fake_jax(bench, args)
    assert rc == 0
    models = [s[0] for s in seen]
    # SUITE's value-per-minute order: resnet50 + the two allreduce A/B
    # rows + the three zero-ladder rows (all resnet50), bert flash,
    # (gpt2 filtered out), bert dense, (resnet152 filtered),
    # densenet121, (vit filtered), bert 2048, then the two large-batch
    # precision A/B rows (resnet50 again; pp rows filtered).
    assert models == ["resnet50"] * 6 + ["bert_base", "bert_base",
                                         "densenet121", "bert_base",
                                         "resnet50", "resnet50"]
    # Suite rows must NOT inherit headline flags; row overrides apply.
    assert all(s[3] is False for s in seen[:3])  # remat reset
    out = [json.loads(line) for line in
           capsys.readouterr().out.strip().splitlines()]
    errors = [r for r in out if r.get("value") is None]
    assert len(errors) == 1 and "boom" in errors[0]["error"]


def test_sweep_emits_only_if_faster(bench, monkeypatch, capsys):
    rates = {512: 100.0, 256: 90.0, 128: 120.0}

    def fake_measure(row, emit_quick=True, emit_final=True):
        rate = rates[row.batch_size]
        if emit_final:
            bench._emit_metric(row, rate, protocol=f"b{row.batch_size}")
        return rate

    monkeypatch.setattr(bench, "_child_measure", fake_measure)
    args = _args(bench, ["--model", "resnet50", "--sweep", "256,128"])

    _run_child_with_fake_jax(bench, args)
    out = [json.loads(line) for line in
           capsys.readouterr().out.strip().splitlines()]
    # Primary (100) emitted; b256 (90) silent; b128 (120) emitted.
    values = [r["value"] for r in out]
    assert values == [100.0, 120.0]
    assert "sweep" in out[-1]["protocol"]


def test_fused_block_alternate_emits_only_if_faster(bench, monkeypatch,
                                                    capsys):
    """The headline run measures the conv-epilogue-fusion variant at the
    winning batch and emits it only on a strict win (same last-line-wins
    discipline as the batch sweep)."""
    for fused_rate, expect_emitted in ((130.0, True), (80.0, False)):
        rates = {(512, False): 100.0, (256, False): 90.0,
                 (512, True): fused_rate}

        def fake_measure(row, emit_quick=True, emit_final=True):
            rate = rates[(row.batch_size, row.fused_block)]
            if emit_final:
                bench._emit_metric(row, rate, protocol=f"b{row.batch_size}")
            return rate

        monkeypatch.setattr(bench, "_child_measure", fake_measure)
        args = _args(bench, ["--model", "resnet50"])  # sweep stays "auto"

        _run_child_with_fake_jax(bench, args)
        out = [json.loads(line) for line in
               capsys.readouterr().out.strip().splitlines()]
        fused = [r for r in out if "fusedblock" in (r.get("protocol") or "")]
        assert bool(fused) == expect_emitted, (fused_rate, out)
        if expect_emitted:
            assert out[-1]["value"] == fused_rate  # last line wins


def test_fused_conv3_alternate_chains_after_v1(bench, monkeypatch, capsys):
    """The headline run tries v1 (fused_block) then v2 (fused_conv3);
    each emits only on a strict win over the running best, and a v2
    failure (e.g. Mosaic rejection on-chip) costs one caught exception,
    never the headline or the v1 result."""
    def run(rates, conv3_raises=False):
        def fake_measure(row, emit_quick=True, emit_final=True,
                         deadline=None):
            key = ("conv3" if getattr(row, "fused_conv3", False)
                   else "v1" if row.fused_block
                   else row.batch_size)
            if key == "conv3" and conv3_raises:
                raise RuntimeError("mosaic says no")
            rate = rates[key]
            if emit_final:
                bench._emit_metric(row, rate, protocol=f"b{row.batch_size}")
            return rate

        monkeypatch.setattr(bench, "_child_measure", fake_measure)
        args = _args(bench, ["--model", "resnet50"])  # sweep auto
        _run_child_with_fake_jax(bench, args)
        return [json.loads(line) for line in
                capsys.readouterr().out.strip().splitlines()]

    # v2 beats v1 beats baseline: three lines, last one is v2.
    out = run({512: 100.0, 256: 90.0, "v1": 110.0, "conv3": 120.0})
    assert [r["value"] for r in out] == [100.0, 110.0, 120.0]
    assert "fusedconv3" in out[-1]["protocol"]
    # v2 slower than v1: v1's line stands as the last.
    out = run({512: 100.0, 256: 90.0, "v1": 110.0, "conv3": 105.0})
    assert [r["value"] for r in out] == [100.0, 110.0]
    # v2 raises: v1's win survives, no error record pollutes stdout.
    out = run({512: 100.0, 256: 90.0, "v1": 110.0, "conv3": 0.0},
              conv3_raises=True)
    assert [r["value"] for r in out] == [100.0, 110.0]


def test_preflight_kills_hung_backend_fast(bench):
    # A child that never prints the backend-up heartbeat models a down
    # tunnel (jax.devices() hangs). The attempt must die at the preflight
    # deadline, not the full timeout.
    import time as _time
    t0 = _time.monotonic()
    n, _err, rc = bench._run_attempt(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        timeout=60, relay_errors=False, preflight=2)
    assert n == 0
    assert isinstance(rc, str) and rc.startswith("preflight")
    assert _time.monotonic() - t0 < 20


def test_preflight_disarmed_by_backend_heartbeat(bench):
    # Once the heartbeat lands, the preflight deadline must NOT fire; the
    # ordinary attempt timeout governs from then on.
    # Emit the real heartbeat constant so this test fails if the child's
    # note and the parent's matcher ever drift apart.
    child = (f"import sys, time; "
             f"print('# bench: {bench.BACKEND_UP_HEARTBEAT} 1 x tpu', "
             f"file=sys.stderr, flush=True); time.sleep(60)")
    n, _err, rc = bench._run_attempt(
        [sys.executable, "-c", child],
        timeout=6, relay_errors=False, preflight=2)
    assert n == 0
    assert isinstance(rc, str) and rc.startswith("timeout")


def test_preflight_failure_skips_remaining_attempts(bench, monkeypatch,
                                                    capsys):
    calls = []

    def fake_attempt(cmd, timeout, *, relay_errors, record_good=True,
                     preflight=0):
        calls.append(preflight)
        return 0, "", "preflight 75s: backend never came up"

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    rc = bench.main(["--attempts", "3"])
    assert rc == 0
    assert len(calls) == 1  # no retries against a hung backend
    out = [json.loads(line) for line in
           capsys.readouterr().out.strip().splitlines()]
    assert out[-1]["value"] is None
    assert "preflight" in out[-1]["error"]


def test_error_record_carries_stale_age(bench, capsys):
    import time as _time
    measured = _time.strftime("%Y-%m-%d %H:%M:%S",
                              _time.localtime(_time.time() - 3600))
    bench._record_last_good(json.dumps({
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": 2000.0, "measured_at": measured}))
    args = _args(bench, ["--model", "resnet50"])
    bench._emit_error(args, "tunnel down")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["last_measured_on_live_chip"]["value"] == 2000.0
    # Top-level age: ~1h, with slack for slow test boxes.
    assert 3500 <= rec["stale_age_s"] <= 3800


def test_suite_budget_skips_and_admits_rows(bench, monkeypatch, capsys):
    """VERDICT r4 Weak #5 contract: a row whose estimate doesn't fit the
    remaining suite budget is skipped WITH a stderr note, and cheaper rows
    behind it are still admitted (a dying window yields the best prefix,
    not a silent truncation)."""
    seen = []

    def fake_measure(row, emit_quick=True, emit_final=True, deadline=None):
        seen.append((row.model, deadline))
        print(json.dumps({"metric": f"{row.model}_x", "value": 1.0}),
              flush=True)
        return 1.0

    monkeypatch.setattr(bench, "_child_measure", fake_measure)
    monkeypatch.setattr(bench, "SUITE", (
        ("resnet50", "resnet50", {}, 10_000),  # can't fit: skip + note
        ("gpt2_1024", "gpt2_small",
         {"batch_size": 16, "seq_len": 1024}, 1),  # fits
    ))
    args = _args(bench, ["--suite", "--suite-budget", "5"])

    rc = _run_child_with_fake_jax(bench, args)
    assert rc == 0
    assert [s[0] for s in seen] == ["gpt2_small"]
    # The admitted row carries a concrete per-row deadline.
    assert seen[0][1] is not None
    captured = capsys.readouterr()
    assert "SKIPPED on budget" in captured.err
    assert "resnet50" in captured.err
    out = [json.loads(line) for line in captured.out.strip().splitlines()]
    assert [r["metric"] for r in out] == ["gpt2_small_x"]


def test_suite_rows_selects_exact_rows(bench, monkeypatch, capsys):
    """--suite-rows picks SUITE entries by NAME — the only way to select
    one bert_base protocol variant (tools/chip_window.sh splits the suite
    across window steps with it)."""
    seen = []

    def fake_measure(row, emit_quick=True, emit_final=True, deadline=None):
        seen.append((row.model, row.attention_impl, row.seq_len))
        return 1.0

    monkeypatch.setattr(bench, "_child_measure", fake_measure)
    args = _args(bench, ["--suite", "--suite-rows",
                         "bert512_flash,bert2048_flash"])
    _run_child_with_fake_jax(bench, args)
    assert seen == [("bert_base", "flash", 512),
                    ("bert_base", "flash", 2048)]


def test_suite_order_contract_for_chip_window(bench):
    """tools/chip_window.sh selects rows by these NAMES (suite_top /
    suite_rest / allreduce_ab steps); renaming a row breaks the script, so
    this pin and the script must move in lockstep. Order still matters for
    budget gating (value-per-minute prefix), so it is pinned too."""
    names = [n for n, _m, _o, _e in bench.SUITE]
    assert names == [
        "resnet50", "ar_fused", "ar_perleaf", "zero1", "zero2", "zero3",
        "bert512_flash", "gpt2_1024", "bert512", "resnet152",
        "densenet121", "vit_b16", "bert2048_flash",
        "largebatch_fp32", "largebatch_bf16",
        "pp_gpipe", "pp_1f1b",
    ]
    key = {n: (m, o.get("attention_impl"), o.get("seq_len"),
               o.get("allreduce_bucket_mb"))
           for n, m, o, _e in bench.SUITE}
    assert key["resnet50"] == ("resnet50", None, None, None)
    assert key["ar_fused"] == ("resnet50", None, None, 4.0)
    assert key["ar_perleaf"] == ("resnet50", None, None, 0.0)
    assert key["zero1"] == ("resnet50", None, None, 4.0)
    # The zero-ladder rows pair with ar_fused: identical protocol except
    # the sharding stage (chip_window.sh's zero_ladder step selects all
    # four by name for the A/B).
    for stage in ("zero1", "zero2", "zero3"):
        assert key[stage] == ("resnet50", None, None, 4.0)
        zrow = next(o for n, _m, o, _e in bench.SUITE if n == stage)
        assert zrow["optimizer_sharding"] == stage
    assert key["bert512_flash"] == ("bert_base", "flash", 512, None)
    assert key["bert2048_flash"] == ("bert_base", "flash", 2048, None)
    # The pipeline A/B rows pair with each other: identical geometry, the
    # schedule is the only delta (chip_window.sh's pipeline_ab step
    # selects both by name).
    for name, sched, v in (("pp_gpipe", "gpipe", 1), ("pp_1f1b", "1f1b", 2)):
        row = next(o for n, _m, o, _e in bench.SUITE if n == name)
        assert key[name] == ("bert_tiny_pp4", None, 128, None)
        assert row["pp"] == 2
        assert row["pipeline_schedule"] == sched
        assert row["pipeline_virtual_stages"] == v


def test_suite_rows_validation(bench, capsys):
    with pytest.raises(SystemExit):
        # 99 is out of range even as a deprecated positional index
        bench.main(["--suite", "--suite-rows", "0,99"])
    with pytest.raises(SystemExit):
        bench.main(["--suite", "--suite-rows", "resnet50,nope"])
    with pytest.raises(SystemExit):
        bench.main(["--suite", "--suite-rows", "bert512",
                    "--suite-models", "resnet50"])


def test_suite_rows_index_alias_deprecated(bench, capsys):
    """Positional indices predate named rows: they still resolve (old
    drivers keep working) but to the NAME at that suite position, with a
    stderr deprecation note; a name+its-index pair dedupes to one row."""
    names = [n for n, _m, _o, _e in bench.SUITE]
    args = _args(bench, ["--suite", "--suite-rows", f"4,0,{names[0]}"])
    assert args.suite_rows == f"{names[4]},{names[0]}"
    err = capsys.readouterr().err
    assert "deprecated" in err and names[4] in err


def test_suite_budget_zero_disables_gating(bench, monkeypatch, capsys):
    seen = []

    def fake_measure(row, emit_quick=True, emit_final=True, deadline=None):
        seen.append((row.model, deadline))
        return 1.0

    monkeypatch.setattr(bench, "_child_measure", fake_measure)
    monkeypatch.setattr(bench, "SUITE",
                        (("resnet50", "resnet50", {}, 10_000),))
    args = _args(bench, ["--suite", "--suite-budget", "0"])
    _run_child_with_fake_jax(bench, args)
    assert seen == [("resnet50", None)]


def test_parent_derives_child_suite_budget(bench):
    """The parent forwards --suite-budget = --budget minus the init margin
    unless explicitly overridden, so the child's row gating always engages
    on driver-style invocations (bench.py --suite --budget N)."""
    argv = ["--suite", "--budget", "520"]
    derived = {}

    def fake_attempt(cmd, timeout, *, relay_errors, record_good=True,
                     preflight=0):
        derived["cmd"] = list(cmd)
        return 1, "", 0

    orig = bench._run_attempt
    bench._run_attempt = fake_attempt
    try:
        bench.main(argv)
    finally:
        bench._run_attempt = orig
    i = derived["cmd"].index("--suite-budget")
    # Derived per-attempt from the REMAINING budget (520 minus elapsed,
    # minus the 120s relay margin) — a second attempt gets a smaller one.
    assert 395 <= int(derived["cmd"][i + 1]) <= 400


def test_metric_line_carries_tflops_and_fused_block_field(bench, capsys):
    """MFU reporting contract (VERDICT r4 Next #5) + the structured
    fused-block marker (ADVICE r4): the emitted record computes
    tflops_per_sec from the analytic model FLOPs, and mfu_pct appears
    exactly when the detected chip has a known bf16 peak."""
    from distributeddeeplearning_tpu.models import flops as flopslib

    args = _args(bench, ["--model", "resnet50"])
    args.fused_block = True
    bench._emit_metric(args, 2366.0, protocol="w11+30 b512")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    per_ex = flopslib.train_flops_per_example("resnet50")
    assert rec["tflops_per_sec"] == round(2366.0 * per_ex / 1e12, 2)
    assert rec["fused_block"] is True
    # This test runs on CPU (unknown peak): mfu_pct must be absent, not
    # wrong. On a detected TPU it must match the peak-table arithmetic.
    import jax
    peak = flopslib.bf16_peak_flops(jax.devices()[0].device_kind)
    if peak:
        assert rec["mfu_pct"] == round(
            100.0 * 2366.0 * per_ex / peak, 1)
    else:
        assert "mfu_pct" not in rec


def test_unknown_model_omits_mfu_fields(bench, capsys):
    args = _args(bench, ["--model", "resnet50"])
    args.model = "bert_tiny"  # no flops entry by design
    args.seq_len = 64
    bench._emit_metric(args, 10.0, protocol="x")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "tflops_per_sec" not in rec and "mfu_pct" not in rec
    assert "fused_block" not in rec  # marker only when the flag is set


def test_perleaf_allreduce_gets_its_own_metric_name(bench):
    """The A/B's reference schedule (bucket_mb=0) must never evict the
    fused row's last-good entry: metric-name separation + protocol
    markers (docs/fused_allreduce.md A/B protocol)."""
    fused = _args(bench, ["--model", "resnet50",
                          "--allreduce-bucket-mb", "4"])
    perleaf = _args(bench, ["--model", "resnet50",
                            "--allreduce-bucket-mb", "0"])
    default = _args(bench, ["--model", "resnet50"])
    m_fused, _ = bench._metric_name_unit(fused)
    m_perleaf, _ = bench._metric_name_unit(perleaf)
    m_default, _ = bench._metric_name_unit(default)
    assert m_fused == m_default  # fused IS the production metric
    assert "_perleaf_ar" in m_perleaf and m_perleaf != m_fused
    assert "ar4mb" in bench._protocol_suffix(fused)
    assert "perleaf-ar" in bench._protocol_suffix(perleaf)
    assert "ar" not in bench._protocol_suffix(default)
    bf16 = _args(bench, ["--model", "resnet50", "--allreduce-dtype",
                         "bfloat16"])
    assert "ar-bf16" in bench._protocol_suffix(bf16)


def test_allreduce_flag_validation_and_forwarding(bench):
    with pytest.raises(SystemExit):
        bench.main(["--allreduce-bucket-mb", "-1"])
    # The parent must forward the protocol flags to the measuring child,
    # or the A/B rows would silently measure the default schedule.
    derived = {}

    def fake_attempt(cmd, timeout, *, relay_errors, record_good=True,
                     preflight=0):
        derived["cmd"] = list(cmd)
        return 1, "", 0

    orig = bench._run_attempt
    bench._run_attempt = fake_attempt
    try:
        bench.main(["--allreduce-bucket-mb", "0",
                    "--allreduce-dtype", "bfloat16"])
    finally:
        bench._run_attempt = orig
    cmd = derived["cmd"]
    i = cmd.index("--allreduce-bucket-mb")
    assert cmd[i + 1] == "0.0"
    assert cmd[cmd.index("--allreduce-dtype") + 1] == "bfloat16"


def test_last_good_cache_keyed_per_metric(bench, tmp_path):
    bench._record_last_good(json.dumps({"metric": "a", "value": 1}))
    bench._record_last_good(json.dumps({"metric": "b", "value": 2}))
    bench._record_last_good(json.dumps({"metric": "a", "value": 3}))
    # Written through the sidecars envelope: metrics table nested, plus
    # schema/written_at stamps.
    with open(bench.LAST_GOOD_PATH) as f:
        side = json.load(f)
    table = side["metrics"]
    assert table["a"]["value"] == 3 and table["b"]["value"] == 2
    assert "written_at" in side and "schema" in side


# --- provenance schema on bench records (ISSUE 6 tentpole) ------------------

def test_metric_record_is_fresh_with_attempt_and_pct_of_peak(
        bench, monkeypatch, capsys):
    """Every live metric line carries the full perf_report schema: fresh
    provenance (the ONLY path allowed to claim it), the attempt that
    produced it, backend identity, git rev, and an always-present
    pct_of_peak column (null on CPU where the peak is unknown)."""
    from distributeddeeplearning_tpu.observability import perf_report

    monkeypatch.setenv("DDL_BENCH_ATTEMPT", "3")
    args = _args(bench, ["--model", "resnet50"])
    bench._emit_metric(args, 2366.0, protocol="w11+30 b512")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["provenance"] == "fresh"
    assert rec["schema_version"] == perf_report.SCHEMA_VERSION
    assert rec["attempt"] == 3
    assert rec["backend"]["platform"] == "cpu"
    assert rec["backend"]["device_count"] == 8
    assert len(rec["git_rev"]) == 12
    # pct_of_peak exists on EVERY row; honest null on an unknown peak.
    assert "pct_of_peak" in rec and rec["pct_of_peak"] is None
    assert perf_report.validate(rec) == []


def test_error_record_carries_attempt_history_no_backend(bench, capsys):
    """The parent's error record: provenance=error, the full retry
    history, and NO backend block — the parent never initialized jax and
    must not probe the very tunnel whose death it is reporting."""
    from distributeddeeplearning_tpu.observability import perf_report

    args = _args(bench, ["--model", "resnet50"])
    bench._emit_error(args, "tunnel down", attempts=[
        {"attempt": 1, "rc": "timeout 480s", "relayed_lines": 0},
        {"attempt": 2, "rc": "preflight 75s", "relayed_lines": 0}])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["provenance"] == "error" and rec["value"] is None
    assert [a["attempt"] for a in rec["attempts"]] == [1, 2]
    assert "backend" not in rec
    assert perf_report.validate(rec) == []


def test_max_stale_age_demotes_old_cache_to_expired(bench, capsys):
    """--max-stale-age is the expiry knob: a cached number older than the
    cap is demoted to provenance=expired, stripped of vs_baseline, and
    announced LOUDLY on stderr; inside the cap it stays stale and keeps
    scoring."""
    import time as _time
    measured = _time.strftime("%Y-%m-%d %H:%M:%S",
                              _time.localtime(_time.time() - 7200))
    bench._record_last_good(json.dumps({
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": 2000.0, "vs_baseline": 1.38, "measured_at": measured}))

    # 2h-old cache under a 1h cap: expired.
    args = _args(bench, ["--model", "resnet50", "--max-stale-age", "3600"])
    bench._emit_error(args, "tunnel down")
    captured = capsys.readouterr()
    rec = json.loads(captured.out.strip().splitlines()[-1])
    prior = rec["last_measured_on_live_chip"]
    assert prior["provenance"] == "expired"
    assert "vs_baseline" not in prior
    assert "WARNING" in captured.err and "expired" in captured.err
    assert "history, not a current result" in captured.err

    # Same cache under the 24h default: stale, vs_baseline kept, quiet.
    args = _args(bench, ["--model", "resnet50"])
    assert args.max_stale_age == 24 * 3600.0
    bench._emit_error(args, "tunnel down")
    captured = capsys.readouterr()
    rec = json.loads(captured.out.strip().splitlines()[-1])
    prior = rec["last_measured_on_live_chip"]
    assert prior["provenance"] == "stale"
    assert prior["vs_baseline"] == 1.38
    assert "WARNING" not in captured.err


def test_main_retry_history_lands_in_error_record(bench, monkeypatch,
                                                  capsys):
    """End-to-end through main(): each failed attempt appends to the
    history the final error record ships, and the child env carries the
    attempt number so fresh records can stamp it."""
    seen_env = []

    def fake_attempt(cmd, timeout, *, relay_errors, record_good=True,
                     preflight=0):
        seen_env.append(os.environ.get("DDL_BENCH_ATTEMPT"))
        return 0, "backend never came up", 1

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    rc = bench.main(["--attempts", "2"])
    assert rc == 0
    assert seen_env == ["1", "2"]
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["provenance"] == "error"
    assert [a["attempt"] for a in rec["attempts"]] == [1, 2]
    assert all(a["rc"] == "1" for a in rec["attempts"])
