"""bench.py control flow with the measurement stubbed out — the suite
loop, sweep emit-only-if-faster rule, per-row error records, and the
last-good cache are driver-facing contracts that must not depend on a
live chip to be tested."""

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "LAST_GOOD_PATH",
                        str(tmp_path / "last_bench.json"))
    return mod


def _args(bench, extra=()):
    # Parse exactly as main() would, then return the namespace.
    import argparse  # noqa: F401
    argv = ["--run-child", *extra]
    # Reuse main's parser by intercepting _child.
    ns = {}

    def fake_child(a):
        ns["args"] = a
        return 0

    bench._child, orig = fake_child, bench._child
    try:
        bench.main(argv)
    finally:
        bench._child = orig
    return ns["args"]


class FakeJax:
    """Module-level jax stub for _child tests (the real jax import would
    bind the axon platform); one copy so new jax attribute accesses in
    _child get added here exactly once."""

    @staticmethod
    def device_count():
        return 1

    @staticmethod
    def devices():
        class D:
            platform = "cpu"
        return [D()]

    class config:
        @staticmethod
        def update(*a):
            pass


def _run_child_with_fake_jax(bench, args):
    sys.modules.setdefault("jax", FakeJax)
    try:
        return bench._child(args)
    finally:
        if sys.modules.get("jax") is FakeJax:
            del sys.modules["jax"]


def test_suite_rows_reset_flags_and_filter(bench, monkeypatch, capsys):
    seen = []

    def fake_measure(row, emit_quick=True, emit_final=True):
        seen.append((row.model, row.batch_size, row.attention_impl,
                     row.remat))
        if row.model == "densenet121":
            raise RuntimeError("boom")  # must yield an error record
        print(json.dumps({"metric": f"{row.model}_x", "value": 1.0}),
              flush=True)
        return 1.0

    monkeypatch.setattr(bench, "_child_measure", fake_measure)
    monkeypatch.setattr(bench, "jax", None, raising=False)
    args = _args(bench, ["--suite", "--fused-bn", "--remat",
                         "--suite-models",
                         "resnet50,densenet121,bert_base"])

    rc = _run_child_with_fake_jax(bench, args)
    assert rc == 0
    models = [s[0] for s in seen]
    assert models == ["resnet50", "densenet121", "bert_base", "bert_base",
                      "bert_base"]
    # Suite rows must NOT inherit headline flags; row overrides apply.
    assert all(s[3] is False for s in seen[:2])  # remat reset
    out = [json.loads(line) for line in
           capsys.readouterr().out.strip().splitlines()]
    errors = [r for r in out if r.get("value") is None]
    assert len(errors) == 1 and "boom" in errors[0]["error"]


def test_sweep_emits_only_if_faster(bench, monkeypatch, capsys):
    rates = {512: 100.0, 256: 90.0, 128: 120.0}

    def fake_measure(row, emit_quick=True, emit_final=True):
        rate = rates[row.batch_size]
        if emit_final:
            bench._emit_metric(row, rate, protocol=f"b{row.batch_size}")
        return rate

    monkeypatch.setattr(bench, "_child_measure", fake_measure)
    args = _args(bench, ["--model", "resnet50", "--sweep", "256,128"])

    _run_child_with_fake_jax(bench, args)
    out = [json.loads(line) for line in
           capsys.readouterr().out.strip().splitlines()]
    # Primary (100) emitted; b256 (90) silent; b128 (120) emitted.
    values = [r["value"] for r in out]
    assert values == [100.0, 120.0]
    assert "sweep" in out[-1]["protocol"]


def test_fused_block_alternate_emits_only_if_faster(bench, monkeypatch,
                                                    capsys):
    """The headline run measures the conv-epilogue-fusion variant at the
    winning batch and emits it only on a strict win (same last-line-wins
    discipline as the batch sweep)."""
    for fused_rate, expect_emitted in ((130.0, True), (80.0, False)):
        rates = {(512, False): 100.0, (256, False): 90.0,
                 (512, True): fused_rate}

        def fake_measure(row, emit_quick=True, emit_final=True):
            rate = rates[(row.batch_size, row.fused_block)]
            if emit_final:
                bench._emit_metric(row, rate, protocol=f"b{row.batch_size}")
            return rate

        monkeypatch.setattr(bench, "_child_measure", fake_measure)
        args = _args(bench, ["--model", "resnet50"])  # sweep stays "auto"

        _run_child_with_fake_jax(bench, args)
        out = [json.loads(line) for line in
               capsys.readouterr().out.strip().splitlines()]
        fused = [r for r in out if "fusedblock" in (r.get("protocol") or "")]
        assert bool(fused) == expect_emitted, (fused_rate, out)
        if expect_emitted:
            assert out[-1]["value"] == fused_rate  # last line wins


def test_preflight_kills_hung_backend_fast(bench):
    # A child that never prints the backend-up heartbeat models a down
    # tunnel (jax.devices() hangs). The attempt must die at the preflight
    # deadline, not the full timeout.
    import time as _time
    t0 = _time.monotonic()
    n, _err, rc = bench._run_attempt(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        timeout=60, relay_errors=False, preflight=2)
    assert n == 0
    assert isinstance(rc, str) and rc.startswith("preflight")
    assert _time.monotonic() - t0 < 20


def test_preflight_disarmed_by_backend_heartbeat(bench):
    # Once the heartbeat lands, the preflight deadline must NOT fire; the
    # ordinary attempt timeout governs from then on.
    # Emit the real heartbeat constant so this test fails if the child's
    # note and the parent's matcher ever drift apart.
    child = (f"import sys, time; "
             f"print('# bench: {bench.BACKEND_UP_HEARTBEAT} 1 x tpu', "
             f"file=sys.stderr, flush=True); time.sleep(60)")
    n, _err, rc = bench._run_attempt(
        [sys.executable, "-c", child],
        timeout=6, relay_errors=False, preflight=2)
    assert n == 0
    assert isinstance(rc, str) and rc.startswith("timeout")


def test_preflight_failure_skips_remaining_attempts(bench, monkeypatch,
                                                    capsys):
    calls = []

    def fake_attempt(cmd, timeout, *, relay_errors, record_good=True,
                     preflight=0):
        calls.append(preflight)
        return 0, "", "preflight 75s: backend never came up"

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    rc = bench.main(["--attempts", "3"])
    assert rc == 0
    assert len(calls) == 1  # no retries against a hung backend
    out = [json.loads(line) for line in
           capsys.readouterr().out.strip().splitlines()]
    assert out[-1]["value"] is None
    assert "preflight" in out[-1]["error"]


def test_error_record_carries_stale_age(bench, capsys):
    import time as _time
    measured = _time.strftime("%Y-%m-%d %H:%M:%S",
                              _time.localtime(_time.time() - 3600))
    bench._record_last_good(json.dumps({
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": 2000.0, "measured_at": measured}))
    args = _args(bench, ["--model", "resnet50"])
    bench._emit_error(args, "tunnel down")
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["last_measured_on_live_chip"]["value"] == 2000.0
    # Top-level age: ~1h, with slack for slow test boxes.
    assert 3500 <= rec["stale_age_s"] <= 3800


def test_last_good_cache_keyed_per_metric(bench, tmp_path):
    bench._record_last_good(json.dumps({"metric": "a", "value": 1}))
    bench._record_last_good(json.dumps({"metric": "b", "value": 2}))
    bench._record_last_good(json.dumps({"metric": "a", "value": 3}))
    with open(bench.LAST_GOOD_PATH) as f:
        table = json.load(f)
    assert table["a"]["value"] == 3 and table["b"]["value"] == 2
