"""Version portability shims for the narrow band of JAX APIs this codebase
uses that moved (or did not exist yet) across the JAX releases the repo runs
under — the container pins one JAX, real chip sessions may pin another.

Two groups:

- **Renames/moves** (``shard_map``, ``use_mesh``, ``get_abstract_mesh``,
  ``typeof``, Pallas ``CompilerParams``): resolve the newest-API name first,
  fall back to the older spelling, never change behavior.
- **Replication-check semantics** (``shard_map``'s ``check_vma`` /
  ``check_rep``): the explicit-DP train path (train/steps.py) performs every
  cross-shard reduction EXPLICITLY through parallel/collectives.py — the
  whole point of the bucketed all-reduce is owning the grad-sync schedule —
  so the automatic psum that replication-checked autodiff inserts for
  replicated inputs must be OFF. ``shard_map`` here therefore always
  disables the check: per-shard values stay local until code psums them.
  With the check off ``pvary`` is semantically a no-op, so its shim is
  identity on versions that lack it.
"""

from __future__ import annotations

from typing import Any

import jax


def shard_map(f, *, mesh=None, in_specs, out_specs):
    """``jax.shard_map`` across API generations, replication checking OFF.

    Callers own their collectives: gradients/metrics/statistics that must
    agree across shards are explicitly ``psum``/``pmean``-ed (train/steps.py,
    parallel/collectives.py), so no output relies on inferred replication.

    ``mesh=None`` uses the ambient mesh (``use_mesh``) — on older JAX, where
    shard_map has no ambient-mesh resolution, it is looked up explicitly.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is None and mesh is None:
        mesh = get_abstract_mesh()
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
        except TypeError:  # a jax.shard_map generation before check_vma
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def pvary(x, axis_names):
    """``jax.lax.pvary`` where it exists; identity elsewhere.

    Only meaningful under varying-manual-axes checking, which
    :func:`shard_map` above disables — the call is kept so the code reads
    identically to the checked form and survives a future re-enable.
    """
    fn = getattr(jax.lax, "pvary", None)
    return x if fn is None else fn(x, axis_names)


def axis_size(axis_names):
    """``jax.lax.axis_size`` where it exists; the classic ``psum(1, axis)``
    idiom elsewhere (constant-folds to the mesh axis size inside manual
    collectives)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_names)
    return jax.lax.psum(1, axis_names)


def typeof(x) -> Any:
    """``jax.typeof`` (new) / ``jax.core.get_aval`` (old).

    Consumers only getattr optional attributes (e.g. ``.vma``) off the
    result, so the old aval — which simply lacks them — is a valid stand-in.
    """
    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    return jax.core.get_aval(x)


def get_abstract_mesh():
    """The ambient mesh set by ``use_mesh`` (parallel/mesh.py), across the
    ``jax.sharding.get_abstract_mesh`` rename. Falls back to the legacy
    thread-resources physical mesh (what ``with mesh:`` sets); callers
    treat an empty mesh (no axes) as "no ambient mesh"."""
    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    if fn is not None:
        return fn()
    from jax._src import mesh as mesh_lib

    return mesh_lib.thread_resources.env.physical_mesh


def tpu_compiler_params(*, dimension_semantics: tuple[str, ...]):
    """Pallas-TPU compiler params across the ``TPUCompilerParams`` →
    ``CompilerParams`` rename; None (pallas_call's default) when neither
    exists so interpret-mode-only environments still run."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:  # pragma: no cover - pallas always ships with jax
        return None
    cls = (getattr(pltpu, "CompilerParams", None)
           or getattr(pltpu, "TPUCompilerParams", None))
    if cls is None:  # pragma: no cover
        return None
    return cls(dimension_semantics=dimension_semantics)
