"""Typed configuration for trainers, data, optimizers, and parallelism.

Replaces the reference's dotenv + Makefile variables + per-script argparse
flags (SURVEY.md §2 #12) with one dataclass tree; ``train.py`` exposes the
same CLI surface (``--backend``, model/batch/epoch flags) per BASELINE.json:5
("train.py entrypoints ... run unchanged from the CLI with --backend=tpu").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass
class ParallelConfig:
    """Device-mesh layout.

    Axis sizes multiply to the total device count. ``data`` is the
    Horovod-equivalent allreduce axis (BASELINE.json:5: "jax.pmap/pjit
    emitting XLA psum over ICI"); ``model``/``seq`` enable tensor and
    sequence/context parallelism for transformer workloads.
    """

    data: int = 1       # dp: batch sharding, grad psum
    fsdp: int = 1       # parameter sharding along the data axis family
    model: int = 1      # tp: weight-column/row sharding
    seq: int = 1        # sp/cp: sequence-dim sharding (ring attention)
    expert: int = 1     # ep: MoE expert sharding (models/moe.py)
    pipeline: int = 1   # pp: GPipe pipeline stages (models/pipeline.py)
    # Validation-only: emulate an N-slice pod's hybrid ICI/DCN device layout
    # on non-TPU platforms (tests / dryrun_multichip), exercising the same
    # _hybrid_shapes axis split a real multi-slice mesh gets. 0/1 = off.
    # On real TPU the slice count is auto-detected and this knob is ignored.
    emulate_slices: int = 0

    @property
    def num_devices(self) -> int:
        return (self.data * self.fsdp * self.model * self.seq
                * self.expert * self.pipeline)

    def axis_sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "model": self.model,
            "seq": self.seq,
            "expert": self.expert,
            "pipeline": self.pipeline,
        }


@dataclasses.dataclass
class AllReduceConfig:
    """Gradient all-reduce policy for the explicit-DP path (shard_map).

    Horovod-style tensor fusion (parallel/collectives.py): gradient leaves
    are packed into size-targeted buckets and reduced with ONE collective
    per bucket instead of one per parameter tensor, so XLA can overlap the
    early buckets' reductions with the tail of the backward pass.
    """

    bucket_mb: float = 4.0        # fusion-buffer target size; 0 = per-leaf
                                  # reduction (the unfused A/B baseline)
    dtype: str = "float32"        # reduction payload: float32 (grads' own
                                  # dtype) | bfloat16 (half the wire bytes;
                                  # fp32 masters restored after the reduce)
    algorithm: str = "psum"       # psum (one all-reduce) | ring
                                  # (psum_scatter + all_gather, the
                                  # bandwidth-optimal two-phase form)

    def describe(self) -> str:
        mode = (f"fused bucket_mb={self.bucket_mb:g}" if self.bucket_mb > 0
                else "per-leaf")
        return f"{mode} dtype={self.dtype} algo={self.algorithm}"


@dataclasses.dataclass
class DataConfig:
    """Input pipeline settings (SURVEY.md §2 #5/#6)."""

    dataset: str = "imagenet"
    data_dir: Optional[str] = None
    synthetic: bool = True        # config 1: "synthetic data" BASELINE.json:7
    synthetic_learnable: bool = False  # embed a class signal in synthetic
                                  # images (top-1 becomes meaningful)
    loader: str = "auto"          # auto | tf | native (csrc/ C++ loader) |
                                  # grain (data/grain_pipeline.py)
    image_size: int = 224
    num_classes: int = 1000
    shuffle_buffer: int = 16384
    prefetch_depth: int = 2       # StreamSource lookahead batches (host->HBM
                                  # pipelining; also the native loader's
                                  # batch-slot ring depth - 1)
    # Per-batch loader watchdog for host-streaming sources (tf/native/
    # grain/tokens): a pull that exceeds the timeout is retried (with a
    # loud warning) up to loader_retries times, then the run dies with a
    # clear "loader stalled" error instead of hanging the collective step
    # on every host. 0 = watchdog off (docs/fault_tolerance.md).
    loader_timeout_s: float = 0.0
    loader_retries: int = 2
    # BERT-style sequence workloads:
    seq_len: int = 128
    vocab_size: int = 30522
    mlm_mask_prob: float = 0.15
    mlm_max_predictions: int = 0  # >0: gather-mode MLM — batches carry fixed-
                                  # width (masked_positions, masked_labels)
                                  # and the model projects ONLY those
                                  # positions to vocab (the canonical BERT /
                                  # MLPerf head: ~6.7x less head compute +
                                  # logits memory at 15% masking); 0 = dense
                                  # (B, S) labels


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """End-to-end mixed-precision policy (docs/mixed_precision.md).

    One object names every dtype decision the large-batch recipes care
    about (PAPERS.md: arXiv 1711.04325 trains ResNet-50 at 32k in mixed
    precision), instead of the three half-coordinated knobs the legacy
    path spreads across ``TrainConfig.dtype`` / ``AllReduceConfig.dtype``:

    - ``compute_dtype`` — forward/backward activation (and zero3 gathered-
      parameter) dtype;
    - ``param_dtype`` — the persistent master weights + optimizer state.
      MUST stay ``float32``: the update ``p - lr*g`` at bf16 resolution
      silently loses every increment below ~2^-8 of the weight magnitude
      (the silent-precision-loss bug class ddl-lint's
      ``master-weight-cast`` rule exists for);
    - ``reduce_dtype`` — gradient all-reduce / reduce-scatter wire payload
      (bfloat16 halves wire bytes; fp32 masters are restored after);
    - ``loss_scale`` — initial DYNAMIC loss scale (0 = off). The loss is
      multiplied by the scale before backward and gradients divided after;
      a non-finite scaled gradient skips the update and halves the scale,
      ``loss_scale_growth_interval`` consecutive good steps double it
      (bounded to [``loss_scale_min``, ``loss_scale_max``]). A scale
      backoff is a *controlled* event — it reports under its own
      ``loss_scale_skip`` metric and never increments the bad-step
      anomaly counter (train/loop.py ``_BadStepTracker``).

    The policy is part of the AOT ``config_fingerprint`` (perf/aot.py
    hashes the whole config dataclass), so fp32 and mixed arms key
    separate executables and separate perf baselines by construction.
    """

    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    reduce_dtype: str = "bfloat16"
    loss_scale: float = 0.0
    loss_scale_growth_interval: int = 200
    loss_scale_min: float = 1.0
    loss_scale_max: float = 65536.0

    @classmethod
    def mixed(cls) -> "PrecisionPolicy":
        """The large-batch mixed arm: bf16 compute + wire, fp32 masters,
        dynamic loss scaling armed (bf16 shares fp32's exponent range, so
        the scale rarely moves — it exists to catch the overflow tail)."""
        return cls(compute_dtype="bfloat16", reduce_dtype="bfloat16",
                   loss_scale=32768.0)

    @classmethod
    def fp32(cls) -> "PrecisionPolicy":
        """The A/B reference arm: everything float32, no scaling."""
        return cls(compute_dtype="float32", reduce_dtype="float32",
                   loss_scale=0.0)

    def describe(self) -> str:
        """Compact provenance tag, e.g. ``bf16/f32/bf16+dls32768``."""
        short = {"float32": "f32", "bfloat16": "bf16"}
        tag = (f"{short.get(self.compute_dtype, self.compute_dtype)}/"
               f"{short.get(self.param_dtype, self.param_dtype)}/"
               f"{short.get(self.reduce_dtype, self.reduce_dtype)}")
        if self.loss_scale > 0:
            tag += f"+dls{self.loss_scale:g}"
        return tag


def resolve_precision(config: "TrainConfig") -> PrecisionPolicy:
    """The run's effective precision policy. ``config.precision=None``
    (default) derives the legacy behavior — compute at ``config.dtype``,
    fp32 params, reduction payload per ``config.allreduce`` — so every
    existing config compiles the exact same program as before the policy
    existed. An explicit policy is validated here, once, on the way in."""
    policy = getattr(config, "precision", None)
    if policy is None:
        return PrecisionPolicy(
            compute_dtype=config.dtype, param_dtype="float32",
            reduce_dtype=getattr(config.allreduce, "dtype", "float32"),
            loss_scale=0.0)
    for field, value in (("compute_dtype", policy.compute_dtype),
                         ("reduce_dtype", policy.reduce_dtype)):
        if value not in ("float32", "bfloat16"):
            raise ValueError(
                f"PrecisionPolicy.{field}={value!r}: use 'float32' or "
                f"'bfloat16'")
    if policy.param_dtype != "float32":
        raise ValueError(
            f"PrecisionPolicy.param_dtype={policy.param_dtype!r}: master "
            f"weights must stay float32 — a bf16 master silently drops "
            f"every update below ~2^-8 of the weight magnitude "
            f"(docs/mixed_precision.md)")
    if policy.loss_scale < 0:
        raise ValueError(f"loss_scale must be >= 0 "
                         f"(got {policy.loss_scale})")
    if policy.loss_scale > 0:
        if policy.loss_scale_growth_interval < 1:
            raise ValueError("loss_scale_growth_interval must be >= 1")
        if not (0 < policy.loss_scale_min <= policy.loss_scale
                <= policy.loss_scale_max):
            raise ValueError(
                f"need 0 < loss_scale_min <= loss_scale <= loss_scale_max "
                f"(got {policy.loss_scale_min} / {policy.loss_scale} / "
                f"{policy.loss_scale_max})")
    return policy


@dataclasses.dataclass
class OptimizerConfig:
    """Optimizer + schedule (SGD-momentum default; LARS for config 5)."""

    name: str = "sgd"             # sgd | lars | adamw | lamb
    learning_rate: float = 0.1    # for the reference batch size (256)
    reference_batch: int = 256    # linear-scaling rule base
    momentum: float = 0.9
    weight_decay: float = 1e-4
    warmup_epochs: float = 5.0
    schedule: str = "warmup_cosine"  # warmup_cosine | constant | linear
    label_smoothing: float = 0.1
    grad_clip_norm: Optional[float] = None
    # Exponential moving average of params (0 = off). When on, every
    # update folds new params in at (1 - decay) and ALL held-out evals
    # (periodic, final, --eval-only) score the EMA weights — the classic
    # ImageNet/BERT eval-smoothing recipe.
    ema_decay: float = 0.0
    # LARS (config 5, BASELINE.json:11):
    trust_coefficient: float = 0.001
    # AdamW (BERT):
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8


@dataclasses.dataclass
class TrainConfig:
    """Top-level run description — one per acceptance config."""

    model: str = "resnet50"
    backend: str = "tpu"          # tpu | cpu (BASELINE.json:5); "cpu" forces
                                  # the mesh onto host CPU devices even when
                                  # an accelerator platform is active
    global_batch_size: int = 32   # config 1 default (BASELINE.json:7)
    num_epochs: float = 90.0
    steps_per_epoch: Optional[int] = None  # derived from dataset if None
    total_steps: Optional[int] = None      # overrides epochs when set
    dtype: str = "bfloat16"       # compute dtype; params stay f32. Subsumed
                                  # by ``precision`` when that is set — kept
                                  # as the legacy knob so every existing
                                  # config compiles unchanged
    precision: Optional[PrecisionPolicy] = None  # end-to-end mixed-precision
                                  # policy (compute/param/reduce dtypes +
                                  # dynamic loss scaling). None derives the
                                  # legacy behavior from ``dtype`` and
                                  # ``allreduce.dtype`` (resolve_precision);
                                  # part of the AOT config_fingerprint, so
                                  # fp32 and mixed arms never share an
                                  # executable or a perf baseline
    batch_ramp: Optional[str] = None  # staged global-batch ramp (arXiv
                                  # 1711.04325 recipe), e.g. "8192:600,32768":
                                  # comma stages of batch[:steps], last stage
                                  # (no :steps) runs to the horizon and must
                                  # equal global_batch_size. LR follows the
                                  # linear-scaling rule per stage; every
                                  # boundary must land on a checkpoint
                                  # cadence step (train/optim.py
                                  # parse_batch_ramp validates) so resume and
                                  # elastic re-formation compose unchanged
    grad_accum_steps: int = 1     # microbatches per optimizer step (config 5
                                  # at 32k runs on any mesh via accumulation)
    steps_per_loop: int = 1       # train steps fused into ONE XLA program
                                  # (lax.scan) when data is generated
                                  # on-device; amortizes per-step host
                                  # dispatch — the TPUEstimator
                                  # iterations_per_loop idiom
    seed: int = 0
    log_every: int = 100
    eval_every_epochs: float = 1.0
    checkpoint_dir: Optional[str] = None
    checkpoint_every_steps: int = 5000
    resume: bool = True
    profile_steps: Optional[tuple[int, int]] = None  # SURVEY.md §5.1
    profile_dir: Optional[str] = None  # trace output (TensorBoard-loadable)
    trace_dir: Optional[str] = None  # always-on phase telemetry
                                  # (observability/telemetry.py): per-step
                                  # phase spans + per-bucket collective
                                  # spans + fault/restart instants exported
                                  # as Chrome-trace JSON here. None = the
                                  # no-op disabled path
    trace_steps: Optional[tuple[int, int]] = None  # restrict step-tagged
                                  # telemetry events to [a, b); None = the
                                  # whole run (the ring buffer bounds
                                  # memory either way)
    trace_max_events: int = 200_000  # telemetry ring-buffer capacity
    flight_dir: Optional[str] = None  # flight recorder (observability/
                                  # flight.py): crash-surviving fsync'd
                                  # JSONL event log, one file per host.
                                  # None = the launcher-exported
                                  # DDL_FLIGHT_DIR, else disabled
    anomaly_detection: bool = True  # online anomaly detector (observability/
                                  # anomaly.py) over the chief's log-cadence
                                  # records: loss spikes, grad-norm drift,
                                  # throughput collapse, straggler trending.
                                  # Host-side medians only — no device cost
    straggler_threshold: float = 1.5  # multi-host only: warn when a host's
                                  # log-cadence step_time exceeds this x the
                                  # cross-host mean (observability/
                                  # straggler.py); 0 disables the allgather
    fail_at_step: Optional[int] = None  # DEPRECATED single-fault injection:
                                  # shimmed to fault_plan "crash@N:always"
                                  # (robustness/faults.py); kept so existing
                                  # flags/scripts run unchanged
    fault_plan: Optional[str] = None  # scheduled fault injection, e.g.
                                  # "nan_grads@5,corrupt_latest_ckpt@6,
                                  # sigkill@6" — grammar and semantics in
                                  # robustness/faults.py and
                                  # docs/fault_tolerance.md. None = zero
                                  # injection code anywhere on the hot path
    bad_step_guard: bool = False  # compile the non-finite-update skip guard
                                  # into the train step (auto-on when the
                                  # fault plan injects nan_grads). Opt-in
                                  # because the skip-select keeps pre-update
                                  # buffers alive, which re-fuses the XLA
                                  # program ~1 ULP off the guard-free (and
                                  # zero1-bitwise-pinned) trajectory
    bad_step_limit: int = 10      # abort after K CONSECUTIVE non-finite
                                  # (skipped) update steps — one bad batch
                                  # is skipped and counted, a divergent run
                                  # dies loudly instead of burning the
                                  # budget on no-op steps
    attention_impl: Optional[str] = None  # None=default; dense|ring|flash
    remat: bool = False           # recompute transformer-layer activations
                                  # in backward (less HBM, ~1/3 more FLOPs)
    fused_bn: bool = False        # Pallas fused BN+ReLU kernels (CNNs)
    fused_block: bool = False     # conv-epilogue fusion: bottleneck 1x1
                                  # convs as Pallas matmul+BN (resnet50+)
    fused_conv3: bool = False     # fused_block v2: stride-1 3x3 convs as
                                  # Pallas conv+BN (ops/fused_conv_bn.py);
                                  # requires fused_block
    sync_bn: bool = False         # cross-replica BN statistics (psum over
                                  # the data axis; torch SyncBatchNorm)
    optimizer_sharding: str = "none"  # none | zero1 | zero2 | zero3
                                  # (explicit-DP path only) — the ZeRO
                                  # ladder (parallel/zero.py): zero1 shards
                                  # optimizer state 1/N (reduce-scatter
                                  # grads, chunk update, all-gather updated
                                  # params); zero2 additionally never
                                  # materializes the full gradient tree
                                  # (grads born reduce-scattered during
                                  # backward, same update math as zero1);
                                  # zero3 additionally keeps the parameters
                                  # themselves 1/N-sharded, all-gathered
                                  # on demand per fusion bucket
    overlap_collectives: bool = True  # zero2/zero3 only: issue each fusion
                                  # bucket's gradient reduce-scatter inside
                                  # backward as its cotangents complete
                                  # (custom_vjp bucket boundaries) instead
                                  # of one serialized pass after backward.
                                  # Off = A/B baseline; update math is
                                  # unchanged either way
    opt_state_offload: bool = False  # place the sharded optimizer-state
                                  # chunks in host RAM (pinned_host memory
                                  # kind) instead of HBM. Needs runtime
                                  # support (TPU); silently-loud no-op
                                  # fallback elsewhere (docs/
                                  # zero_sharding.md caveats)
    compile_cache_dir: Optional[str] = None  # persistent compile cache + AOT
                                  # step executables (perf/compile_cache.py):
                                  # None = $DDL_COMPILE_CACHE, else the
                                  # repo-local .cache/jax_compile default;
                                  # "off" disables. Volatile w.r.t. the
                                  # config fingerprint — it never changes
                                  # the compiled program
    # GPipe microbatch count for *_pp models (None = model default). The
    # bubble wastes (P-1)/(M+P-1) of every stage-tick; M >= 4(P-1) keeps it
    # under ~20% (tools/bench_parallel_overhead.py measures this).
    pipeline_microbatches: Optional[int] = None
    pipeline_schedule: str = "gpipe"  # "gpipe" (fill/drain) or "1f1b"
                                  # (interleaved virtual stages, bubble
                                  # (P-1)/(M*V+P-1) — models/pipeline.py,
                                  # docs/pipeline.md). Both compile to one
                                  # XLA program; the fingerprint keeps
                                  # their AOT executables apart
    pipeline_virtual_stages: int = 1  # V chunks per stage under 1f1b; each
                                  # extra chunk divides the bubble at the
                                  # cost of V x more in-flight activation
                                  # shifts per microbatch
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    allreduce: AllReduceConfig = dataclasses.field(
        default_factory=AllReduceConfig)

    @property
    def per_device_batch(self) -> int:
        shards = self.parallel.data * self.parallel.fsdp
        if self.global_batch_size % max(shards, 1):
            raise ValueError(
                f"global_batch_size={self.global_batch_size} not divisible by "
                f"data-parallel shards={shards}")
        per_device = self.global_batch_size // max(shards, 1)
        if self.grad_accum_steps > 1 and per_device % self.grad_accum_steps:
            raise ValueError(
                f"per-device batch {per_device} not divisible by "
                f"grad_accum_steps={self.grad_accum_steps}")
        return per_device

    def replace(self, **kw: Any) -> "TrainConfig":
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------------
# Acceptance-config presets (BASELINE.json:6-12). Keyed by the names used by
# train.py --config=... ; each is a TrainConfig factory so tests can shrink
# them without mutation hazards.
# ---------------------------------------------------------------------------

def resolve_mlm_max_predictions(value: int, seq_len: int,
                                objective: str = "mlm") -> int:
    """One source of truth for the gather-head auto rule shared by
    train.py/bench.py: -1 resolves to the canonical ``round(0.15*seq_len)``
    for the mlm objective and to 0 (dense / no-op) for anything else, so a
    causal model can never silently carry a dead gather config. Explicit
    values are clamped to ``seq_len`` — a wider head is meaningless (at most
    seq_len positions can be masked) and the host pipeline's argsort-based
    masking would emit a narrower batch than the synthetic pipeline,
    crashing downstream with an opaque broadcast error (ADVICE r2 #1)."""
    if value >= 0:
        return min(value, seq_len) if objective == "mlm" else 0
    return int(round(0.15 * seq_len)) if objective == "mlm" else 0


def preset(name: str) -> TrainConfig:
    """Return one of the five acceptance configurations by name."""
    if name == "resnet50_synthetic":      # config 1
        return TrainConfig(
            model="resnet50", global_batch_size=32,
            data=DataConfig(synthetic=True))
    if name == "resnet50_dp":             # config 2
        return TrainConfig(
            model="resnet50", global_batch_size=256,
            parallel=ParallelConfig(data=8),
            data=DataConfig(synthetic=False))
    if name == "resnet152_dp":            # config 3
        return TrainConfig(
            model="resnet152", global_batch_size=256,
            parallel=ParallelConfig(data=8))
    if name == "densenet121_dp":          # config 3
        return TrainConfig(
            model="densenet121", global_batch_size=256,
            parallel=ParallelConfig(data=8))
    if name == "bert_base_mlm":           # config 4
        return TrainConfig(
            model="bert_base", global_batch_size=256,
            parallel=ParallelConfig(data=8),
            data=DataConfig(dataset="mlm", seq_len=128),
            optimizer=OptimizerConfig(
                name="adamw", learning_rate=1e-4, weight_decay=0.01,
                schedule="linear", warmup_epochs=0.0, label_smoothing=0.0))
    if name == "bert_base_mlm_longctx":   # long-context: ring attention over
        return TrainConfig(               # the seq axis (SURVEY.md §5.7)
            model="bert_base", global_batch_size=32,
            parallel=ParallelConfig(data=2, seq=4),
            attention_impl="ring",
            data=DataConfig(dataset="mlm", seq_len=2048),
            optimizer=OptimizerConfig(
                name="adamw", learning_rate=1e-4, weight_decay=0.01,
                schedule="linear", warmup_epochs=0.0, label_smoothing=0.0))
    if name == "resnet50_lars_32k":       # config 5
        # batch 32k as 8-way DP x 16 microbatches per update: the LARS recipe
        # (one optimizer step per 32768 examples) runs on any mesh; on a real
        # 256-chip pod pass --dp 256 --accum 1 to trade accumulation for
        # chips without touching the optimizer math.
        return TrainConfig(
            model="resnet50", global_batch_size=32768, dtype="bfloat16",
            grad_accum_steps=16,
            parallel=ParallelConfig(data=8),
            optimizer=OptimizerConfig(
                # peak LR 29.0 AT batch 32k (LARS paper recipe): pin
                # reference_batch so the linear-scaling rule is identity here.
                name="lars", learning_rate=29.0, reference_batch=32768,
                momentum=0.9, weight_decay=1e-4, warmup_epochs=5.0,
                schedule="warmup_poly", label_smoothing=0.1))
    raise KeyError(f"unknown preset {name!r}; see BASELINE.json configs")


PRESETS = (
    "resnet50_synthetic", "resnet50_dp", "resnet152_dp", "densenet121_dp",
    "bert_base_mlm", "bert_base_mlm_longctx", "resnet50_lars_32k",
)
