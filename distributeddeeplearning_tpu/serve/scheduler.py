"""Per-tenant SLO-aware admission / preemption for the serve engine.

Pure host-side policy, fully deterministic, zero jax: the engine hands it
the wait queue and the live-slot table each step, and it returns a
:class:`Plan` — who to admit (in order) and at most one slot to preempt.
Keeping it pure makes every policy decision unit-testable without a model.

Policy, in the order it is applied:

1. **Priority = deadline slack.** Each waiting request's slack is
   ``(arrival + tenant.ttft_slo_s) - now``; the queue is served most
   negative (most overdue) first, ties broken by arrival then uid — FIFO
   within a tenant class.
2. **Admission by free-page budget.** A request needs
   ``pages_needed(prompt + max_new_tokens)`` pages and one free slot,
   allocate-all-or-nothing — a slot that could run out of pages mid-decode
   would corrupt its own tail, so the full budget is reserved up front.
   A tenant with ``max_pages`` set is also capped across its live slots:
   over-budget tenants simply stop admitting.
3. **Preemption (at most one per plan).** When the most urgent
   *within-budget* request is starved — of a slot or of pages — the most
   recently admitted live slot of an OVER-budget tenant is preempted:
   its slot and pages return, and its request re-queues with everything
   generated so far folded into the prompt (greedy decoding makes the
   continuation deterministic, so no work is lost — tests pin
   token-identity across preemption). One per step bounds thrash; the
   next step re-evaluates.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from distributeddeeplearning_tpu.serve.kv_cache import pages_needed


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """What the engine owes a tenant (TTFT SLO) and what the tenant may
    hold (page cap across its live slots; None = uncapped)."""

    name: str
    ttft_slo_s: float = 1.0
    max_pages: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class Plan:
    """One step's scheduling decision: requests to admit, in priority
    order, and at most one live slot id to preempt first."""

    admit: tuple
    preempt: tuple

    @property
    def empty(self) -> bool:
        return not self.admit and not self.preempt


class SloScheduler:
    """Deadline-slack scheduler over the engine's wait queue.

    ``policies`` maps tenant name -> :class:`TenantPolicy`; unknown
    tenants get ``default_policy``.
    """

    def __init__(self, policies: Optional[Sequence[TenantPolicy]] = None,
                 default_policy: Optional[TenantPolicy] = None):
        self.default_policy = default_policy or TenantPolicy("default")
        self.policies = {p.name: p for p in (policies or ())}

    def policy(self, tenant: str) -> TenantPolicy:
        return self.policies.get(tenant, self.default_policy)

    def slack_s(self, request, now: float) -> float:
        """Seconds until (negative: since) the tenant's TTFT deadline."""
        return (request.arrival_s + self.policy(request.tenant).ttft_slo_s
                - now)

    def plan(self, *, now: float, waiting: Sequence, live: Sequence,
             free_slots: int, free_pages: int, page_size: int) -> Plan:
        """``waiting``: requests (``tenant``/``arrival_s``/``uid`` plus
        ``total_tokens`` = prompt+emitted+remaining). ``live``: slot views
        with ``slot``/``tenant``/``num_pages``/``admitted_seq``."""
        tenant_pages: dict[str, int] = {}
        for s in live:
            tenant_pages[s.tenant] = (tenant_pages.get(s.tenant, 0)
                                      + s.num_pages)

        order = sorted(waiting,
                       key=lambda r: (self.slack_s(r, now), r.arrival_s,
                                      r.uid))
        admit: list = []
        preempt: list = []
        preempted_tenants: set[str] = set()
        for req in order:
            pol = self.policy(req.tenant)
            need = pages_needed(req.total_tokens, page_size)
            if (pol.max_pages is not None
                    and tenant_pages.get(req.tenant, 0) + need
                    > pol.max_pages):
                continue  # over-budget tenant: holds its place, no slot
            if free_slots <= 0 or need > free_pages:
                if preempt:  # at most one eviction per plan
                    break
                # Slot- and page-starvation evict alike: the victim's
                # slot AND pages both return.
                victim = self._victim(live, tenant_pages,
                                      exclude=preempted_tenants)
                if victim is not None and (free_pages + victim.num_pages
                                           >= need):
                    preempt.append(victim.slot)
                    preempted_tenants.add(victim.tenant)
                    tenant_pages[victim.tenant] -= victim.num_pages
                    free_pages += victim.num_pages
                    free_slots += 1
                else:
                    break  # starved and nothing evictable: wait
            admit.append(req)
            free_slots -= 1
            free_pages -= need
            tenant_pages[req.tenant] = tenant_pages.get(req.tenant, 0) + need
        return Plan(admit=tuple(admit), preempt=tuple(preempt))

    def _victim(self, live: Sequence, tenant_pages: dict,
                exclude: set):
        """Most recently admitted slot of an over-budget tenant (newest
        first minimizes wasted decode work), or None when every tenant is
        within budget — within-budget work is never evicted."""
        candidates = []
        for s in live:
            pol = self.policy(s.tenant)
            if s.tenant in exclude or pol.max_pages is None:
                continue
            if tenant_pages.get(s.tenant, 0) > pol.max_pages:
                candidates.append(s)
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.admitted_seq)
