"""Per-tenant SLO-aware admission / preemption for the serve engine.

Pure host-side policy, fully deterministic, zero jax: the engine hands it
the wait queue and the live-slot table each step, and it returns a
:class:`Plan` — who to admit (in order) and at most one slot to preempt.
Keeping it pure makes every policy decision unit-testable without a model.

Policy, in the order it is applied:

1. **Priority = deadline slack.** Each waiting request's slack is
   ``(arrival + tenant.ttft_slo_s) - now``; the queue is served most
   negative (most overdue) first, ties broken by arrival then uid — FIFO
   within a tenant class.
2. **Admission by free-page budget.** A request needs
   ``pages_needed(prompt + max_new_tokens)`` pages and one free slot,
   allocate-all-or-nothing — a slot that could run out of pages mid-decode
   would corrupt its own tail, so the full budget is reserved up front.
   A tenant with ``max_pages`` set is also capped across its live slots:
   over-budget tenants simply stop admitting.
3. **Preemption (at most one per plan).** When the most urgent
   *within-budget* request is starved — of a slot or of pages — the most
   recently admitted live slot of an OVER-budget tenant is preempted:
   its slot and pages return, and its request re-queues with everything
   generated so far folded into the prompt (greedy decoding makes the
   continuation deterministic, so no work is lost — tests pin
   token-identity across preemption). One per step bounds thrash; the
   next step re-evaluates.
4. **Deadlines (opt-in).** A tenant may carry hard budgets on top of the
   soft TTFT SLO: ``ttft_deadline_s`` (a waiting request that has not
   produced its first token by then is expired rather than served
   uselessly late) and ``total_deadline_s`` (a request — waiting or live —
   past its total-latency budget is expired/cancelled, returning its slot
   and pages). Both default to None: no enforcement, the PR-12 behavior.
5. **Bounded retry with backoff.** A re-queued victim (preemption, replica
   loss) is re-admitted at most ``max_retries`` times; each re-admission
   waits ``retry_backoff_s * 2**(retries-1)`` before becoming eligible
   (``Request.not_before_s``), so a thrashing tenant cannot hot-loop the
   admission path. Defaults (0 backoff, unbounded) preserve the PR-12
   preemption-resume behavior.

The brownout controller (:class:`BrownoutController`) rides on the same
slack computation: under page-pool or queue pressure it sheds the waiting
requests that are already past their deadline-slack floor — work that is
doomed anyway — instead of letting it collapse p99 for every tenant.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from distributeddeeplearning_tpu.serve.kv_cache import pages_needed


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """What the engine owes a tenant (TTFT SLO) and what the tenant may
    hold (page cap across its live slots; None = uncapped). The deadlines
    are hard budgets, distinct from the soft SLO: past ``ttft_deadline_s``
    a still-waiting request is expired; past ``total_deadline_s`` a request
    is expired/cancelled wherever it is. None (default) = unenforced."""

    name: str
    ttft_slo_s: float = 1.0
    max_pages: Optional[int] = None
    ttft_deadline_s: Optional[float] = None
    total_deadline_s: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class Plan:
    """One step's scheduling decision: requests to admit, in priority
    order, at most one live slot id to preempt first, waiting requests to
    expire (deadline missed before first token), and live slot ids to
    cancel (total-latency budget blown mid-decode).

    ``reasons`` maps uid -> why an eligible waiting request was NOT
    admitted this step (``backoff`` / ``tenant_cap`` / ``no_slot`` /
    ``no_pages``); requests held only by admission order carry
    ``priority``. The tracing layer classifies waiting time from it:
    resource starvation (``no_pages``) is an admission stall, policy
    holds are scheduler interference."""

    admit: tuple
    preempt: tuple
    expire: tuple = ()
    cancel: tuple = ()
    reasons: dict = dataclasses.field(default_factory=dict)

    @property
    def empty(self) -> bool:
        return (not self.admit and not self.preempt and not self.expire
                and not self.cancel)


class SloScheduler:
    """Deadline-slack scheduler over the engine's wait queue.

    ``policies`` maps tenant name -> :class:`TenantPolicy`; unknown
    tenants get ``default_policy``. ``max_retries``/``retry_backoff_s``
    bound re-admission of preempted/re-queued victims: the engine consults
    them when it re-queues a request.
    """

    def __init__(self, policies: Optional[Sequence[TenantPolicy]] = None,
                 default_policy: Optional[TenantPolicy] = None,
                 *, max_retries: Optional[int] = None,
                 retry_backoff_s: float = 0.0):
        self.default_policy = default_policy or TenantPolicy("default")
        self.policies = {p.name: p for p in (policies or ())}
        self.max_retries = max_retries
        self.retry_backoff_s = float(retry_backoff_s)

    def retry_delay_s(self, retries: int) -> float:
        """Exponential backoff before re-admission eligibility: the Nth
        retry waits ``retry_backoff_s * 2**(N-1)`` seconds. 0 when backoff
        is unconfigured — the PR-12 immediate-requeue behavior."""
        if self.retry_backoff_s <= 0 or retries <= 0:
            return 0.0
        return self.retry_backoff_s * (2.0 ** (retries - 1))

    def policy(self, tenant: str) -> TenantPolicy:
        return self.policies.get(tenant, self.default_policy)

    def slack_s(self, request, now: float) -> float:
        """Seconds until (negative: since) the tenant's TTFT deadline."""
        return (request.arrival_s + self.policy(request.tenant).ttft_slo_s
                - now)

    def plan(self, *, now: float, waiting: Sequence, live: Sequence,
             free_slots: int, free_pages: int, page_size: int,
             need_pages=None) -> Plan:
        """``waiting``: requests (``tenant``/``arrival_s``/``uid`` plus
        ``total_tokens`` = prompt+emitted+remaining). ``live``: slot views
        with ``slot``/``tenant``/``num_pages``/``admitted_seq``.

        ``need_pages``: optional callable ``req -> int`` overriding the
        page charge for a waiting request. The prefix-cache engine passes
        one that charges only the NEW pages an admission would allocate —
        radix-matched full pages are mapped shared (refcount++), not
        drawn from the free list. ``free_pages`` from that engine is the
        allocator free list plus on-demand-evictable tree pages, so the
        all-or-nothing budget check keeps its meaning. Preemption
        accounting is deliberately conservative: a victim's ``num_pages``
        counts every page it maps, but releasing a shared page only
        drops a refcount — the freed total may be smaller, and the next
        step's re-plan corrects for it."""
        tenant_pages: dict[str, int] = {}
        for s in live:
            tenant_pages[s.tenant] = (tenant_pages.get(s.tenant, 0)
                                      + s.num_pages)

        # Deadline enforcement first: expired work must not consume a slot.
        expire: list = []
        cancel: list = []
        pending: list = []
        for req in waiting:
            pol = self.policy(req.tenant)
            age = now - req.arrival_s
            if (pol.total_deadline_s is not None
                    and age > pol.total_deadline_s):
                expire.append(req)
            elif (pol.ttft_deadline_s is not None
                    and age > pol.ttft_deadline_s
                    and getattr(req, "ttft_s", None) is None):
                # Past the first-token budget with no token out (a resumed
                # victim that already streamed keeps its original TTFT).
                expire.append(req)
            else:
                pending.append(req)
        survivors: list = []
        for s in live:
            pol = self.policy(s.tenant)
            arrival = getattr(s, "arrival_s", None)
            if (pol.total_deadline_s is not None and arrival is not None
                    and now - arrival > pol.total_deadline_s):
                cancel.append(s.slot)
                tenant_pages[s.tenant] -= s.num_pages
                free_slots += 1
                free_pages += s.num_pages
            else:
                survivors.append(s)
        live = survivors

        order = sorted(pending,
                       key=lambda r: (self.slack_s(r, now), r.arrival_s,
                                      r.uid))
        admit: list = []
        preempt: list = []
        reasons: dict = {}
        preempted_tenants: set[str] = set()
        for idx, req in enumerate(order):
            if getattr(req, "not_before_s", 0.0) > now:
                reasons[req.uid] = "backoff"
                continue  # backing off after a retry: holds its place
            pol = self.policy(req.tenant)
            need = (need_pages(req) if need_pages is not None
                    else pages_needed(req.total_tokens, page_size))
            if (pol.max_pages is not None
                    and tenant_pages.get(req.tenant, 0) + need
                    > pol.max_pages):
                reasons[req.uid] = "tenant_cap"
                continue  # over-budget tenant: holds its place, no slot
            if free_slots <= 0 or need > free_pages:
                starve = "no_slot" if free_slots <= 0 else "no_pages"
                if preempt:  # at most one eviction per plan
                    for r in order[idx:]:
                        reasons.setdefault(r.uid, starve)
                    break
                # Slot- and page-starvation evict alike: the victim's
                # slot AND pages both return.
                victim = self._victim(live, tenant_pages,
                                      exclude=preempted_tenants)
                if victim is not None and (free_pages + victim.num_pages
                                           >= need):
                    preempt.append(victim.slot)
                    preempted_tenants.add(victim.tenant)
                    tenant_pages[victim.tenant] -= victim.num_pages
                    free_pages += victim.num_pages
                    free_slots += 1
                else:
                    # Starved and nothing evictable: everything behind
                    # this request (itself included) waits for the same
                    # resource.
                    for r in order[idx:]:
                        reasons.setdefault(r.uid, starve)
                    break
            admit.append(req)
            free_slots -= 1
            free_pages -= need
            tenant_pages[req.tenant] = tenant_pages.get(req.tenant, 0) + need
        return Plan(admit=tuple(admit), preempt=tuple(preempt),
                    expire=tuple(expire), cancel=tuple(cancel),
                    reasons=reasons)

    def _victim(self, live: Sequence, tenant_pages: dict,
                exclude: set):
        """Most recently admitted slot of an over-budget tenant (newest
        first minimizes wasted decode work), or None when every tenant is
        within budget — within-budget work is never evicted."""
        candidates = []
        for s in live:
            pol = self.policy(s.tenant)
            if s.tenant in exclude or pol.max_pages is None:
                continue
            if tenant_pages.get(s.tenant, 0) > pol.max_pages:
                candidates.append(s)
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.admitted_seq)


class BrownoutController:
    """Graceful degradation under overload: shed doomed work, save p99.

    When the page pool or the wait queue is pressured, requests whose
    deadline slack has fallen below ``shed_slack_s`` (i.e. already overdue
    by more than that margin) are shed — they were going to blow their SLO
    anyway, and serving them late steals decode steps and pages from every
    request that can still make its deadline. With no pressure, nothing is
    ever shed: a healthy engine behaves exactly as before.

    Pure host-side policy like the scheduler — deterministic and
    unit-testable without a model.
    """

    def __init__(self, *, page_pressure: float = 0.95,
                 queue_pressure: int = 8, shed_slack_s: float = 0.0,
                 max_shed_per_step: int = 2):
        if not 0.0 < page_pressure <= 1.0:
            raise ValueError(f"page_pressure={page_pressure}: need (0, 1]")
        self.page_pressure = float(page_pressure)
        self.queue_pressure = int(queue_pressure)
        self.shed_slack_s = float(shed_slack_s)
        self.max_shed_per_step = int(max_shed_per_step)

    def pressured(self, *, waiting_depth: int, free_pages: int,
                  num_pages: int) -> bool:
        occupancy = 1.0 - free_pages / max(1, num_pages)
        return (occupancy >= self.page_pressure
                or waiting_depth >= self.queue_pressure)

    def plan_shed(self, *, now: float, waiting: Sequence,
                  scheduler: SloScheduler, free_pages: int,
                  num_pages: int) -> list:
        """Waiting requests to shed this step, lowest slack (most overdue)
        first, at most ``max_shed_per_step`` — empty without pressure."""
        if not self.pressured(waiting_depth=len(waiting),
                              free_pages=free_pages, num_pages=num_pages):
            return []
        overdue = [r for r in waiting
                   if scheduler.slack_s(r, now) < -self.shed_slack_s]
        overdue.sort(key=lambda r: (scheduler.slack_s(r, now), r.uid))
        return overdue[:self.max_shed_per_step]
