"""Paged KV cache: fixed-size pages from a preallocated pool.

The dense decode cache (models/gpt.py, models/llama.py) allocates
``(B, max_position, heads, d)`` per request — memory scales with
batch x the STATIC position bound even when most slots hold short, mostly
finished sequences. Serving wants memory that scales with LIVE tokens:

- one pool per attention layer, ``pages_k``/``pages_v`` of shape
  ``(num_pages, page_size, kv_heads, head_dim)``, allocated once by the
  engine and carried through the decode program as flax "cache" leaves
  (donated, so XLA updates them in place);
- a per-slot **page table** ``(max_slots, max_pages_per_slot)`` mapping
  each slot's token range to pool pages in position order — entry ``j``
  covers positions ``[j*page_size, (j+1)*page_size)``;
- a host-side refcounted free list (:class:`PageAllocator`): admission
  takes pages, retirement returns them, so a retiring slot's memory is
  reusable on the very next step without any copying. Pages can be
  SHARED — a radix prefix cache (:class:`RadixPrefixCache`) and any
  number of slots may hold the same full page (refcount per holder);
  a page returns to the free list only when its last claim drops, and
  in-place writes are only legal at refcount 1 (copy-on-write above —
  ``assert_writable`` / ``clone_page_rows`` enforce the discipline).

Numerics match the dense decode branches exactly where it matters: same
``d**-0.5`` scale, same f32 softmax over ``finfo(f32).min``-masked dead
slots, and the gather is in page-table order == ascending positions, so a
greedy argmax over paged logits equals the dense one (tests pin
token-identity end to end).

Leaf naming follows ``models/generate.py``'s taught-leaf scheme
(:data:`~distributeddeeplearning_tpu.models.generate.CACHE_LEAF_KINDS`):
``pages_k``/``pages_v`` are registered there as kind "pool", so the beam
path rejects them explicitly instead of mis-expanding page rows as batch
rows.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

# Dense decode-cache leaf -> its paged pool counterpart. Shared by the
# prefill packing below and by pool initialization, so the mapping lives
# in exactly one place.
POOL_FOR_DENSE = {"cached_key": "pages_k", "cached_value": "pages_v"}


class PagedState(NamedTuple):
    """Per-step view of the slot table, passed into the decode program.

    ``page_table`` (max_slots, max_pages_per_slot) int32 — pool page ids in
    position order; entries past a slot's allocation are arbitrary (their
    gathered K/V is masked by ``lengths``).
    ``lengths`` (max_slots,) int32 — tokens already cached per slot; also
    the position of the token being decoded this step. 0 for dead slots.
    ``live`` (max_slots,) bool — whether the slot holds an active request;
    dead slots' writes are dropped (out-of-range index, ``mode="drop"``).
    """

    page_table: jax.Array
    lengths: jax.Array
    live: jax.Array


class PagedBlockState(NamedTuple):
    """Block variant of :class:`PagedState` for the serve fast path:
    every slot advances up to ``T`` tokens in one program call (suffix
    prefill after a radix prefix hit; speculative verify of a drafted
    block). Fields as in :class:`PagedState`, plus:

    ``n_new`` (max_slots,) int32 — how many of the ``T`` block columns
    are real for each slot; columns past it (and every column of a dead
    slot) have their pool writes dropped and their logits ignored.
    ``lengths`` is the BASE position: block column ``t`` of slot ``i``
    sits at absolute position ``lengths[i] + t``.
    """

    page_table: jax.Array
    lengths: jax.Array
    live: jax.Array
    n_new: jax.Array


def pages_needed(total_tokens: int, page_size: int) -> int:
    """Pages covering ``total_tokens`` positions (ceil division)."""
    return -(-int(total_tokens) // int(page_size))


def unseeded_pool(name: str):
    """init_fn for the models' ``self.variable("cache", "pages_k"/...)``:
    pool shapes are an ENGINE decision (num_pages x page_size), not a model
    one, so a paged decode whose cache collection lacks the pool is a
    wiring bug — fail loudly instead of inventing a shape."""
    def init():
        raise ValueError(
            f"paged decode needs the '{name}' pool seeded in "
            f"variables['cache'] by the serve engine "
            f"(serve/engine.py builds it via kv_cache.init_pools); "
            f"models never size pool memory themselves")
    return init


def paged_attention_step(q, k_new, v_new, pool_k, pool_v,
                         state: PagedState):
    """One decode step of paged attention for every slot at once.

    ``q`` (S, 1, heads, d); ``k_new``/``v_new`` (S, 1, kv_heads, d) — the
    current token's projections per slot (RoPE already applied for Llama).
    Writes each live slot's K/V at position ``lengths[i]`` into its page,
    then attends slot ``i``'s query over its own gathered pages.

    Returns ``(out, pool_k, pool_v)`` with ``out`` (S, 1, heads*d). Dead
    slots produce garbage rows (masked softmax over one arbitrary slot —
    finite, never NaN) that the engine discards; their writes are dropped
    via an out-of-range flat index with ``mode="drop"``.
    """
    num_pages, page_size, kvh, d = pool_k.shape
    slots = q.shape[0]
    heads = q.shape[2]
    rep = heads // kvh
    lengths = state.lengths

    # --- write: slot i's token lands at flat pool row
    #     page_table[i, lengths[i] // page_size] * page_size + offset ----
    page_col = lengths // page_size
    page_id = jnp.take_along_axis(state.page_table, page_col[:, None],
                                  axis=1)[:, 0]
    flat_idx = page_id * page_size + lengths % page_size
    flat_idx = jnp.where(state.live, flat_idx, num_pages * page_size)
    flat_k = pool_k.reshape(num_pages * page_size, kvh, d)
    flat_v = pool_v.reshape(num_pages * page_size, kvh, d)
    flat_k = flat_k.at[flat_idx].set(k_new[:, 0].astype(pool_k.dtype),
                                     mode="drop")
    flat_v = flat_v.at[flat_idx].set(v_new[:, 0].astype(pool_v.dtype),
                                     mode="drop")
    pool_k = flat_k.reshape(pool_k.shape)
    pool_v = flat_v.reshape(pool_v.shape)

    # --- gather: page-table order == ascending positions, so slot i's
    #     context is a contiguous [0, lengths[i]] prefix of the gather ----
    k_ctx = pool_k[state.page_table].reshape(slots, -1, kvh, d)
    v_ctx = pool_v[state.page_table].reshape(slots, -1, kvh, d)
    ctx = k_ctx.shape[1]  # max_pages_per_slot * page_size

    qg = q.reshape(slots, 1, kvh, rep, d)
    scores = jnp.einsum("bqgrd,bkgd->bgrqk", qg, k_ctx) * (d ** -0.5)
    # The query sits at position lengths[i] (just written): visible slots
    # are 0..lengths[i] inclusive — same rule as the dense branches'
    # ``arange <= idx``.
    visible = (jnp.arange(ctx)[None, :]
               <= lengths[:, None])[:, None, None, None, :]
    scores = jnp.where(visible, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, v_ctx)
    return out.reshape(slots, 1, heads * d), pool_k, pool_v


def paged_attention_block(q, k_new, v_new, pool_k, pool_v,
                          state: PagedBlockState):
    """Block of ``T`` tokens of paged attention for every slot at once.

    ``q`` (S, T, heads, d); ``k_new``/``v_new`` (S, T, kv_heads, d) —
    slot ``i``'s block column ``t`` is the token at absolute position
    ``lengths[i] + t`` (RoPE already applied for Llama). Writes columns
    ``t < n_new[i]`` of live slots into their pages, then attends each
    query over its slot's gathered pages with the causal rule
    ``visible <= lengths[i] + t``.

    Numerics are bitwise-identical to ``T`` sequential
    :func:`paged_attention_step` calls: per-(query, key) dot products
    are independent of the block width, and masked entries go through
    the same ``finfo(f32).min`` -> f32 softmax that underflows them to
    exactly 0.0 — the same argument that pins paged == dense token
    identity. Invalid columns produce finite garbage rows the engine
    ignores; their writes are dropped via an out-of-range flat index.

    Returns ``(out, pool_k, pool_v)`` with ``out`` (S, T, heads*d).
    """
    num_pages, page_size, kvh, d = pool_k.shape
    slots, t_block = q.shape[0], q.shape[1]
    heads = q.shape[2]
    rep = heads // kvh
    max_pages_per_slot = state.page_table.shape[1]

    # --- write: column t of slot i lands at absolute position
    #     lengths[i] + t; invalid columns (t >= n_new, dead slots) are
    #     dropped through an out-of-range flat index -------------------
    t_pos = state.lengths[:, None] + jnp.arange(t_block)[None, :]  # (S,T)
    valid = ((jnp.arange(t_block)[None, :] < state.n_new[:, None])
             & state.live[:, None])
    page_col = jnp.clip(t_pos // page_size, 0, max_pages_per_slot - 1)
    page_id = jnp.take_along_axis(state.page_table, page_col, axis=1)
    flat_idx = jnp.where(valid, page_id * page_size + t_pos % page_size,
                         num_pages * page_size)
    flat_k = pool_k.reshape(num_pages * page_size, kvh, d)
    flat_v = pool_v.reshape(num_pages * page_size, kvh, d)
    flat_k = flat_k.at[flat_idx].set(k_new.astype(pool_k.dtype),
                                     mode="drop")
    flat_v = flat_v.at[flat_idx].set(v_new.astype(pool_v.dtype),
                                     mode="drop")
    pool_k = flat_k.reshape(pool_k.shape)
    pool_v = flat_v.reshape(pool_v.shape)

    # --- gather + causal attention: query (i, t) sees positions
    #     0..lengths[i]+t inclusive, same rule as the step path --------
    k_ctx = pool_k[state.page_table].reshape(slots, -1, kvh, d)
    v_ctx = pool_v[state.page_table].reshape(slots, -1, kvh, d)
    ctx = k_ctx.shape[1]
    qg = q.reshape(slots, t_block, kvh, rep, d)
    scores = jnp.einsum("btgrd,bkgd->bgrtk", qg, k_ctx) * (d ** -0.5)
    visible = (jnp.arange(ctx)[None, None, :]
               <= t_pos[:, :, None])[:, None, None, :, :]
    scores = jnp.where(visible, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrtk,bkgd->btgrd", probs, v_ctx)
    return out.reshape(slots, t_block, heads * d), pool_k, pool_v


def init_pools(model, variables, *, num_pages: int, page_size: int):
    """Zeroed per-layer pools matching the model's dense cache tree.

    Discovers each attention layer's (kv_heads, head_dim, dtype) by
    ``jax.eval_shape`` over a dense decode prefill — no FLOPs, no memory —
    then mirrors every ``cached_key``/``cached_value`` leaf as a
    ``pages_k``/``pages_v`` pool of shape
    ``(num_pages, page_size, kv_heads, head_dim)`` at the same tree path.
    """
    from flax import traverse_util

    from distributeddeeplearning_tpu.models.generate import CACHE_LEAF_KINDS

    fresh = {k: v for k, v in variables.items() if k != "cache"}
    probe = jnp.zeros((1, 1), jnp.int32)
    _, shapes = jax.eval_shape(
        lambda v, ids: model.apply(v, ids, train=False, decode=True,
                                   mutable=["cache"]),
        fresh, probe)
    pools = {}
    for path, leaf in traverse_util.flatten_dict(shapes["cache"]).items():
        kind = CACHE_LEAF_KINDS.get(path[-1])
        if kind != "batched":
            continue  # scalars have no paged counterpart
        _, _, kvh, d = leaf.shape
        pools[path[:-1] + (POOL_FOR_DENSE[path[-1]],)] = jnp.zeros(
            (num_pages, page_size, kvh, d), leaf.dtype)
    if not pools:
        raise ValueError(
            f"{type(model).__name__} produced no dense K/V cache leaves "
            f"under decode=True — paged serving needs the GPT/Llama "
            f"decode mode")
    return traverse_util.unflatten_dict(pools)


def pack_prefill_cache(dense_cache, pools, *, page_row, plen):
    """Scatter one slot's dense prefill cache into its pages.

    ``dense_cache`` is the mutated "cache" collection of a batch-1 dense
    decode prefill (prompt right-padded to a bucket length L);
    ``page_row`` (max_pages_per_slot,) int32 is the slot's page-table row;
    ``plen`` (traced scalar ok) is the real prompt length — positions
    ``[0, plen)`` are written, pad positions ``[plen, L)`` are dropped via
    an out-of-range index, so one compiled program serves every prompt
    length within the bucket.

    Leaves are classified through CACHE_LEAF_KINDS: batched K/V leaves map
    to their pools, scalars (cache_index / position) are prefill-local and
    skipped, anything unknown raises.
    """
    from flax import traverse_util

    from distributeddeeplearning_tpu.models.generate import CACHE_LEAF_KINDS

    flat_dense = traverse_util.flatten_dict(dense_cache)
    flat_pools = traverse_util.flatten_dict(pools)
    for path, leaf in flat_dense.items():
        kind = CACHE_LEAF_KINDS.get(path[-1])
        if kind == "scalar":
            continue
        if kind != "batched":
            raise ValueError(
                f"prefill cache leaf {'/'.join(map(str, path))} is not "
                f"classified in CACHE_LEAF_KINDS — teach it there before "
                f"packing it into pages")
        dest = path[:-1] + (POOL_FOR_DENSE[path[-1]],)
        pool = flat_pools[dest]
        num_pages, page_size, kvh, d = pool.shape
        length = leaf.shape[1]
        t = jnp.arange(length)
        flat_idx = page_row[t // page_size] * page_size + t % page_size
        flat_idx = jnp.where(t < plen, flat_idx, num_pages * page_size)
        flat_pool = pool.reshape(num_pages * page_size, kvh, d)
        flat_pool = flat_pool.at[flat_idx].set(
            leaf[0].astype(pool.dtype), mode="drop")
        flat_pools[dest] = flat_pool.reshape(pool.shape)
    return traverse_util.unflatten_dict(flat_pools)


def clone_page_rows(pools, src, dst):
    """Copy one pool page row ``src`` -> ``dst`` across every pool leaf —
    the copy-on-write primitive. A page reachable at refcount > 1 (a radix
    prefix-cache node and/or another slot reads it) must never be written
    in place; the engine clones it into a private page first and maps the
    clone into the writing slot's page table. ``src``/``dst`` may be
    traced scalars, so one compiled program serves every copy."""
    from flax import traverse_util

    flat = traverse_util.flatten_dict(pools)
    for path, pool in flat.items():
        flat[path] = pool.at[dst].set(pool[src])
    return traverse_util.unflatten_dict(flat)


class PageAllocator:
    """Host-side refcounted page allocator: admission takes, retirement
    returns, and a page may be SHARED by several holders (slots mapping a
    cached prefix, radix-tree nodes). A page returns to the free list only
    when its last claim drops. Double-decref raises (a claim released
    twice means some holder's bookkeeping is wrong — left unchecked the
    page would be handed out while still mapped, corrupting both
    sequences silently — the one failure mode this class exists to make
    impossible), and in-place writes to a shared page are refused by
    :meth:`assert_writable` (copy-on-write via ``clone_page_rows``)."""

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"num_pages={num_pages}: need >= 1")
        self.num_pages = int(num_pages)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._ref: dict[int, int] = {}
        # Running totals for the tracing/anomaly layer: how often the
        # pool was asked, and how often it said no (an allocation-stall
        # signal that scalar occupancy gauges cannot distinguish from
        # healthy high utilization).
        self.alloc_calls = 0
        self.alloc_failures = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> Optional[list[int]]:
        """``n`` fresh page ids at refcount 1, or None (allocate-all-or-
        nothing) when the pool cannot cover the request — admission
        control's budget check."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        self.alloc_calls += 1
        if n > len(self._free):
            self.alloc_failures += 1
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    @property
    def held_pages(self) -> frozenset:
        """Pages currently allocated — the ground truth the engine's
        integrity check reconciles against the slots' page tables."""
        return frozenset(self._ref)

    def refcount(self, page) -> int:
        """Claims on ``page`` (0 = free)."""
        return self._ref.get(int(page), 0)

    def incref(self, pages) -> None:
        """Add one claim per page — a new holder (slot page-table row or
        radix-tree node) mapping an already-allocated page. Incref of a
        free page raises: sharing can only extend a live allocation."""
        for p in pages:
            p = int(p)
            if p not in self._ref:
                raise ValueError(
                    f"incref of page {p}: it is not currently allocated — "
                    f"only a live page can gain a second holder")
            self._ref[p] += 1

    def decref(self, pages) -> None:
        """Drop one claim per page; the page returns to the free list when
        its last claim drops. Decref of a free page raises (double-decref:
        some holder released a claim it no longer owns)."""
        for p in pages:
            p = int(p)
            if p not in self._ref:
                raise ValueError(
                    f"double-decref of page {p}: it is not currently "
                    f"allocated — a claim released twice would free a page "
                    f"another holder still maps")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)

    def free(self, pages) -> None:
        """Strict single-claim release — kept for non-victim paths where a
        second call IS a bug. ``decref`` semantics, double-free raises."""
        for p in pages:
            p = int(p)
            if p not in self._ref:
                raise ValueError(
                    f"double-free of page {p}: it is not currently "
                    f"allocated — a page on two page tables would corrupt "
                    f"both slots' K/V")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)

    def release(self, pages) -> int:
        """Idempotent variant of ``free`` for victim retirement: drops one
        claim per page still allocated, silently skipping free ones, and
        returns how many claims were actually dropped. A request that was
        preempted (pages freed, re-queued) and later shed/cancelled walks
        this path — its second cleanup must be a no-op, not a double-free
        crash. Holders must clear their page lists after releasing (the
        engine's ``entry.pages = []`` pattern): idempotency is per-claim,
        and a stale list re-released after the page found a NEW holder
        would steal that holder's claim."""
        freed = 0
        for p in pages:
            p = int(p)
            if p in self._ref:
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    del self._ref[p]
                    self._free.append(p)
                freed += 1
        return freed

    def assert_writable(self, pages) -> None:
        """Raise unless every page is exclusively held (refcount == 1).
        The engine calls this on the pages a program is about to write
        in place: a write to a shared page would corrupt the cached
        prefix under every OTHER holder — the copy-on-write hazard this
        check makes loud (clone first via ``clone_page_rows``)."""
        shared = sorted(p for p in (int(p) for p in pages)
                        if self._ref.get(p, 0) > 1)
        if shared:
            raise RuntimeError(
                f"write to shared page(s) {shared} (refcount > 1): "
                f"in-place writes are only legal at refcount 1 — "
                f"copy-on-write the page first (kv_cache.clone_page_rows)")

    def check_leaks(self, owned_pages) -> None:
        """Raise unless allocator accounting balances exactly against the
        claims of live holders: ``owned_pages`` is a MULTISET (each slot
        contributes its page-table row, the radix tree one entry per
        node), and each page's multiplicity must equal its refcount;
        free + held == num_pages. Called at engine shutdown and after
        every chaos soak — a leak here means a claim was dropped on the
        floor (or a page double-owned without a matching share) and the
        pool will eventually starve admission."""
        counts: dict[int, int] = {}
        for p in owned_pages:
            p = int(p)
            counts[p] = counts.get(p, 0) + 1
        over = sorted(p for p, c in counts.items()
                      if c > self._ref.get(p, 0) and p in self._ref)
        if over:
            raise RuntimeError(
                f"page-table corruption: page(s) {over} appear on more "
                f"live tables than their refcount allows — an unshared "
                f"page on two slots' tables corrupts both")
        phantom = sorted(p for p in counts if p not in self._ref)
        leaked = sorted(p for p, c in self._ref.items()
                        if counts.get(p, 0) < c)
        if leaked or phantom:
            raise RuntimeError(
                f"KV page leak: allocator refcounts {dict(self._ref)} vs "
                f"live claims {counts} "
                f"(leaked={leaked}, phantom={phantom})")
        if len(self._free) + len(self._ref) != self.num_pages:
            raise RuntimeError(
                f"allocator accounting broken: free={len(self._free)} + "
                f"held={len(self._ref)} != num_pages={self.num_pages}")


class _RadixNode:
    """One radix-tree node: owns exactly ONE pool page whose K/V covers a
    full ``page_size``-token chunk, keyed by that chunk's token ids."""

    __slots__ = ("key", "page", "children", "parent", "last_used")

    def __init__(self, key, page, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: dict = {}
        self.last_used = 0


class RadixPrefixCache:
    """Token-prefix -> KV-page radix tree over the shared page pool.

    Nodes are FULL pages only (a chunk of exactly ``page_size`` token
    ids); a node holds one allocator claim on its page, so a retired
    slot's prefix pages survive retirement inside the tree (refcount
    drops to the tree's 1, not to 0) and the next request with the same
    prompt prefix maps them instead of recomputing prefill. The partial
    trailing page of a fully-cached prompt is never shared in place —
    the engine copy-on-writes it (``clone_page_rows``).

    Eviction is LRU over leaf nodes whose page has no holder besides the
    tree (refcount == 1): evicting frees the page back to the allocator,
    children before parents (a leaf's parent becomes evictable next
    round), and never touches a page some live slot still maps — so the
    allocator's all-or-nothing budget check and ``check_leaks()`` drain
    gate keep working unchanged.
    """

    def __init__(self, allocator: PageAllocator, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size={page_size}: need >= 1")
        self.allocator = allocator
        self.page_size = int(page_size)
        self._root = _RadixNode(None, None, None)
        self._tick = 0
        self.evictions = 0

    def _chunks(self, tokens):
        ps = self.page_size
        for j in range(len(tokens) // ps):
            yield tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])

    def match(self, tokens) -> tuple[int, list[int]]:
        """Longest cached full-page prefix of ``tokens``: returns
        ``(matched_tokens, pages)`` with ``matched_tokens`` a multiple of
        ``page_size`` and ``pages`` the node pages in position order.
        Touches every node on the path (LRU recency)."""
        self._tick += 1
        node = self._root
        pages: list[int] = []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_used = self._tick
            pages.append(child.page)
            node = child
        return len(pages) * self.page_size, pages

    def insert(self, tokens, pages) -> int:
        """Register the full pages of a freshly-prefilled sequence:
        ``pages[j]`` must hold the K/V of positions
        ``[j*page_size, (j+1)*page_size)``, all of them written (only
        chunks with ``page_size*(j+1) <= len(tokens)`` are considered).
        New nodes take one allocator claim on their page; a chunk already
        cached (under the same or a different page) is left as is.
        Returns how many nodes were created."""
        self._tick += 1
        node = self._root
        created = 0
        for j, chunk in enumerate(self._chunks(tokens)):
            child = node.children.get(chunk)
            if child is None:
                page = int(pages[j])
                self.allocator.incref([page])
                child = _RadixNode(chunk, page, node)
                node.children[chunk] = child
                created += 1
            child.last_used = self._tick
            node = child
        return created

    def _evictable_leaves(self) -> list:
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.allocator.refcount(n.page) == 1:
                out.append(n)
        return out

    def evict(self, need: int) -> int:
        """Free at least ``need`` pages back to the allocator by dropping
        LRU tree-only (refcount-1) leaves, cascading into parents as they
        become leaves. Returns how many pages were actually freed (may be
        short when live slots pin the rest)."""
        freed = 0
        while freed < need:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for n in leaves:
                if freed >= need:
                    break
                self.allocator.decref([n.page])
                del n.parent.children[n.key]
                self.evictions += 1
                freed += 1
        return freed

    def evictable_pages(self) -> int:
        """Pages the tree could free on demand: nodes whose whole subtree
        is tree-only (refcount 1) — what admission control may count as
        available on top of the allocator's free list."""
        def count(node) -> tuple[int, bool]:
            total, all_free = 0, True
            for c in node.children.values():
                sub, ok = count(c)
                total += sub
                all_free &= ok
            if node is self._root:
                return total, all_free
            if all_free and self.allocator.refcount(node.page) == 1:
                return total + 1, True
            return total, False
        return count(self._root)[0]

    def owned_pages(self) -> list[int]:
        """One entry per node — the tree's contribution to the engine's
        ``check_leaks`` claim multiset."""
        out: list[int] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            out.append(n.page)
            stack.extend(n.children.values())
        return out

    def num_nodes(self) -> int:
        return len(self.owned_pages())
