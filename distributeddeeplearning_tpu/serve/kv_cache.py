"""Paged KV cache: fixed-size pages from a preallocated pool.

The dense decode cache (models/gpt.py, models/llama.py) allocates
``(B, max_position, heads, d)`` per request — memory scales with
batch x the STATIC position bound even when most slots hold short, mostly
finished sequences. Serving wants memory that scales with LIVE tokens:

- one pool per attention layer, ``pages_k``/``pages_v`` of shape
  ``(num_pages, page_size, kv_heads, head_dim)``, allocated once by the
  engine and carried through the decode program as flax "cache" leaves
  (donated, so XLA updates them in place);
- a per-slot **page table** ``(max_slots, max_pages_per_slot)`` mapping
  each slot's token range to pool pages in position order — entry ``j``
  covers positions ``[j*page_size, (j+1)*page_size)``;
- a host-side free list (:class:`PageAllocator`): admission takes pages,
  retirement returns them, so a retiring slot's memory is reusable on the
  very next step without any copying.

Numerics match the dense decode branches exactly where it matters: same
``d**-0.5`` scale, same f32 softmax over ``finfo(f32).min``-masked dead
slots, and the gather is in page-table order == ascending positions, so a
greedy argmax over paged logits equals the dense one (tests pin
token-identity end to end).

Leaf naming follows ``models/generate.py``'s taught-leaf scheme
(:data:`~distributeddeeplearning_tpu.models.generate.CACHE_LEAF_KINDS`):
``pages_k``/``pages_v`` are registered there as kind "pool", so the beam
path rejects them explicitly instead of mis-expanding page rows as batch
rows.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

# Dense decode-cache leaf -> its paged pool counterpart. Shared by the
# prefill packing below and by pool initialization, so the mapping lives
# in exactly one place.
POOL_FOR_DENSE = {"cached_key": "pages_k", "cached_value": "pages_v"}


class PagedState(NamedTuple):
    """Per-step view of the slot table, passed into the decode program.

    ``page_table`` (max_slots, max_pages_per_slot) int32 — pool page ids in
    position order; entries past a slot's allocation are arbitrary (their
    gathered K/V is masked by ``lengths``).
    ``lengths`` (max_slots,) int32 — tokens already cached per slot; also
    the position of the token being decoded this step. 0 for dead slots.
    ``live`` (max_slots,) bool — whether the slot holds an active request;
    dead slots' writes are dropped (out-of-range index, ``mode="drop"``).
    """

    page_table: jax.Array
    lengths: jax.Array
    live: jax.Array


def pages_needed(total_tokens: int, page_size: int) -> int:
    """Pages covering ``total_tokens`` positions (ceil division)."""
    return -(-int(total_tokens) // int(page_size))


def unseeded_pool(name: str):
    """init_fn for the models' ``self.variable("cache", "pages_k"/...)``:
    pool shapes are an ENGINE decision (num_pages x page_size), not a model
    one, so a paged decode whose cache collection lacks the pool is a
    wiring bug — fail loudly instead of inventing a shape."""
    def init():
        raise ValueError(
            f"paged decode needs the '{name}' pool seeded in "
            f"variables['cache'] by the serve engine "
            f"(serve/engine.py builds it via kv_cache.init_pools); "
            f"models never size pool memory themselves")
    return init


def paged_attention_step(q, k_new, v_new, pool_k, pool_v,
                         state: PagedState):
    """One decode step of paged attention for every slot at once.

    ``q`` (S, 1, heads, d); ``k_new``/``v_new`` (S, 1, kv_heads, d) — the
    current token's projections per slot (RoPE already applied for Llama).
    Writes each live slot's K/V at position ``lengths[i]`` into its page,
    then attends slot ``i``'s query over its own gathered pages.

    Returns ``(out, pool_k, pool_v)`` with ``out`` (S, 1, heads*d). Dead
    slots produce garbage rows (masked softmax over one arbitrary slot —
    finite, never NaN) that the engine discards; their writes are dropped
    via an out-of-range flat index with ``mode="drop"``.
    """
    num_pages, page_size, kvh, d = pool_k.shape
    slots = q.shape[0]
    heads = q.shape[2]
    rep = heads // kvh
    lengths = state.lengths

    # --- write: slot i's token lands at flat pool row
    #     page_table[i, lengths[i] // page_size] * page_size + offset ----
    page_col = lengths // page_size
    page_id = jnp.take_along_axis(state.page_table, page_col[:, None],
                                  axis=1)[:, 0]
    flat_idx = page_id * page_size + lengths % page_size
    flat_idx = jnp.where(state.live, flat_idx, num_pages * page_size)
    flat_k = pool_k.reshape(num_pages * page_size, kvh, d)
    flat_v = pool_v.reshape(num_pages * page_size, kvh, d)
    flat_k = flat_k.at[flat_idx].set(k_new[:, 0].astype(pool_k.dtype),
                                     mode="drop")
    flat_v = flat_v.at[flat_idx].set(v_new[:, 0].astype(pool_v.dtype),
                                     mode="drop")
    pool_k = flat_k.reshape(pool_k.shape)
    pool_v = flat_v.reshape(pool_v.shape)

    # --- gather: page-table order == ascending positions, so slot i's
    #     context is a contiguous [0, lengths[i]] prefix of the gather ----
    k_ctx = pool_k[state.page_table].reshape(slots, -1, kvh, d)
    v_ctx = pool_v[state.page_table].reshape(slots, -1, kvh, d)
    ctx = k_ctx.shape[1]  # max_pages_per_slot * page_size

    qg = q.reshape(slots, 1, kvh, rep, d)
    scores = jnp.einsum("bqgrd,bkgd->bgrqk", qg, k_ctx) * (d ** -0.5)
    # The query sits at position lengths[i] (just written): visible slots
    # are 0..lengths[i] inclusive — same rule as the dense branches'
    # ``arange <= idx``.
    visible = (jnp.arange(ctx)[None, :]
               <= lengths[:, None])[:, None, None, None, :]
    scores = jnp.where(visible, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, v_ctx)
    return out.reshape(slots, 1, heads * d), pool_k, pool_v


def init_pools(model, variables, *, num_pages: int, page_size: int):
    """Zeroed per-layer pools matching the model's dense cache tree.

    Discovers each attention layer's (kv_heads, head_dim, dtype) by
    ``jax.eval_shape`` over a dense decode prefill — no FLOPs, no memory —
    then mirrors every ``cached_key``/``cached_value`` leaf as a
    ``pages_k``/``pages_v`` pool of shape
    ``(num_pages, page_size, kv_heads, head_dim)`` at the same tree path.
    """
    from flax import traverse_util

    from distributeddeeplearning_tpu.models.generate import CACHE_LEAF_KINDS

    fresh = {k: v for k, v in variables.items() if k != "cache"}
    probe = jnp.zeros((1, 1), jnp.int32)
    _, shapes = jax.eval_shape(
        lambda v, ids: model.apply(v, ids, train=False, decode=True,
                                   mutable=["cache"]),
        fresh, probe)
    pools = {}
    for path, leaf in traverse_util.flatten_dict(shapes["cache"]).items():
        kind = CACHE_LEAF_KINDS.get(path[-1])
        if kind != "batched":
            continue  # scalars have no paged counterpart
        _, _, kvh, d = leaf.shape
        pools[path[:-1] + (POOL_FOR_DENSE[path[-1]],)] = jnp.zeros(
            (num_pages, page_size, kvh, d), leaf.dtype)
    if not pools:
        raise ValueError(
            f"{type(model).__name__} produced no dense K/V cache leaves "
            f"under decode=True — paged serving needs the GPT/Llama "
            f"decode mode")
    return traverse_util.unflatten_dict(pools)


def pack_prefill_cache(dense_cache, pools, *, page_row, plen):
    """Scatter one slot's dense prefill cache into its pages.

    ``dense_cache`` is the mutated "cache" collection of a batch-1 dense
    decode prefill (prompt right-padded to a bucket length L);
    ``page_row`` (max_pages_per_slot,) int32 is the slot's page-table row;
    ``plen`` (traced scalar ok) is the real prompt length — positions
    ``[0, plen)`` are written, pad positions ``[plen, L)`` are dropped via
    an out-of-range index, so one compiled program serves every prompt
    length within the bucket.

    Leaves are classified through CACHE_LEAF_KINDS: batched K/V leaves map
    to their pools, scalars (cache_index / position) are prefill-local and
    skipped, anything unknown raises.
    """
    from flax import traverse_util

    from distributeddeeplearning_tpu.models.generate import CACHE_LEAF_KINDS

    flat_dense = traverse_util.flatten_dict(dense_cache)
    flat_pools = traverse_util.flatten_dict(pools)
    for path, leaf in flat_dense.items():
        kind = CACHE_LEAF_KINDS.get(path[-1])
        if kind == "scalar":
            continue
        if kind != "batched":
            raise ValueError(
                f"prefill cache leaf {'/'.join(map(str, path))} is not "
                f"classified in CACHE_LEAF_KINDS — teach it there before "
                f"packing it into pages")
        dest = path[:-1] + (POOL_FOR_DENSE[path[-1]],)
        pool = flat_pools[dest]
        num_pages, page_size, kvh, d = pool.shape
        length = leaf.shape[1]
        t = jnp.arange(length)
        flat_idx = page_row[t // page_size] * page_size + t % page_size
        flat_idx = jnp.where(t < plen, flat_idx, num_pages * page_size)
        flat_pool = pool.reshape(num_pages * page_size, kvh, d)
        flat_pool = flat_pool.at[flat_idx].set(
            leaf[0].astype(pool.dtype), mode="drop")
        flat_pools[dest] = flat_pool.reshape(pool.shape)
    return traverse_util.unflatten_dict(flat_pools)


class PageAllocator:
    """Host-side free-list page allocator: admission takes, retirement
    returns, double-free raises (a page on two slots' tables corrupts both
    sequences silently — the one failure mode this class exists to make
    impossible)."""

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"num_pages={num_pages}: need >= 1")
        self.num_pages = int(num_pages)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._held: set[int] = set()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> Optional[list[int]]:
        """``n`` page ids, or None (allocate-all-or-nothing) when the pool
        cannot cover the request — admission control's budget check."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._held.update(pages)
        return pages

    @property
    def held_pages(self) -> frozenset:
        """Pages currently allocated — the ground truth the engine's
        integrity check reconciles against the slots' page tables."""
        return frozenset(self._held)

    def free(self, pages) -> None:
        for p in pages:
            if p not in self._held:
                raise ValueError(
                    f"double-free of page {p}: it is not currently "
                    f"allocated — a page on two page tables would corrupt "
                    f"both slots' K/V")
            self._held.discard(p)
            self._free.append(p)

    def release(self, pages) -> int:
        """Idempotent variant of ``free`` for victim retirement: frees only
        the pages still held, silently skipping the rest, and returns how
        many were actually returned. A request that was preempted (pages
        freed, re-queued) and later shed/cancelled walks this path — its
        second cleanup must be a no-op, not a double-free crash."""
        freed = 0
        for p in pages:
            if p in self._held:
                self._held.discard(p)
                self._free.append(p)
                freed += 1
        return freed

    def check_leaks(self, owned_pages) -> None:
        """Raise unless allocator accounting balances exactly against the
        pages owned by live slots: every held page is owned by exactly one
        slot, every owned page is held, and free + held == num_pages. Called
        at engine shutdown and after every chaos soak — a leak here means a
        page was dropped on the floor (or double-owned) and the pool will
        eventually starve admission."""
        owned = list(owned_pages)
        if len(owned) != len(set(owned)):
            dupes = sorted({p for p in owned if owned.count(p) > 1})
            raise RuntimeError(
                f"page-table corruption: page(s) {dupes} appear on more "
                f"than one live slot's table")
        if set(owned) != self._held:
            leaked = sorted(self._held - set(owned))
            phantom = sorted(set(owned) - self._held)
            raise RuntimeError(
                f"KV page leak: allocator holds {sorted(self._held)} but "
                f"live slots own {sorted(set(owned))} "
                f"(leaked={leaked}, phantom={phantom})")
        if len(self._free) + len(self._held) != self.num_pages:
            raise RuntimeError(
                f"allocator accounting broken: free={len(self._free)} + "
                f"held={len(self._held)} != num_pages={self.num_pages}")
