"""One supervised serve replica: an Engine wrapped in the file protocol
``launch.run_serve`` speaks.

The supervisor (launch.py serve mode) owns the request trace and the
frontend view of every stream; replicas own a model and a paged KV pool.
The wire protocol is deliberately plain files, chosen for the same reason
the flight recorder is fsync'd JSONL — every piece must survive a replica
dying at ANY instruction with no cleanup:

- ``<workdir>/config.json``        — ServeConfig fields (shared by all
  replicas; same fingerprint -> shared AOT executable cache -> a restarted
  replica warm-boots with zero retraces).
- ``<workdir>/inbox/r<I>.a<A>/*.json`` — one file per dispatched request:
  ``{uid, tenant, prompt, max_new_tokens, prefix}``. ``prefix`` is the
  token stream the supervisor already received for a re-dispatched victim;
  the replica folds it into the prompt (``Engine`` prefix-folding), so the
  continuation is token-identical to the uninterrupted run.
- ``<workdir>/events/r<I>.jsonl``  — append-only stream back: ``accepted``
  / ``token`` / ``finished`` / ``failed`` / ``drained``. Flushed per step:
  an OS-buffered line survives SIGKILL of the writer, so the supervisor's
  view after a replica loss is exactly "everything up to the last completed
  step" — tokens emitted by the dying step were never reported and are
  regenerated identically on replay.
- ``<workdir>/stop.r<I>``          — drain sentinel: finish live work, run
  the shutdown leak gate, exit 0. A replica that leaked pages exits
  nonzero here — leaks are loud, not logged.

Heartbeats (``DDL_HEARTBEAT_DIR``/``DDL_PROCESS_ID``) and the flight
recorder (``DDL_FLIGHT_DIR``) arm exactly as training children do, so the
supervisor reuses the launcher's staleness clock and attribution. Fault
plans arrive via ``DDL_FAULT_PLAN`` (the supervisor's per-replica
injection), resolved attempt-scoped inside the Engine.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _emit(fh, obj: dict) -> None:
    fh.write(json.dumps(obj) + "\n")
    fh.flush()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="supervised serve replica (spawned by launch.py serve "
                    "mode; not a user entry point)")
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--replica", type=int, required=True)
    parser.add_argument("--poll-s", type=float, default=0.02,
                        help="idle inbox poll interval")
    args = parser.parse_args(argv)
    wd, rid = args.workdir, args.replica

    from distributeddeeplearning_tpu.observability import (anomaly, flight,
                                                           health, telemetry)
    from distributeddeeplearning_tpu.robustness import faults
    from distributeddeeplearning_tpu.serve import engine as enginelib

    with open(os.path.join(wd, "config.json"), encoding="utf-8") as f:
        d = json.load(f)
    # JSON turned the bucket tuple into a list; normalize it back so the
    # serve fingerprint (and with it the shared AOT executable cache key)
    # is byte-identical to an in-process Engine built from the same
    # ServeConfig — warm restarts depend on that exact match.
    if "prefill_buckets" in d:
        d["prefill_buckets"] = tuple(d["prefill_buckets"])
    cfg = enginelib.ServeConfig(**d)

    flight.configure_from_env(host=rid)
    # Tracing destination rides DDL_TRACE_DIR from the supervisor; the
    # replica id is the Chrome pid, so every replica gets its own named
    # process track in the merged trace. Must happen BEFORE the engine
    # is built — the engine resolves its tracer at construction.
    tele = telemetry.configure_from_env(
        process_index=rid, process_name=f"serve-replica-{rid}")
    attempt = faults.current_attempt()
    flight.get().record("serve_replica_start", replica=rid, attempt=attempt)
    hb = health.HeartbeatWriter.from_env()

    eng = enginelib.Engine(cfg)
    aot = eng.warmup()
    if hb is not None:
        hb.beat(0)

    # Per-attempt inbox: a restarted replica must NOT replay its
    # predecessor's inbox — the supervisor already re-dispatched those
    # victims (possibly to this very replica, into the new inbox).
    inbox = os.path.join(wd, "inbox", f"r{rid}.a{attempt}")
    events_path = os.path.join(wd, "events", f"r{rid}.jsonl")
    stop_path = os.path.join(wd, f"stop.r{rid}")
    os.makedirs(inbox, exist_ok=True)
    os.makedirs(os.path.dirname(events_path), exist_ok=True)
    ev = open(events_path, "a", encoding="utf-8")
    _emit(ev, {"ev": "ready", "replica": rid, "attempt": attempt,
               "aot": aot})

    seen: set = set()
    reqs: dict = {}    # supervisor uid -> engine Request
    sent: dict = {}    # supervisor uid -> tokens already reported
    closed: set = set()

    def pull_inbox() -> None:
        try:
            names = sorted(os.listdir(inbox))
        except OSError:
            return
        for name in names:
            if not name.endswith(".json") or name in seen:
                continue
            seen.add(name)
            with open(os.path.join(inbox, name), encoding="utf-8") as f:
                d = json.load(f)
            uid = int(d["uid"])
            prefix = [int(t) for t in (d.get("prefix") or [])]
            # The supervisor's GLOBAL uid is the trace/flow id (engine
            # uids are replica-local): a re-dispatched victim keeps ONE
            # flow id across both replica processes, which is what links
            # its spans in the merged trace.
            req = eng.submit(
                [int(t) for t in d["prompt"]] + prefix,
                max_new_tokens=int(d["max_new_tokens"]) - len(prefix),
                tenant=d.get("tenant", "default"),
                trace_id=int(d.get("trace", uid)),
                resumed=bool(d.get("redispatch")) or bool(prefix))
            reqs[uid], sent[uid] = req, 0
            _emit(ev, {"ev": "accepted", "uid": uid, "replica": rid,
                       "resumed_from": len(prefix)})

    def report_progress() -> None:
        for uid, req in reqs.items():
            n = len(req.tokens)
            if n > sent[uid]:
                _emit(ev, {"ev": "token", "uid": uid, "step": eng.steps,
                           "tokens": [int(t)
                                      for t in req.tokens[sent[uid]:n]]})
                sent[uid] = n
            if uid in closed:
                continue
            if req.failed is not None:
                closed.add(uid)
                _emit(ev, {"ev": "failed", "uid": uid, "step": eng.steps,
                           "reason": req.failed})
            elif req.finished_s is not None:
                closed.add(uid)
                _emit(ev, {"ev": "finished", "uid": uid, "step": eng.steps,
                           "tokens": n})

    # Attribution-fed anomaly watch (queue-wait regression, allocation
    # stall, decode stall) rides the same cadence as the trace export;
    # both exist only when the supervisor asked for tracing, so an
    # untraced replica's step loop is unchanged.
    det = anomaly.AnomalyDetector() if tele is not None else None
    det_last = (0, 0, 0, 0, 0)

    while True:
        pull_inbox()
        if eng.idle:
            if os.path.exists(stop_path):
                break
            if hb is not None:
                hb.beat(eng.steps)
            time.sleep(args.poll_s)
            continue
        eng.step()
        if hb is not None:
            hb.beat(eng.steps)
        report_progress()
        if tele is not None:
            # Export every step: the merge in telemetry.export is what
            # makes a SIGKILL'd replica lose at most the dying step's
            # spans — the pre-kill life of a later re-dispatched request
            # survives into the merged trace.
            tele.export()
            if det is not None and eng.steps % 16 == 0:
                cur = (eng.sheds, eng.deadline_misses, len(eng.finished),
                       eng.spec_proposed, eng.spec_accepted)
                diff = [c - p for c, p in zip(cur, det_last)]
                det_last = cur
                sig = (eng.tracer.interval_signals()
                       if eng.tracer is not None else {})
                anomaly.report(
                    det.update_serve(
                        eng.steps, queue_depth=len(eng.waiting),
                        sheds=diff[0], deadline_misses=diff[1],
                        finished=diff[2], spec_proposed=diff[3],
                        spec_accepted=diff[4],
                        queue_wait_s=sig.get("queue_wait_s"),
                        alloc_stall_s=sig.get("alloc_stall_s"),
                        decode_tick_s=sig.get("decode_tick_s")),
                    flight_rec=flight.get(), tele=tele)

    try:
        eng.shutdown()  # raises on a page leak -> nonzero exit, by design
    finally:
        if tele is not None:
            tele.export()
    # Fast-path counters ride the drain event so the supervisor (and
    # doctor's serve report) can aggregate prefix reuse and speculative
    # acceptance across replicas without scraping flight logs.
    _emit(ev, {"ev": "drained", "replica": rid, "steps": eng.steps,
               "finished": len(eng.finished), "failed": len(eng.failed),
               "prefix_hits": eng.prefix_hits,
               "prefix_misses": eng.prefix_misses,
               "prefix_tokens_reused": eng.prefix_tokens_reused,
               "prefix_evictions": (eng.prefix.evictions
                                    if eng.prefix is not None else 0),
               "cow_copies": eng.cow_copies,
               "spec_rounds": eng.spec_rounds,
               "spec_proposed": eng.spec_proposed,
               "spec_accepted": eng.spec_accepted})
    return 0


if __name__ == "__main__":
    sys.exit(main())
