"""Per-request distributed tracing + TTFT attribution for the serve stack.

The training side has always had Chrome-trace phase spans (the Horovod
timeline idea — ``observability/telemetry.py``); serve observability
stopped at scalar gauges. This module closes the gap: every request
carries a trace id from arrival and emits a span tree through the whole
serve path — admission-queue wait, scheduler decisions, page allocation
and radix prefix hit/miss, prefill, every decode tick it participates in,
speculative draft/verify, preempt/requeue/deadline events — and, when a
replica dies, Chrome flow events link the re-dispatched request's spans
across replica processes so the merged timeline shows one request's life
across the fleet.

Two layers, one discipline:

1. **Spans** land in the existing telemetry ring buffer (Chrome-trace
   JSON), so every trace tool (summarize_trace, postmortem, Perfetto)
   keeps working. Every emitted ``serve:*`` name must be registered in
   :data:`REGISTERED_PHASES` — enforced both by a ddl-lint rule
   (``serve-span-registered``) and a tier-1 schema test, so a new code
   path cannot silently escape attribution.
2. **Attribution** decomposes each request's TTFT and total latency into
   the :data:`COMPONENTS` — ``queue`` / ``admission_stall`` / ``prefill``
   / ``interference`` / ``decode`` — by *moving a per-request mark*
   through monotonic time: every accounting point accrues the elapsed
   interval into exactly one component, so the components sum to the
   measured latency BY CONSTRUCTION (float addition error only; the
   bench asserts < 1 ms). Classification of waiting time comes from the
   scheduler's per-request non-admission reason (``Plan.reasons``):
   resource starvation (``no_pages`` / allocator race) is an admission
   stall; policy holds (``no_slot`` / ``tenant_cap`` / ``backoff`` /
   ``priority`` — the engine is busy making progress for *other*
   requests) are scheduler interference; everything uncovered (idle
   gaps between steps, pre-first-sighting) is queue time.

**The disabled path is a true no-op.** The engine holds ``tracer = None``
when telemetry is off at construction; every instrumentation site is
behind one ``is not None`` check, no :class:`RequestTrace` objects are
ever allocated, and a tier-1 test pins zero allocations per decode tick
attributable to this module.

Trace/flow ids: in-process the request uid; under ``launch.run_serve``
the supervisor's global uid rides the inbox payload (``"trace"``), so a
request re-dispatched after a replica death keeps ONE id across both
replica processes — its admission on the first replica opens the flow
(``ph: "s"``), the resumed admission on the survivor continues it
(``"t"``), and retirement closes it (``"f"``).

Pure stdlib on purpose (imports only telemetry/metrics, themselves pure
stdlib): the lint layer and jax-free tools import the registry.
"""

from __future__ import annotations

from typing import Any, Optional

from distributeddeeplearning_tpu.observability import metrics, telemetry

# ---------------------------------------------------------------------------
# Span-name registry — the schema every serve emission must come from.
# ---------------------------------------------------------------------------

#: Complete ("X") spans the serve stack emits.
SERVE_SPANS = (
    "serve:scheduler_plan",   # one per engine step: the Plan computation
    "serve:page_alloc",       # admission: radix walk + incref + alloc
    "serve:cow_copy",         # copy-on-write clone of a shared page
    "serve:prefill",          # dense or block prefill to first token
    "serve:decode",           # one engine decode dispatch (all slots)
    "serve:decode_tick",      # per-slot view of one decode/spec round
    "serve:spec_draft",       # drafter proposal rounds
    "serve:spec_verify",      # batched target verify forward
)

#: Instant ("i") markers.
SERVE_INSTANTS = (
    "serve:submit",           # request entered the wait queue
    "serve:prefix_match",     # radix cache hit/miss at admission
    "serve:preempt",          # victim evicted back to the queue
    "serve:requeue",          # admission raced the allocator; retried
    "serve:shed",             # brownout / retries_exhausted failure
    "serve:deadline_miss",    # hard deadline blown
    "serve:attribution",      # final per-request latency decomposition
    "serve:dispatch",         # supervisor: request dropped in an inbox
    "serve:redispatch",       # supervisor: victim re-sent to a survivor
    "serve:replica_lost",     # supervisor: a replica died mid-flight
)

#: Async ("b"/"e") request-lifetime track and the cross-process flow.
SERVE_TRACKS = (
    "serve:request",          # async span: arrival -> retire/fail
    "serve:request_flow",     # flow: links one request across processes
)

REGISTERED_PHASES = frozenset(SERVE_SPANS + SERVE_INSTANTS + SERVE_TRACKS)

#: Attribution components, exhaustive by construction: every accrued
#: interval lands in exactly one, and their sum equals the measured
#: latency. Order is the report order (arrival -> first token -> done).
COMPONENTS = ("queue", "admission_stall", "prefill", "interference",
              "decode")

#: Scheduler non-admission reasons that mean RESOURCE starvation (the
#: pool cannot cover the request) rather than policy/priority.
STALL_REASONS = frozenset({"no_pages", "alloc_race"})

#: Chrome tid base for per-slot decode-tick tracks: slot k renders on
#: tid PER_SLOT_TID + k, a stable lane per slot instead of the host
#: thread id (which would interleave every slot onto one row).
PER_SLOT_TID = 0x5150


def component_for_reason(reason: str) -> str:
    """Map a scheduler non-admission reason to the waiting component it
    charges: resource starvation -> ``admission_stall``; policy holds
    (slots busy with other requests, tenant cap, retry backoff,
    priority) -> ``interference``."""
    return "admission_stall" if reason in STALL_REASONS else "interference"


class RequestTrace:
    """Per-request attribution state: one trace id, one moving mark, one
    component accumulator. Allocated only when tracing is on."""

    __slots__ = ("trace_id", "comp", "ttft_comp", "last_mark_s",
                 "forced_reason", "resumed_origin", "opened", "done")

    def __init__(self, trace_id: int, arrival_s: float,
                 resumed_origin: bool = False):
        self.trace_id = int(trace_id)
        self.comp = {k: 0.0 for k in COMPONENTS}
        self.ttft_comp: Optional[dict] = None
        self.last_mark_s = float(arrival_s)
        self.forced_reason: Optional[str] = None  # alloc_race override
        # True when this engine-local request CONTINUES a flow another
        # process opened (supervisor re-dispatch after replica loss).
        self.resumed_origin = bool(resumed_origin)
        self.opened = False   # flow "s"/"t" emitted at first admission
        self.done = False

    def accrue(self, t_s: float, component: str) -> None:
        """Charge ``[last_mark, t_s]`` to ``component`` and advance the
        mark — the one mutation that keeps the decomposition exact."""
        dt = t_s - self.last_mark_s
        if dt > 0.0:
            self.comp[component] += dt
        self.last_mark_s = t_s


class ServeTracer:
    """The engine's tracing/attribution sidecar.

    Built by :func:`maybe_tracer` only when the telemetry singleton is
    enabled at engine construction; a ``None`` tracer IS the disabled
    path. All methods take explicit monotonic timestamps from the
    engine's injectable clock, so fake-clock tests get exact sums.
    """

    def __init__(self, tele: telemetry.Telemetry):
        self.tele = tele
        # Interval accumulators for the anomaly cadence
        # (queue-wait regression / allocation stall / decode stall).
        self._iv_finished = 0
        self._iv_queue_wait = 0.0
        self._iv_alloc_stall = 0.0
        self._iv_decode_sum = 0.0
        self._iv_decode_n = 0

    # -- request lifecycle -------------------------------------------------

    def on_submit(self, req, trace_id: Optional[int],
                  resumed: bool = False) -> None:
        rt = RequestTrace(req.uid if trace_id is None else trace_id,
                          req.arrival_s, resumed_origin=resumed)
        req.trace = rt
        self.tele.instant("serve:submit", request=req.uid,
                          trace=rt.trace_id, tenant=req.tenant)
        self.tele.async_begin("serve:request", rt.trace_id,
                              ts_s=req.arrival_s, request=req.uid,
                              tenant=req.tenant)

    def on_step_start(self, waiting, now: float) -> None:
        """Inter-step gaps (host scheduling, idle sleeps before this
        step) are queue time for everything still waiting."""
        for req in waiting:
            rt = req.trace
            if rt is not None:
                rt.accrue(now, "queue")

    def on_plan(self, plan, t0: float, t1: float, *, step: int,
                waiting: int) -> None:
        self.tele.record_span(
            "serve:scheduler_plan", t0, t1, step=step, waiting=waiting,
            admit=len(plan.admit), preempt=len(plan.preempt),
            expire=len(plan.expire), cancel=len(plan.cancel),
            reasons={str(u): r for u, r in sorted(plan.reasons.items())})

    def on_step_end(self, waiting, plan, t_end: float) -> None:
        """Classify this step's waiting time per request from the
        scheduler's non-admission reason (an allocator-race requeue
        overrides with ``alloc_race``)."""
        for req in waiting:
            rt = req.trace
            if rt is None:
                continue
            reason = rt.forced_reason or plan.reasons.get(req.uid,
                                                          "priority")
            rt.forced_reason = None
            rt.accrue(t_end, component_for_reason(reason))

    # -- admission ---------------------------------------------------------

    def on_admit_start(self, req, t: float) -> None:
        """Time from step start to this admission (earlier admissions'
        prefills, preempt/expire handling) served OTHER requests."""
        req.trace.accrue(t, "interference")

    def on_requeue(self, req, t: float, *, step: int) -> None:
        rt = req.trace
        rt.accrue(t, "admission_stall")
        rt.forced_reason = "alloc_race"
        self.tele.instant("serve:requeue", step=step, request=req.uid,
                          trace=rt.trace_id, reason="alloc_race")

    def on_alloc(self, req, t0: float, t1: float, *, step: int, slot: int,
                 new_pages: int, shared_pages: int, prefix_tokens: int,
                 prefix_cache: bool, cow: bool) -> None:
        rt = req.trace
        self.tele.record_span(
            "serve:page_alloc", t0, t1, step=step, request=req.uid,
            trace=rt.trace_id, slot=slot, new_pages=new_pages,
            shared_pages=shared_pages, cow=cow)
        if prefix_cache:
            self.tele.instant(
                "serve:prefix_match", step=step, request=req.uid,
                trace=rt.trace_id, hit=prefix_tokens > 0,
                prefix_tokens=prefix_tokens, shared_pages=shared_pages)

    def on_cow_copy(self, req, t0: float, t1: float, *, step: int,
                    src: int, dst: int) -> None:
        self.tele.record_span("serve:cow_copy", t0, t1, step=step,
                              request=req.uid, trace=req.trace.trace_id,
                              src=src, dst=dst)

    def on_prefill(self, req, t0: float, t1: float, *, step: int,
                   slot: int, bucket: int, prefill_tokens: int,
                   prefix_tokens: int, first: bool,
                   resumed: bool) -> None:
        """Everything from admission entry to the first emitted token —
        allocation, COW, the prefill program(s) — is the request's own
        service time: charge it to ``prefill`` and, on a first token,
        freeze the TTFT attribution snapshot."""
        rt = req.trace
        rt.accrue(t1, "prefill")
        self.tele.record_span(
            "serve:prefill", t0, t1, step=step, request=req.uid,
            trace=rt.trace_id, slot=slot, bucket=bucket,
            prefill_tokens=prefill_tokens, prefix_tokens=prefix_tokens,
            resumed=resumed)
        if first:
            rt.ttft_comp = dict(rt.comp)
        if not rt.opened:
            rt.opened = True
            # Flow binding: the event must land INSIDE a slice on the
            # same pid/tid, so stamp it mid-prefill-span. A fresh
            # admission opens the flow; a resumed one (preemption, or a
            # re-dispatch after replica loss where this is a different
            # process — ``resumed_origin``) continues it under the same
            # id.
            cont = resumed or rt.resumed_origin
            self.tele.flow("serve:request_flow", rt.trace_id,
                           "t" if cont else "s",
                           ts_s=(t0 + t1) / 2.0, request=req.uid,
                           resumed=cont)

    # -- decode ------------------------------------------------------------

    def on_decode(self, t0: float, t1: float, *, step: int,
                  slots) -> None:
        """One engine decode dispatch: a step-level span plus a per-slot
        tick span on a stable per-slot lane, and a ``decode`` accrual
        for every participant. ``slots``: (slot, request[, args]) rows."""
        self.tele.record_span("serve:decode", t0, t1, step=step,
                              live=len(slots))
        self._iv_decode_sum += max(t1 - t0, 0.0)
        self._iv_decode_n += 1
        for row in slots:
            slot, req = row[0], row[1]
            extra = row[2] if len(row) > 2 else {}
            rt = req.trace
            self.tele.record_span(
                "serve:decode_tick", t0, t1, step=step, request=req.uid,
                trace=rt.trace_id, slot=slot,
                tid=PER_SLOT_TID + slot, **extra)
            rt.accrue(t1, "decode")

    def on_spec_phases(self, t_draft0: float, t_draft1: float,
                       t_verify1: float, *, step: int, rounds: int,
                       proposed: int, accepted: int) -> None:
        self.tele.record_span("serve:spec_draft", t_draft0, t_draft1,
                              step=step, rounds=rounds, proposed=proposed)
        self.tele.record_span("serve:spec_verify", t_draft1, t_verify1,
                              step=step, proposed=proposed,
                              accepted=accepted)

    # -- exits -------------------------------------------------------------

    def on_preempt(self, req, t: float, *, step: int, slot: int) -> None:
        rt = req.trace
        rt.accrue(t, "decode")  # in-slot time since its last tick
        self.tele.instant("serve:preempt", step=step, request=req.uid,
                          trace=rt.trace_id, slot=slot,
                          tokens_done=len(req.tokens),
                          retries=req.retries)

    def on_cancel(self, req, t: float) -> None:
        req.trace.accrue(t, "decode")

    def on_fail(self, req, t: float, *, reason: str) -> None:
        """A failed request: the matching instant, then the same
        finalize path a retirement takes (attribution still holds — the
        components account for where its latency went before it died)."""
        rt = req.trace
        if rt is None or rt.done:
            return
        name = ("serve:deadline_miss" if reason == "deadline"
                else "serve:shed")
        self.tele.instant(name, request=req.uid, trace=rt.trace_id,
                          tenant=req.tenant, reason=reason,
                          tokens_done=len(req.tokens))
        self.finalize(req, t, status=reason)

    def finalize(self, req, t: float, *, status: str) -> None:
        """Close the request's track: residue to ``queue``, emit the
        attribution instant + flow close + async end, feed the metric
        series and the anomaly interval accumulators. Idempotent."""
        rt = req.trace
        if rt is None or rt.done:
            return
        rt.done = True
        rt.accrue(t, "queue")
        total = t - req.arrival_s
        comp = {k: round(v, 9) for k, v in rt.comp.items()}
        args: dict[str, Any] = {
            "request": req.uid, "trace": rt.trace_id,
            "tenant": req.tenant, "status": status,
            "total_s": round(total, 9), "components": comp,
            "sum_err_s": round(total - sum(rt.comp.values()), 9),
        }
        if req.ttft_s is not None and rt.ttft_comp is not None:
            args["ttft_s"] = round(req.ttft_s, 9)
            args["ttft_components"] = {k: round(v, 9)
                                       for k, v in rt.ttft_comp.items()}
            args["ttft_sum_err_s"] = round(
                req.ttft_s - sum(rt.ttft_comp.values()), 9)
        self.tele.instant("serve:attribution", **args)
        if rt.opened:
            self.tele.flow("serve:request_flow", rt.trace_id, "f",
                           ts_s=t, request=req.uid, status=status)
        self.tele.async_end("serve:request", rt.trace_id, ts_s=t,
                            status=status)
        reg = metrics.get()
        reg.observe("serve_total_latency_s", total)
        if rt.ttft_comp is not None:
            for k, v in rt.ttft_comp.items():
                reg.observe(f"serve_ttft_{k}_s", v)
            self._iv_queue_wait += (rt.ttft_comp["queue"]
                                    + rt.ttft_comp["interference"])
            self._iv_alloc_stall += rt.ttft_comp["admission_stall"]
        self._iv_finished += 1

    # -- anomaly cadence ---------------------------------------------------

    def interval_signals(self, *, reset: bool = True) -> dict:
        """Mean attribution signals since the last call, for
        ``AnomalyDetector.update_serve``: queue wait (queue +
        interference) and admission stall per completion, mean decode
        dispatch duration per step."""
        n = max(self._iv_finished, 1)
        out = {
            "queue_wait_s": (self._iv_queue_wait / n
                             if self._iv_finished else None),
            "alloc_stall_s": (self._iv_alloc_stall / n
                              if self._iv_finished else None),
            "decode_tick_s": (self._iv_decode_sum / self._iv_decode_n
                              if self._iv_decode_n else None),
            "finished": self._iv_finished,
        }
        if reset:
            self._iv_finished = 0
            self._iv_queue_wait = self._iv_alloc_stall = 0.0
            self._iv_decode_sum = 0.0
            self._iv_decode_n = 0
        return out


def maybe_tracer(tele: Optional[telemetry.Telemetry] = None
                 ) -> Optional[ServeTracer]:
    """A :class:`ServeTracer` over the (given or active) telemetry
    registry when it is enabled, else None — the engine's whole
    disabled-tracing story is this None."""
    tele = telemetry.get() if tele is None else tele
    return ServeTracer(tele) if tele.enabled else None
