"""Continuous-batching serving substrate (ISSUE 12 / ROADMAP item 1).

- :mod:`serve.kv_cache`   — paged/ragged KV cache: fixed-size pages from a
  preallocated HBM pool, per-slot page tables, free-list reuse;
- :mod:`serve.engine`     — prefill/decode-split generation engine that
  admits and retires decode slots every step;
- :mod:`serve.scheduler`  — per-tenant SLO-aware admission / preemption.

Imports are deliberately lazy (no submodule import here): models import
``serve.kv_cache`` from inside their decode branches, and an eager package
import would cycle back through ``models``.
"""
