"""Continuous-batching generation engine: prefill/decode split over a
paged KV cache, slots admitted and retired every step.

Why not ``models/generate.py`` for serving: ``generate()`` runs one batch
shape to completion — chips idle whenever sequences finish early, and a
long prompt stalls every other request in the batch. This engine runs two
separately compiled programs instead (the same per-program decomposition
PAPERS.md motivates for MPMD pipeline training, applied to inference):

- **prefill** (one program per prompt-length bucket): a batch-1 dense
  decode forward over the right-padded prompt, whose K/V is packed into
  pool pages *inside the same program* (``kv_cache.pack_prefill_cache``
  with the real length as a traced scalar — one compile per bucket, any
  prompt length within it), returning the first generated token;
- **decode** (one static-shape program): every live slot advances exactly
  one token per call via the models' ``paged_state`` branch. Slots join
  and leave between calls by flipping rows of the page table / lengths /
  live mask — the compiled program never changes shape.

Both programs are lowered through ``perf/aot.py``'s executable cache
under a serve-specific config fingerprint, so a warm replica boots with
zero retraces (``Engine.warmup()`` + ``aot_stats()``).

Greedy (temperature=0) only in v1: preemption re-queues a request with
its generated prefix folded into the prompt, and greedy decoding is what
makes that continuation deterministic (tests pin token-identity against
sequential ``generate(use_cache=True)``, including across preemption and
mid-stream retire/admit). Sampled serving needs per-slot RNG lanes —
deliberately out of scope here.

Observability: per-request lifecycle events (``serve_admit`` /
``serve_prefill`` / ``serve_first_token`` / ``serve_retire`` /
``serve_preempt`` / ``serve_shed`` / ``serve_deadline_miss``) go to the
flight recorder; engine gauges (live slots, page occupancy, queue depth,
TTFT, shed/deadline-miss/retry counters) to ``observability/metrics.py``.

Failure modes (docs/serving.md "Failure modes and recovery"): the engine
accepts a serve fault plan (``robustness/faults.py`` grammar, resolved
attempt-scoped from ``DDL_FAULT_PLAN``) and fires it at step boundaries —
``crash``/``sigkill`` kill the replica mid-decode, ``decode_stall`` sleeps
a step, ``page_leak``/``corrupt_page_table`` sabotage the paged-KV host
state. Under an active plan every step opens with ``check_integrity()``
(page-table rows vs owned pages vs allocator accounting), so sabotage is
detected BEFORE the corrupt state reaches a dispatch; ``shutdown()`` runs
the same gate unconditionally. Requests lost with a replica are replayed
by the supervisor (``launch.run_serve``) through the same greedy
prefix-folding path preemption uses, which is what makes recovery
token-identical.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import time
from typing import Any, Callable, NamedTuple, Optional, Sequence

import numpy as np

from distributeddeeplearning_tpu.robustness import faults as faultslib
from distributeddeeplearning_tpu.serve import kv_cache
from distributeddeeplearning_tpu.serve.scheduler import (BrownoutController,
                                                         SloScheduler)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Everything that shapes the compiled serve programs, plus the one
    volatile knob (``compile_cache_dir``) excluded from the fingerprint."""

    model: str = "gpt_tiny"
    vocab_size: int = 1024
    dtype: str = "float32"
    max_slots: int = 4                      # decode batch rows
    page_size: int = 16                     # tokens per KV page
    num_pages: int = 64                     # pool size, all slots share it
    max_pages_per_slot: int = 8             # page-table width
    prefill_buckets: tuple = (16, 32, 64)   # padded prompt lengths
    seed: int = 0
    compile_cache_dir: Optional[str] = None

    @property
    def slot_capacity(self) -> int:
        """Max prompt+generated tokens a single slot can ever hold."""
        return self.page_size * self.max_pages_per_slot


def serve_fingerprint(config: ServeConfig) -> str:
    """Stable hash of the program-shaping serve config (+ jax versions) —
    the serving analogue of ``perf/aot.config_fingerprint``, which cannot
    be reused directly because it resolves TrainConfig-only fields (fault
    plans) that a ServeConfig does not have."""
    import jax
    import jaxlib

    d = dataclasses.asdict(config)
    d.pop("compile_cache_dir", None)  # volatile: where, not what
    d["_versions"] = {"jax": jax.__version__, "jaxlib": jaxlib.__version__}
    blob = json.dumps(d, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


@dataclasses.dataclass
class Request:
    """One generation request and its accumulated lifecycle state."""

    uid: int
    tenant: str
    prompt: list
    max_new_tokens: int
    arrival_s: float
    tokens: list = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None
    itl_s: list = dataclasses.field(default_factory=list)
    finished_s: Optional[float] = None
    preemptions: int = 0
    retries: int = 0            # re-admissions after preemption/loss
    not_before_s: float = 0.0   # retry backoff: ineligible before this
    failed: Optional[str] = None  # "deadline"/"shed"/"retries_exhausted"
    _last_emit_s: Optional[float] = None

    @property
    def total_tokens(self) -> int:
        """Full page budget: prompt + every token it may ever emit."""
        return len(self.prompt) + self.max_new_tokens

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def prefill_ids(self) -> list:
        """What a (re-)admission prefills: the prompt plus everything
        already emitted — after preemption the generated prefix is part
        of the context, and greedy decoding continues it exactly."""
        return list(self.prompt) + list(self.tokens)

    @property
    def output_ids(self) -> list:
        return list(self.prompt) + list(self.tokens)

    def emit(self, token: int, now: float) -> None:
        if self.ttft_s is None:
            self.ttft_s = now - self.arrival_s
        elif self._last_emit_s is not None:
            self.itl_s.append(now - self._last_emit_s)
        self.tokens.append(int(token))
        self._last_emit_s = now


class _SlotView(NamedTuple):
    """What the scheduler sees of a live slot."""

    slot: int
    tenant: str
    num_pages: int
    admitted_seq: int
    arrival_s: float = 0.0


@dataclasses.dataclass
class _Slot:
    request: Request
    pages: list
    admitted_seq: int


class Engine:
    """Continuous-batching engine over one model replica.

    ``clock`` is injectable (tests drive a fake clock; the bench uses
    ``time.monotonic``). All host state is plain numpy/python; device
    state is exactly (params, pools) with pools donated through both
    programs, so XLA updates the KV pool in place every step.
    """

    def __init__(self, config: ServeConfig, *, model=None, variables=None,
                 scheduler: Optional[SloScheduler] = None,
                 clock: Optional[Callable[[], float]] = None,
                 brownout: Optional[BrownoutController] = None,
                 fault_plan: Optional[str] = None,
                 stall: Optional[Callable[[float], None]] = None):
        import jax
        import jax.numpy as jnp

        from distributeddeeplearning_tpu.models import generate as genlib
        from distributeddeeplearning_tpu.perf import aot as aotlib
        from distributeddeeplearning_tpu.perf import compile_cache

        cfg = config
        if not cfg.prefill_buckets:
            raise ValueError("prefill_buckets must name at least one "
                             "padded prompt length")
        self.config = cfg
        self.scheduler = scheduler or SloScheduler()
        self._clock = clock or time.monotonic
        if model is None:
            from distributeddeeplearning_tpu import models as modelslib
            model = modelslib.model_spec(cfg.model).build(
                vocab_size=cfg.vocab_size, dtype=getattr(jnp, cfg.dtype))
        self.model = model
        if variables is None:
            probe = jnp.zeros((1, min(cfg.prefill_buckets)), jnp.int32)
            variables = model.init({"params": jax.random.key(cfg.seed)},
                                   probe, train=False)
        self._fresh = {k: v for k, v in variables.items() if k != "cache"}

        capacity = genlib.decode_capacity(model)
        if capacity is not None and cfg.slot_capacity > capacity:
            raise ValueError(
                f"slot capacity {cfg.slot_capacity} tokens (page_size x "
                f"max_pages_per_slot) exceeds the model's decode bound "
                f"{capacity} — positions past it cannot be generated")
        if max(cfg.prefill_buckets) > cfg.slot_capacity:
            raise ValueError(
                f"largest prefill bucket {max(cfg.prefill_buckets)} "
                f"exceeds slot capacity {cfg.slot_capacity}")

        self._pools = kv_cache.init_pools(
            model, {**self._fresh}, num_pages=cfg.num_pages,
            page_size=cfg.page_size)
        self.allocator = kv_cache.PageAllocator(cfg.num_pages)
        s, p = cfg.max_slots, cfg.max_pages_per_slot
        self._page_table = np.zeros((s, p), np.int32)
        self._lengths = np.zeros((s,), np.int32)
        self._live = np.zeros((s,), bool)
        self._feed = np.zeros((s, 1), np.int32)
        self._slots: list = [None] * s
        self.waiting: collections.deque = collections.deque()
        self.finished: list = []
        self.failed: list = []
        self._uid = 0
        self._admitted_seq = 0
        self.steps = 0
        self.preemptions = 0
        self.sheds = 0
        self.deadline_misses = 0
        self.retries = 0

        self.brownout = brownout
        # Serve chaos: the resolved (attempt-scoped) plan installs a stall
        # table and a boundary injector; a plan-free engine pays one
        # ``is not None`` check per step and no integrity sweep.
        plan = faultslib.resolve_serve(fault_plan)
        self._stalls = plan.serve_stalls()
        self._fault_fire = faultslib.make_serve_injector(plan, self)
        self._chaos = bool(plan)
        self._stall = stall or time.sleep

        self._aot = aotlib.StepExecutableCache(
            compile_cache.resolve_dir(cfg.compile_cache_dir),
            serve_fingerprint(cfg))
        self._prefill_exec: dict = {}
        self._decode_exec = None

    # -- public surface ---------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int,
               tenant: str = "default",
               arrival_s: Optional[float] = None) -> Request:
        """Queue one request; admission happens on a later ``step()``."""
        from distributeddeeplearning_tpu.models import generate as genlib

        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt: prefill needs >= 1 token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}: a request "
                             f"that emits nothing never leaves its slot")
        total = len(prompt) + max_new_tokens
        genlib._require_decode(self.model, total, request_totals=[total])
        if total > self.config.slot_capacity:
            raise ValueError(
                f"request needs {total} tokens (prompt {len(prompt)} + "
                f"max_new {max_new_tokens}) but a slot holds at most "
                f"{self.config.slot_capacity} (page_size "
                f"{self.config.page_size} x max_pages_per_slot "
                f"{self.config.max_pages_per_slot})")
        if len(prompt) > max(self.config.prefill_buckets):
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the largest "
                f"prefill bucket {max(self.config.prefill_buckets)}")
        req = Request(uid=self._uid, tenant=tenant, prompt=prompt,
                      max_new_tokens=max_new_tokens,
                      arrival_s=(self._clock() if arrival_s is None
                                 else arrival_s))
        self._uid += 1
        self.waiting.append(req)
        return req

    @property
    def num_live(self) -> int:
        return int(self._live.sum())

    @property
    def idle(self) -> bool:
        return not self.waiting and self.num_live == 0

    def step(self) -> list:
        """One engine step: schedule, expire/cancel deadline-blown work,
        shed under brownout pressure, preempt, admit (+prefill), advance
        every live slot one token, retire finished. Returns the requests
        that finished during this step. Under an active fault plan the
        step opens with an integrity sweep (sabotage from the previous
        boundary must not reach a dispatch) and closes by firing the
        injector."""
        from distributeddeeplearning_tpu.observability import flight, metrics

        if self._chaos:
            self.check_integrity()
        stall_s = self._stalls.get(self.steps + 1)
        if stall_s:
            flight.get().record("fault", kind="decode_stall",
                                step=self.steps + 1, seconds=stall_s,
                                scope="serve")
            self._stall(stall_s)
        now = self._clock()
        finished_before = len(self.finished)
        if self.brownout is not None:
            for req in self.brownout.plan_shed(
                    now=now, waiting=list(self.waiting),
                    scheduler=self.scheduler,
                    free_pages=self.allocator.free_pages,
                    num_pages=self.config.num_pages):
                self.waiting.remove(req)
                self._fail(req, "shed", now)
        plan = self.scheduler.plan(
            now=now, waiting=list(self.waiting), live=self._slot_views(),
            free_slots=self.config.max_slots - self.num_live,
            free_pages=self.allocator.free_pages,
            page_size=self.config.page_size)
        for slot in plan.cancel:
            self._cancel(slot, now)
        for req in plan.expire:
            self.waiting.remove(req)
            self._fail(req, "deadline", now)
        for slot in plan.preempt:
            self._preempt(slot, now)
        for req in plan.admit:
            self.waiting.remove(req)
            self._admit(req)
        if self.num_live:
            self._decode_step()
        self.steps += 1
        reg = metrics.get()
        reg.observe("serve_live_slots", self.num_live, step=self.steps)
        reg.observe("serve_page_occupancy",
                    self.allocator.pages_in_use / self.config.num_pages,
                    step=self.steps)
        reg.observe("serve_queue_depth", len(self.waiting), step=self.steps)
        reg.observe("serve_shed_total", self.sheds, step=self.steps)
        reg.observe("serve_deadline_miss_total", self.deadline_misses,
                    step=self.steps)
        reg.observe("serve_retry_total", self.retries, step=self.steps)
        if self._fault_fire is not None:
            self._fault_fire(self.steps)
        return self.finished[finished_before:]

    def run_until_idle(self, *, max_steps: int = 10_000) -> list:
        """Drain queue + slots; returns all finished requests. The step
        bound turns a scheduling livelock into a loud failure."""
        for _ in range(max_steps):
            if self.idle:
                return self.finished
            self.step()
        raise RuntimeError(
            f"engine not idle after {max_steps} steps: "
            f"{len(self.waiting)} waiting, {self.num_live} live — "
            f"scheduling livelock or a request that cannot ever fit")

    def warmup(self) -> dict:
        """Compile (or AOT-load) the decode program and every prefill
        bucket without touching pool contents: the dummy prefill packs
        zero positions (plen=0) and the dummy decode has no live rows, so
        every pool write is dropped. Returns ``aot_stats()``."""
        import jax.numpy as jnp

        for bucket in sorted(self.config.prefill_buckets):
            self._run_prefill(
                np.zeros((1, bucket), np.int32), plen=0,
                page_row=np.zeros((self.config.max_pages_per_slot,),
                                  np.int32))
        tok, pools = self._decode_program()(
            self._fresh, jnp.asarray(self._feed),
            jnp.asarray(self._page_table), jnp.asarray(self._lengths),
            jnp.asarray(self._live), self._pools)
        tok.block_until_ready()
        self._pools = pools
        return self.aot_stats()

    def aot_stats(self) -> dict:
        return self._aot.stats()

    # -- internals --------------------------------------------------------

    def _slot_views(self) -> list:
        return [_SlotView(slot=i, tenant=s.request.tenant,
                          num_pages=len(s.pages),
                          admitted_seq=s.admitted_seq,
                          arrival_s=s.request.arrival_s)
                for i, s in enumerate(self._slots) if s is not None]

    def _bucket_for(self, plen: int) -> int:
        for b in sorted(self.config.prefill_buckets):
            if plen <= b:
                return b
        raise ValueError(
            f"prefill of {plen} tokens exceeds the largest bucket "
            f"{max(self.config.prefill_buckets)} — after preemption the "
            f"generated prefix re-prefills too; size buckets to "
            f"prompt + max_new_tokens")

    def _program(self, name: str, fn, example_args, donate_argnums):
        """Lower/compile through the AOT executable cache: warm replicas
        deserialize instead of retracing."""
        import jax

        key = self._aot.key(name, example_args)
        cached = self._aot.load(name, key)
        if cached is not None:
            return cached
        compiled = jax.jit(fn, donate_argnums=donate_argnums).lower(
            *example_args).compile()
        self._aot.save(name, key, compiled)
        return compiled

    def _prefill_program(self, bucket: int):
        import jax
        import jax.numpy as jnp

        if bucket in self._prefill_exec:
            return self._prefill_exec[bucket]

        def prefill(fresh, ids, plen, page_row, pools):
            logits, mut = self.model.apply(fresh, ids, train=False,
                                           decode=True, mutable=["cache"])
            pools = kv_cache.pack_prefill_cache(
                mut["cache"], pools, page_row=page_row, plen=plen)
            last = jax.lax.dynamic_slice_in_dim(
                logits, plen - 1, 1, axis=1)[:, 0]
            return jnp.argmax(last, axis=-1).astype(jnp.int32)[0], pools

        example = (self._fresh, jnp.zeros((1, bucket), jnp.int32),
                   jnp.int32(0),
                   jnp.zeros((self.config.max_pages_per_slot,), jnp.int32),
                   self._pools)
        exec_ = self._program(f"serve_prefill_{bucket}", prefill, example,
                              donate_argnums=(4,))
        self._prefill_exec[bucket] = exec_
        return exec_

    def _decode_program(self):
        import jax.numpy as jnp

        if self._decode_exec is not None:
            return self._decode_exec

        def decode(fresh, feed, page_table, lengths, live, pools):
            state = kv_cache.PagedState(page_table, lengths, live)
            logits, mut = self.model.apply(
                {**fresh, "cache": pools}, feed, train=False, decode=True,
                paged_state=state, mutable=["cache"])
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return tok, mut["cache"]

        example = (self._fresh, jnp.asarray(self._feed),
                   jnp.asarray(self._page_table),
                   jnp.asarray(self._lengths), jnp.asarray(self._live),
                   self._pools)
        self._decode_exec = self._program("serve_decode", decode, example,
                                          donate_argnums=(5,))
        return self._decode_exec

    def _run_prefill(self, padded: np.ndarray, *, plen: int,
                     page_row: np.ndarray) -> int:
        import jax.numpy as jnp

        bucket = padded.shape[1]
        tok, pools = self._prefill_program(bucket)(
            self._fresh, jnp.asarray(padded), jnp.int32(plen),
            jnp.asarray(page_row), self._pools)
        self._pools = pools
        return int(tok)

    def _admit(self, req: Request) -> None:
        from distributeddeeplearning_tpu.observability import flight

        cfg = self.config
        slot = next(i for i, s in enumerate(self._slots) if s is None)
        need = kv_cache.pages_needed(req.total_tokens, cfg.page_size)
        pages = self.allocator.alloc(need)
        if pages is None:  # scheduler raced itself — re-queue, not crash
            self.waiting.appendleft(req)
            return
        self._admitted_seq += 1
        self._slots[slot] = _Slot(request=req, pages=pages,
                                  admitted_seq=self._admitted_seq)
        page_row = np.zeros((cfg.max_pages_per_slot,), np.int32)
        page_row[:need] = pages
        self._page_table[slot] = page_row

        ids = req.prefill_ids
        plen = len(ids)
        bucket = self._bucket_for(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = ids
        flight.get().record("serve_admit", request=req.uid,
                            tenant=req.tenant, slot=slot, pages=need,
                            resumed=bool(req.tokens))
        tok = self._run_prefill(padded, plen=plen, page_row=page_row)
        now = self._clock()
        flight.get().record("serve_prefill", request=req.uid, slot=slot,
                            bucket=bucket, prompt_tokens=plen)
        first = req.ttft_s is None
        req.emit(tok, now)
        if first:
            from distributeddeeplearning_tpu.observability import metrics
            metrics.get().observe("serve_ttft_s", req.ttft_s,
                                  step=self.steps)
            flight.get().record("serve_first_token", request=req.uid,
                                slot=slot, ttft_s=round(req.ttft_s, 6))
        self._lengths[slot] = plen
        self._live[slot] = True
        self._feed[slot, 0] = tok
        if req.remaining == 0:
            self._retire(slot, now)

    def _decode_step(self) -> None:
        import jax.numpy as jnp

        toks, pools = self._decode_program()(
            self._fresh, jnp.asarray(self._feed),
            jnp.asarray(self._page_table), jnp.asarray(self._lengths),
            jnp.asarray(self._live), self._pools)
        self._pools = pools
        toks = np.asarray(toks)
        now = self._clock()
        for i in np.flatnonzero(self._live):
            req = self._slots[i].request
            req.emit(toks[i], now)
            self._lengths[i] += 1
            self._feed[i, 0] = toks[i]
            if req.remaining == 0:
                self._retire(int(i), now)

    def _retire(self, slot: int, now: float) -> None:
        from distributeddeeplearning_tpu.observability import flight

        entry = self._slots[slot]
        req = entry.request
        req.finished_s = now
        # release() + pages=[]: retirement is idempotent — a request that
        # already walked a victim path cannot double-free (the one bug the
        # strict free() exists to catch in non-victim paths).
        self.allocator.release(entry.pages)
        entry.pages = []
        self._clear_slot(slot)
        self.finished.append(req)
        flight.get().record("serve_retire", request=req.uid, slot=slot,
                            tokens=len(req.tokens),
                            preemptions=req.preemptions)

    def _preempt(self, slot: int, now: float) -> None:
        from distributeddeeplearning_tpu.observability import flight

        entry = self._slots[slot]
        req = entry.request
        req.preemptions += 1
        req._last_emit_s = None  # the gap back through the queue is not ITL
        self.allocator.release(entry.pages)
        entry.pages = []
        self._clear_slot(slot)
        self.preemptions += 1
        flight.get().record("serve_preempt", request=req.uid, slot=slot,
                            tenant=req.tenant,
                            tokens_done=len(req.tokens))
        # Bounded retry with exponential backoff: the scheduler owns the
        # policy, the engine applies it on every re-queue.
        req.retries += 1
        self.retries += 1
        max_r = self.scheduler.max_retries
        if max_r is not None and req.retries > max_r:
            self._fail(req, "retries_exhausted", now)
            return
        delay = self.scheduler.retry_delay_s(req.retries)
        if delay > 0:
            req.not_before_s = now + delay
        self.waiting.append(req)

    def _cancel(self, slot: int, now: float) -> None:
        """A live slot whose request blew its total-latency deadline:
        return the slot and pages, fail the request as a deadline miss."""
        entry = self._slots[slot]
        req = entry.request
        self.allocator.release(entry.pages)
        entry.pages = []
        self._clear_slot(slot)
        self._fail(req, "deadline", now)

    def _fail(self, req: Request, reason: str, now: float) -> None:
        from distributeddeeplearning_tpu.observability import flight

        req.failed = reason
        req.finished_s = now
        self.failed.append(req)
        if reason == "deadline":
            self.deadline_misses += 1
            flight.get().record("serve_deadline_miss", request=req.uid,
                                tenant=req.tenant,
                                waited_s=round(now - req.arrival_s, 6),
                                tokens_done=len(req.tokens))
        else:
            self.sheds += 1
            flight.get().record("serve_shed", request=req.uid,
                                tenant=req.tenant, reason=reason,
                                tokens_done=len(req.tokens))

    def _clear_slot(self, slot: int) -> None:
        self._slots[slot] = None
        self._live[slot] = False
        self._lengths[slot] = 0
        self._feed[slot, 0] = 0
        self._page_table[slot] = 0

    # -- integrity / chaos hooks ------------------------------------------

    def check_integrity(self) -> None:
        """Reconcile the three views of page ownership — slot page-table
        rows, slot owned-page lists, allocator accounting — and raise on
        any divergence. Runs before every dispatch under an active fault
        plan and unconditionally at shutdown: a leaked page starves
        admission later; a corrupt row serves another slot's K/V now."""
        owned: list = []
        for i, entry in enumerate(self._slots):
            if entry is None:
                continue
            row = [int(p) for p in self._page_table[i, :len(entry.pages)]]
            pages = [int(p) for p in entry.pages]
            if row != pages:
                raise RuntimeError(
                    f"page-table corruption: slot {i} row {row} != owned "
                    f"pages {pages}")
            owned.extend(pages)
        self.allocator.check_leaks(owned)

    def corrupt_page_table(self) -> Optional[int]:
        """Fault-injection hook (``corrupt_page_table@N``): scribble over
        the first live slot's page-table row. Returns the slot hit, or
        None when nothing is live to corrupt."""
        for i, entry in enumerate(self._slots):
            if entry is not None and entry.pages:
                self._page_table[i, 0] = (
                    int(self._page_table[i, 0]) + 1) % self.config.num_pages
                return i
        return None

    def shutdown(self) -> None:
        """Final gate: flight-record the lifetime counters, then assert
        page accounting balances (allocated == sum of live page tables).
        Raises RuntimeError on a leak — a replica that leaks pages must
        exit loudly, not report success."""
        from distributeddeeplearning_tpu.observability import flight

        flight.get().record("serve_shutdown", steps=self.steps,
                            finished=len(self.finished),
                            failed=len(self.failed),
                            preemptions=self.preemptions,
                            sheds=self.sheds,
                            deadline_misses=self.deadline_misses)
        self.check_integrity()
