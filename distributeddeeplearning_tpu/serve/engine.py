"""Continuous-batching generation engine: prefill/decode split over a
paged KV cache, slots admitted and retired every step.

Why not ``models/generate.py`` for serving: ``generate()`` runs one batch
shape to completion — chips idle whenever sequences finish early, and a
long prompt stalls every other request in the batch. This engine runs two
separately compiled programs instead (the same per-program decomposition
PAPERS.md motivates for MPMD pipeline training, applied to inference):

- **prefill** (one program per prompt-length bucket): a batch-1 dense
  decode forward over the right-padded prompt, whose K/V is packed into
  pool pages *inside the same program* (``kv_cache.pack_prefill_cache``
  with the real length as a traced scalar — one compile per bucket, any
  prompt length within it), returning the first generated token;
- **decode** (one static-shape program): every live slot advances exactly
  one token per call via the models' ``paged_state`` branch. Slots join
  and leave between calls by flipping rows of the page table / lengths /
  live mask — the compiled program never changes shape.

Both programs are lowered through ``perf/aot.py``'s executable cache
under a serve-specific config fingerprint, so a warm replica boots with
zero retraces (``Engine.warmup()`` + ``aot_stats()``).

Greedy (temperature=0) only in v1: preemption re-queues a request with
its generated prefix folded into the prompt, and greedy decoding is what
makes that continuation deterministic (tests pin token-identity against
sequential ``generate(use_cache=True)``, including across preemption and
mid-stream retire/admit). Sampled serving needs per-slot RNG lanes —
deliberately out of scope here.

Observability: per-request lifecycle events (``serve_admit`` /
``serve_prefill`` / ``serve_first_token`` / ``serve_retire`` /
``serve_preempt`` / ``serve_shed`` / ``serve_deadline_miss``) go to the
flight recorder; engine gauges (live slots, page occupancy, queue depth,
TTFT, shed/deadline-miss/retry counters) to ``observability/metrics.py``.
When telemetry is enabled at construction, ``serve/tracing.py`` adds
request-scoped Chrome-trace span trees and exact TTFT/latency
attribution (docs/serve_tracing.md); when it is not, the engine holds no
tracer and the hot loop pays one ``is not None`` check per site.

Failure modes (docs/serving.md "Failure modes and recovery"): the engine
accepts a serve fault plan (``robustness/faults.py`` grammar, resolved
attempt-scoped from ``DDL_FAULT_PLAN``) and fires it at step boundaries —
``crash``/``sigkill`` kill the replica mid-decode, ``decode_stall`` sleeps
a step, ``page_leak``/``corrupt_page_table`` sabotage the paged-KV host
state. Under an active plan every step opens with ``check_integrity()``
(page-table rows vs owned pages vs allocator accounting), so sabotage is
detected BEFORE the corrupt state reaches a dispatch; ``shutdown()`` runs
the same gate unconditionally. Requests lost with a replica are replayed
by the supervisor (``launch.run_serve``) through the same greedy
prefix-folding path preemption uses, which is what makes recovery
token-identical.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import time
from typing import Any, Callable, NamedTuple, Optional, Sequence

import numpy as np

from distributeddeeplearning_tpu.robustness import faults as faultslib
from distributeddeeplearning_tpu.serve import kv_cache
from distributeddeeplearning_tpu.serve import tracing as tracinglib
from distributeddeeplearning_tpu.serve.scheduler import (BrownoutController,
                                                         SloScheduler)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Everything that shapes the compiled serve programs, plus the one
    volatile knob (``compile_cache_dir``) excluded from the fingerprint."""

    model: str = "gpt_tiny"
    vocab_size: int = 1024
    dtype: str = "float32"
    max_slots: int = 4                      # decode batch rows
    page_size: int = 16                     # tokens per KV page
    num_pages: int = 64                     # pool size, all slots share it
    max_pages_per_slot: int = 8             # page-table width
    prefill_buckets: tuple = (16, 32, 64)   # padded prompt lengths
    seed: int = 0
    # Serve fast path (both default OFF — the PR-12 engine exactly).
    # prefix_cache: radix-tree prefix reuse over the shared page pool —
    # admission maps cached full prompt pages into the slot's table
    # (refcount++) and prefills only the unmatched suffix.
    prefix_cache: bool = False
    # Speculative decoding: a shrunk same-family drafter proposes
    # spec_k tokens per round; one batched verify program accepts the
    # longest greedy-matching prefix (token-identical by construction).
    # Both must be set together.
    spec_draft_model: Optional[str] = None
    spec_k: int = 0
    compile_cache_dir: Optional[str] = None

    @property
    def slot_capacity(self) -> int:
        """Max prompt+generated tokens a single slot can ever hold."""
        return self.page_size * self.max_pages_per_slot

    @property
    def spec_enabled(self) -> bool:
        return self.spec_k > 0 and self.spec_draft_model is not None


def serve_fingerprint(config: ServeConfig) -> str:
    """Stable hash of the program-shaping serve config (+ jax versions) —
    the serving analogue of ``perf/aot.config_fingerprint``, which cannot
    be reused directly because it resolves TrainConfig-only fields (fault
    plans) that a ServeConfig does not have."""
    import jax
    import jaxlib

    d = dataclasses.asdict(config)
    d.pop("compile_cache_dir", None)  # volatile: where, not what
    d["_versions"] = {"jax": jax.__version__, "jaxlib": jaxlib.__version__}
    blob = json.dumps(d, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


@dataclasses.dataclass
class Request:
    """One generation request and its accumulated lifecycle state."""

    uid: int
    tenant: str
    prompt: list
    max_new_tokens: int
    arrival_s: float
    tokens: list = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None
    itl_s: list = dataclasses.field(default_factory=list)
    finished_s: Optional[float] = None
    preemptions: int = 0
    retries: int = 0            # re-admissions after preemption/loss
    not_before_s: float = 0.0   # retry backoff: ineligible before this
    failed: Optional[str] = None  # "deadline"/"shed"/"retries_exhausted"
    _last_emit_s: Optional[float] = None
    # tracing.RequestTrace when the engine was built with telemetry
    # enabled; stays None (zero per-request overhead) otherwise.
    trace: Any = None

    @property
    def total_tokens(self) -> int:
        """Full page budget: prompt + every token it may ever emit."""
        return len(self.prompt) + self.max_new_tokens

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def prefill_ids(self) -> list:
        """What a (re-)admission prefills: the prompt plus everything
        already emitted — after preemption the generated prefix is part
        of the context, and greedy decoding continues it exactly."""
        return list(self.prompt) + list(self.tokens)

    @property
    def output_ids(self) -> list:
        return list(self.prompt) + list(self.tokens)

    def emit(self, token: int, now: float) -> None:
        if self.ttft_s is None:
            self.ttft_s = now - self.arrival_s
        elif self._last_emit_s is not None:
            self.itl_s.append(now - self._last_emit_s)
        self.tokens.append(int(token))
        self._last_emit_s = now


class _SlotView(NamedTuple):
    """What the scheduler sees of a live slot."""

    slot: int
    tenant: str
    num_pages: int
    admitted_seq: int
    arrival_s: float = 0.0


@dataclasses.dataclass
class _Slot:
    request: Request
    pages: list
    admitted_seq: int


class Engine:
    """Continuous-batching engine over one model replica.

    ``clock`` is injectable (tests drive a fake clock; the bench uses
    ``time.monotonic``). All host state is plain numpy/python; device
    state is exactly (params, pools) with pools donated through both
    programs, so XLA updates the KV pool in place every step.
    """

    def __init__(self, config: ServeConfig, *, model=None, variables=None,
                 scheduler: Optional[SloScheduler] = None,
                 clock: Optional[Callable[[], float]] = None,
                 brownout: Optional[BrownoutController] = None,
                 fault_plan: Optional[str] = None,
                 stall: Optional[Callable[[float], None]] = None):
        import jax
        import jax.numpy as jnp

        from distributeddeeplearning_tpu.models import generate as genlib
        from distributeddeeplearning_tpu.perf import aot as aotlib
        from distributeddeeplearning_tpu.perf import compile_cache

        cfg = config
        if not cfg.prefill_buckets:
            raise ValueError("prefill_buckets must name at least one "
                             "padded prompt length")
        self.config = cfg
        self.scheduler = scheduler or SloScheduler()
        self._clock = clock or time.monotonic
        # Resolved ONCE: telemetry must be configured before the engine
        # is built. None IS the disabled path — every instrumentation
        # site below is behind a single ``is not None`` check and no
        # per-request trace state is ever allocated (pinned by test).
        self._tracer = tracinglib.maybe_tracer()
        if model is None:
            from distributeddeeplearning_tpu import models as modelslib
            model = modelslib.model_spec(cfg.model).build(
                vocab_size=cfg.vocab_size, dtype=getattr(jnp, cfg.dtype))
        self.model = model
        if variables is None:
            probe = jnp.zeros((1, min(cfg.prefill_buckets)), jnp.int32)
            variables = model.init({"params": jax.random.key(cfg.seed)},
                                   probe, train=False)
        self._fresh = {k: v for k, v in variables.items() if k != "cache"}

        capacity = genlib.decode_capacity(model)
        if capacity is not None and cfg.slot_capacity > capacity:
            raise ValueError(
                f"slot capacity {cfg.slot_capacity} tokens (page_size x "
                f"max_pages_per_slot) exceeds the model's decode bound "
                f"{capacity} — positions past it cannot be generated")
        if max(cfg.prefill_buckets) > cfg.slot_capacity:
            raise ValueError(
                f"largest prefill bucket {max(cfg.prefill_buckets)} "
                f"exceeds slot capacity {cfg.slot_capacity}")

        self._pools = kv_cache.init_pools(
            model, {**self._fresh}, num_pages=cfg.num_pages,
            page_size=cfg.page_size)
        self.allocator = kv_cache.PageAllocator(cfg.num_pages)

        # Radix prefix cache: tree nodes hold allocator claims on cached
        # full prompt pages, so a retired slot's prefix survives for the
        # next request with the same prompt head.
        self.prefix = (kv_cache.RadixPrefixCache(self.allocator,
                                                 cfg.page_size)
                       if cfg.prefix_cache else None)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_reused = 0
        self.cow_copies = 0

        # Speculative decoding: a shrunk same-family drafter over its OWN
        # pools but the SAME page-id space (one allocator, one page
        # table), so shared prefix pages carry drafter K/V too. A drafter
        # named identically to the target shares its seed (bitwise-equal
        # params — the always-accept path tests exercise).
        if (cfg.spec_k > 0) != (cfg.spec_draft_model is not None):
            raise ValueError(
                f"speculative decoding needs BOTH spec_draft_model and "
                f"spec_k > 0 (got draft={cfg.spec_draft_model!r}, "
                f"k={cfg.spec_k})")
        self._draft_model = None
        if cfg.spec_enabled:
            from distributeddeeplearning_tpu import models as modelslib
            draft = modelslib.model_spec(cfg.spec_draft_model).build(
                vocab_size=cfg.vocab_size, dtype=getattr(jnp, cfg.dtype))
            dseed = (cfg.seed if cfg.spec_draft_model == cfg.model
                     else cfg.seed + 1)
            probe = jnp.zeros((1, min(cfg.prefill_buckets)), jnp.int32)
            dvars = draft.init({"params": jax.random.key(dseed)}, probe,
                               train=False)
            dcap = genlib.decode_capacity(draft)
            if dcap is not None and cfg.slot_capacity > dcap:
                raise ValueError(
                    f"slot capacity {cfg.slot_capacity} exceeds the "
                    f"drafter's decode bound {dcap}")
            self._draft_model = draft
            self._draft_fresh = {k: v for k, v in dvars.items()
                                 if k != "cache"}
            self._draft_pools = kv_cache.init_pools(
                draft, {**self._draft_fresh}, num_pages=cfg.num_pages,
                page_size=cfg.page_size)
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0

        s, p = cfg.max_slots, cfg.max_pages_per_slot
        # Drafter cached length per slot: the drafter may lag the target
        # by at most one token after a fully-accepted round.
        self._d_len = np.zeros((s,), np.int32)
        self._page_table = np.zeros((s, p), np.int32)
        self._lengths = np.zeros((s,), np.int32)
        self._live = np.zeros((s,), bool)
        self._feed = np.zeros((s, 1), np.int32)
        self._slots: list = [None] * s
        self.waiting: collections.deque = collections.deque()
        self.finished: list = []
        self.failed: list = []
        self._uid = 0
        self._admitted_seq = 0
        self.steps = 0
        self.preemptions = 0
        self.sheds = 0
        self.deadline_misses = 0
        self.retries = 0

        self.brownout = brownout
        # Serve chaos: the resolved (attempt-scoped) plan installs a stall
        # table and a boundary injector; a plan-free engine pays one
        # ``is not None`` check per step and no integrity sweep.
        plan = faultslib.resolve_serve(fault_plan)
        self._stalls = plan.serve_stalls()
        self._fault_fire = faultslib.make_serve_injector(plan, self)
        self._chaos = bool(plan)
        self._stall = stall or time.sleep

        self._aot = aotlib.StepExecutableCache(
            compile_cache.resolve_dir(cfg.compile_cache_dir),
            serve_fingerprint(cfg))
        self._prefill_exec: dict = {}
        self._block_prefill_exec: dict = {}
        self._decode_exec = None
        self._draft_decode_exec = None
        self._verify_exec = None
        self._clone_exec: dict = {}

    # -- public surface ---------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int,
               tenant: str = "default",
               arrival_s: Optional[float] = None,
               trace_id: Optional[int] = None,
               resumed: bool = False) -> Request:
        """Queue one request; admission happens on a later ``step()``.

        ``trace_id``/``resumed`` are tracing metadata: the supervisor
        passes its GLOBAL uid as the trace id (engine uids are local) so
        a re-dispatched request keeps one flow id across replicas, and
        ``resumed=True`` marks a continuation of a flow another process
        opened. Both are ignored when tracing is off."""
        from distributeddeeplearning_tpu.models import generate as genlib

        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt: prefill needs >= 1 token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}: a request "
                             f"that emits nothing never leaves its slot")
        total = len(prompt) + max_new_tokens
        genlib._require_decode(self.model, total, request_totals=[total])
        if total > self.config.slot_capacity:
            raise ValueError(
                f"request needs {total} tokens (prompt {len(prompt)} + "
                f"max_new {max_new_tokens}) but a slot holds at most "
                f"{self.config.slot_capacity} (page_size "
                f"{self.config.page_size} x max_pages_per_slot "
                f"{self.config.max_pages_per_slot})")
        if len(prompt) > max(self.config.prefill_buckets):
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the largest "
                f"prefill bucket {max(self.config.prefill_buckets)}")
        req = Request(uid=self._uid, tenant=tenant, prompt=prompt,
                      max_new_tokens=max_new_tokens,
                      arrival_s=(self._clock() if arrival_s is None
                                 else arrival_s))
        self._uid += 1
        self.waiting.append(req)
        if self._tracer is not None:
            self._tracer.on_submit(req, trace_id, resumed=resumed)
        return req

    @property
    def tracer(self):
        """The serve tracer (``serve/tracing.ServeTracer``), or None when
        telemetry was disabled at construction — callers branch on this
        for attribution-fed reporting (replica anomaly cadence, bench)."""
        return self._tracer

    @property
    def num_live(self) -> int:
        return int(self._live.sum())

    @property
    def idle(self) -> bool:
        return not self.waiting and self.num_live == 0

    def step(self) -> list:
        """One engine step: schedule, expire/cancel deadline-blown work,
        shed under brownout pressure, preempt, admit (+prefill), advance
        every live slot one token, retire finished. Returns the requests
        that finished during this step. Under an active fault plan the
        step opens with an integrity sweep (sabotage from the previous
        boundary must not reach a dispatch) and closes by firing the
        injector."""
        from distributeddeeplearning_tpu.observability import flight, metrics

        if self._chaos:
            self.check_integrity()
        stall_s = self._stalls.get(self.steps + 1)
        if stall_s:
            flight.get().record("fault", kind="decode_stall",
                                step=self.steps + 1, seconds=stall_s,
                                scope="serve")
            self._stall(stall_s)
        now = self._clock()
        finished_before = len(self.finished)
        tr = self._tracer
        if tr is not None:
            # Time since the previous step's end is queue time for
            # everything still waiting (accrued BEFORE the shed pass so
            # a shed request's attribution is complete at finalize).
            tr.on_step_start(self.waiting, now)
        if self.brownout is not None:
            for req in self.brownout.plan_shed(
                    now=now, waiting=list(self.waiting),
                    scheduler=self.scheduler,
                    free_pages=self._free_page_budget(),
                    num_pages=self.config.num_pages):
                self.waiting.remove(req)
                self._fail(req, "shed", now)
        t_plan0 = self._clock() if tr is not None else 0.0
        plan = self.scheduler.plan(
            now=now, waiting=list(self.waiting), live=self._slot_views(),
            free_slots=self.config.max_slots - self.num_live,
            free_pages=self._free_page_budget(),
            page_size=self.config.page_size,
            need_pages=(self._need_pages if self.prefix is not None
                        else None))
        if tr is not None:
            tr.on_plan(plan, t_plan0, self._clock(), step=self.steps,
                       waiting=len(self.waiting))
        for slot in plan.cancel:
            self._cancel(slot, now)
        for req in plan.expire:
            self.waiting.remove(req)
            self._fail(req, "deadline", now)
        for slot in plan.preempt:
            self._preempt(slot, now)
        for req in plan.admit:
            self.waiting.remove(req)
            self._admit(req)
        if self.num_live:
            if self._draft_model is not None:
                self._spec_decode_step()
            else:
                self._decode_step()
        if tr is not None:
            # Classify this step's waiting time per request from the
            # scheduler's non-admission reason (an allocator-race
            # requeue in _admit overrides its own).
            tr.on_step_end(self.waiting, plan, self._clock())
        self.steps += 1
        reg = metrics.get()
        reg.observe("serve_live_slots", self.num_live, step=self.steps)
        reg.observe("serve_page_occupancy",
                    self.allocator.pages_in_use / self.config.num_pages,
                    step=self.steps)
        reg.observe("serve_queue_depth", len(self.waiting), step=self.steps)
        reg.observe("serve_shed_total", self.sheds, step=self.steps)
        reg.observe("serve_deadline_miss_total", self.deadline_misses,
                    step=self.steps)
        reg.observe("serve_retry_total", self.retries, step=self.steps)
        reg.observe("serve_alloc_failures", self.allocator.alloc_failures,
                    step=self.steps)
        if self.prefix is not None:
            admits = self.prefix_hits + self.prefix_misses
            reg.observe("serve_prefix_hit_rate",
                        (self.prefix_hits / admits) if admits else 0.0,
                        step=self.steps)
        if self._draft_model is not None:
            reg.observe("serve_spec_acceptance",
                        (self.spec_accepted / self.spec_proposed)
                        if self.spec_proposed else 0.0, step=self.steps)
        if self._fault_fire is not None:
            self._fault_fire(self.steps)
        return self.finished[finished_before:]

    def run_until_idle(self, *, max_steps: int = 10_000) -> list:
        """Drain queue + slots; returns all finished requests. The step
        bound turns a scheduling livelock into a loud failure."""
        for _ in range(max_steps):
            if self.idle:
                return self.finished
            self.step()
        raise RuntimeError(
            f"engine not idle after {max_steps} steps: "
            f"{len(self.waiting)} waiting, {self.num_live} live — "
            f"scheduling livelock or a request that cannot ever fit")

    def warmup(self) -> dict:
        """Compile (or AOT-load) every program this engine's feature set
        will dispatch, without touching pool contents: dummy prefills
        pack zero positions (plen/n_suffix = 0), dummy decode/verify
        calls have no live rows, the dummy clone copies page 0 onto
        itself — every pool write is dropped or a no-op. Which programs
        exist depends on the config (prefix cache swaps the dense
        prefill for the block suffix prefill + COW clone; speculation
        swaps decode for drafter decode + verify), and all of them key
        off the extended ``serve_fingerprint``, so a warm replica boots
        with zero retraces whatever features are on. Returns
        ``aot_stats()``."""
        import jax.numpy as jnp

        cfg = self.config
        zero_row = np.zeros((cfg.max_pages_per_slot,), np.int32)
        for bucket in sorted(cfg.prefill_buckets):
            if self.prefix is not None:
                self._run_block_prefill(
                    np.zeros((1, bucket), np.int32), n_suffix=0,
                    prefix_len=0, page_row=zero_row, draft=False)
            else:
                self._run_prefill(np.zeros((1, bucket), np.int32), plen=0,
                                  page_row=zero_row)
            if self._draft_model is not None:
                self._run_block_prefill(
                    np.zeros((1, bucket), np.int32), n_suffix=0,
                    prefix_len=0, page_row=zero_row, draft=True)
        if self.prefix is not None:
            # Drive the COW clone program directly (page 0 onto itself):
            # a compile, not a real copy — no counter, no flight event.
            self._pools = self._clone_program(draft=False)(
                self._pools, jnp.int32(0), jnp.int32(0))
            if self._draft_model is not None:
                self._draft_pools = self._clone_program(draft=True)(
                    self._draft_pools, jnp.int32(0), jnp.int32(0))
        if self._draft_model is not None:
            toks, dpools = self._draft_decode_program()(
                self._draft_fresh, jnp.asarray(self._feed),
                jnp.asarray(self._page_table), jnp.asarray(self._d_len),
                jnp.asarray(self._live), self._draft_pools)
            toks.block_until_ready()
            self._draft_pools = dpools
            block = np.zeros((cfg.max_slots, cfg.spec_k + 1), np.int32)
            greedy, pools = self._verify_program()(
                self._fresh, jnp.asarray(block),
                jnp.asarray(self._page_table), jnp.asarray(self._lengths),
                jnp.asarray(self._live),
                jnp.zeros((cfg.max_slots,), jnp.int32), self._pools)
            greedy.block_until_ready()
            self._pools = pools
        else:
            tok, pools = self._decode_program()(
                self._fresh, jnp.asarray(self._feed),
                jnp.asarray(self._page_table), jnp.asarray(self._lengths),
                jnp.asarray(self._live), self._pools)
            tok.block_until_ready()
            self._pools = pools
        return self.aot_stats()

    def aot_stats(self) -> dict:
        return self._aot.stats()

    # -- internals --------------------------------------------------------

    def _slot_views(self) -> list:
        return [_SlotView(slot=i, tenant=s.request.tenant,
                          num_pages=len(s.pages),
                          admitted_seq=s.admitted_seq,
                          arrival_s=s.request.arrival_s)
                for i, s in enumerate(self._slots) if s is not None]

    def _bucket_for(self, plen: int) -> int:
        for b in sorted(self.config.prefill_buckets):
            if plen <= b:
                return b
        raise ValueError(
            f"prefill of {plen} tokens exceeds the largest bucket "
            f"{max(self.config.prefill_buckets)} — after preemption the "
            f"generated prefix re-prefills too; size buckets to "
            f"prompt + max_new_tokens")

    def _program(self, name: str, fn, example_args, donate_argnums):
        """Lower/compile through the AOT executable cache: warm replicas
        deserialize instead of retracing."""
        import jax

        key = self._aot.key(name, example_args)
        cached = self._aot.load(name, key)
        if cached is not None:
            return cached
        compiled = jax.jit(fn, donate_argnums=donate_argnums).lower(
            *example_args).compile()
        self._aot.save(name, key, compiled)
        return compiled

    def _prefill_program(self, bucket: int):
        import jax
        import jax.numpy as jnp

        if bucket in self._prefill_exec:
            return self._prefill_exec[bucket]

        def prefill(fresh, ids, plen, page_row, pools):
            logits, mut = self.model.apply(fresh, ids, train=False,
                                           decode=True, mutable=["cache"])
            pools = kv_cache.pack_prefill_cache(
                mut["cache"], pools, page_row=page_row, plen=plen)
            last = jax.lax.dynamic_slice_in_dim(
                logits, plen - 1, 1, axis=1)[:, 0]
            return jnp.argmax(last, axis=-1).astype(jnp.int32)[0], pools

        example = (self._fresh, jnp.zeros((1, bucket), jnp.int32),
                   jnp.int32(0),
                   jnp.zeros((self.config.max_pages_per_slot,), jnp.int32),
                   self._pools)
        exec_ = self._program(f"serve_prefill_{bucket}", prefill, example,
                              donate_argnums=(4,))
        self._prefill_exec[bucket] = exec_
        return exec_

    def _decode_program(self):
        import jax.numpy as jnp

        if self._decode_exec is not None:
            return self._decode_exec

        def decode(fresh, feed, page_table, lengths, live, pools):
            state = kv_cache.PagedState(page_table, lengths, live)
            logits, mut = self.model.apply(
                {**fresh, "cache": pools}, feed, train=False, decode=True,
                paged_state=state, mutable=["cache"])
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return tok, mut["cache"]

        example = (self._fresh, jnp.asarray(self._feed),
                   jnp.asarray(self._page_table),
                   jnp.asarray(self._lengths), jnp.asarray(self._live),
                   self._pools)
        self._decode_exec = self._program("serve_decode", decode, example,
                                          donate_argnums=(5,))
        return self._decode_exec

    def _block_prefill_program(self, bucket: int, *, draft: bool):
        """Suffix prefill over the paged block path: processes up to
        ``bucket`` suffix tokens at base position ``prefix_len`` against
        a page row whose leading pages already hold the cached prefix
        K/V (mapped shared from the radix tree). One compiled program
        per bucket per model; ``prefix_len``/``n_suffix`` are traced
        scalars, so any split within the bucket reuses it."""
        import jax
        import jax.numpy as jnp

        key = (bucket, draft)
        if key in self._block_prefill_exec:
            return self._block_prefill_exec[key]
        model = self._draft_model if draft else self.model
        fresh = self._draft_fresh if draft else self._fresh
        pools = self._draft_pools if draft else self._pools

        def prefill(fresh, ids, prefix_len, n_suffix, page_row, pools):
            state = kv_cache.PagedBlockState(
                page_table=page_row[None], lengths=prefix_len[None],
                live=jnp.ones((1,), bool), n_new=n_suffix[None])
            logits, mut = model.apply(
                {**fresh, "cache": pools}, ids, train=False, decode=True,
                paged_state=state, mutable=["cache"])
            last = jax.lax.dynamic_slice_in_dim(
                logits, jnp.maximum(n_suffix - 1, 0), 1, axis=1)[:, 0]
            return jnp.argmax(last, axis=-1).astype(jnp.int32)[0], \
                mut["cache"]

        example = (fresh, jnp.zeros((1, bucket), jnp.int32),
                   jnp.int32(0), jnp.int32(0),
                   jnp.zeros((self.config.max_pages_per_slot,), jnp.int32),
                   pools)
        name = (f"serve_draft_prefill_{bucket}" if draft
                else f"serve_prefix_prefill_{bucket}")
        exec_ = self._program(name, prefill, example, donate_argnums=(5,))
        self._block_prefill_exec[key] = exec_
        return exec_

    def _clone_program(self, *, draft: bool):
        """The COW copy: clone one pool page row across every leaf of the
        (target or drafter) pool tree — ``kv_cache.clone_page_rows``
        compiled with donated pools so the clone is in-place on device."""
        import jax.numpy as jnp

        if draft in self._clone_exec:
            return self._clone_exec[draft]
        pools = self._draft_pools if draft else self._pools

        def clone(pools, src, dst):
            return kv_cache.clone_page_rows(pools, src, dst)

        name = "serve_draft_page_clone" if draft else "serve_page_clone"
        exec_ = self._program(name, clone,
                              (pools, jnp.int32(0), jnp.int32(0)),
                              donate_argnums=(0,))
        self._clone_exec[draft] = exec_
        return exec_

    def _draft_decode_program(self):
        """One drafter token for every slot — same shape as the target
        decode program, over the drafter's pools and per-slot drafter
        lengths (the drafter may trail the target by one)."""
        import jax.numpy as jnp

        if self._draft_decode_exec is not None:
            return self._draft_decode_exec
        draft = self._draft_model

        def decode(fresh, feed, page_table, lengths, live, pools):
            state = kv_cache.PagedState(page_table, lengths, live)
            logits, mut = draft.apply(
                {**fresh, "cache": pools}, feed, train=False, decode=True,
                paged_state=state, mutable=["cache"])
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return tok, mut["cache"]

        example = (self._draft_fresh, jnp.asarray(self._feed),
                   jnp.asarray(self._page_table),
                   jnp.asarray(self._d_len), jnp.asarray(self._live),
                   self._draft_pools)
        self._draft_decode_exec = self._program(
            "serve_draft_decode", decode, example, donate_argnums=(5,))
        return self._draft_decode_exec

    def _verify_program(self):
        """One batched target forward over each slot's [feed, proposals]
        block: returns the target's greedy token at every block position.
        Accepting the longest prefix where proposals match this greedy
        output IS sequential greedy decoding — token identity by
        construction. Rejected columns' pool writes land past the
        accepted length and are masked garbage the next block
        overwrites."""
        import jax.numpy as jnp

        if self._verify_exec is not None:
            return self._verify_exec

        def verify(fresh, block, page_table, lengths, live, n_new, pools):
            state = kv_cache.PagedBlockState(page_table, lengths, live,
                                             n_new)
            logits, mut = self.model.apply(
                {**fresh, "cache": pools}, block, train=False, decode=True,
                paged_state=state, mutable=["cache"])
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return greedy, mut["cache"]

        cfg = self.config
        example = (self._fresh,
                   jnp.zeros((cfg.max_slots, cfg.spec_k + 1), jnp.int32),
                   jnp.asarray(self._page_table),
                   jnp.asarray(self._lengths), jnp.asarray(self._live),
                   jnp.zeros((cfg.max_slots,), jnp.int32), self._pools)
        self._verify_exec = self._program("serve_verify", verify, example,
                                          donate_argnums=(6,))
        return self._verify_exec

    def _run_block_prefill(self, padded: np.ndarray, *, n_suffix: int,
                           prefix_len: int, page_row: np.ndarray,
                           draft: bool) -> int:
        import jax.numpy as jnp

        bucket = padded.shape[1]
        exec_ = self._block_prefill_program(bucket, draft=draft)
        fresh = self._draft_fresh if draft else self._fresh
        pools = self._draft_pools if draft else self._pools
        tok, pools = exec_(fresh, jnp.asarray(padded),
                           jnp.int32(prefix_len), jnp.int32(n_suffix),
                           jnp.asarray(page_row), pools)
        if draft:
            self._draft_pools = pools
        else:
            self._pools = pools
        return int(tok)

    def _run_page_copy(self, src: int, dst: int) -> None:
        """Copy-on-write a shared page into a slot-private one (target
        pools and, under speculation, drafter pools). Flight-logged
        BEFORE the copy dispatches — the ddl-lint ``cow-before-write``
        rule pins callers to the same record-then-dispatch discipline
        the page-table rule established."""
        import jax.numpy as jnp

        from distributeddeeplearning_tpu.observability import flight

        flight.get().record("serve_cow_copy", src=int(src), dst=int(dst))
        self.cow_copies += 1
        self._pools = self._clone_program(draft=False)(
            self._pools, jnp.int32(src), jnp.int32(dst))
        if self._draft_model is not None:
            self._draft_pools = self._clone_program(draft=True)(
                self._draft_pools, jnp.int32(src), jnp.int32(dst))

    def _free_page_budget(self) -> int:
        """Pages admission control may count on: the allocator's free
        list plus everything the prefix cache could evict on demand."""
        free = self.allocator.free_pages
        if self.prefix is not None:
            free += self.prefix.evictable_pages()
        return free

    def _need_pages(self, req: Request) -> int:
        """Scheduler callback under the prefix cache: charge only the
        NEW pages an admission would allocate — full pages matched in
        the radix tree are mapped shared, not taken from the free
        list (the COW clone of a partial trailing page counts as
        new)."""
        cfg = self.config
        matched, _ = self.prefix.match(req.prefill_ids)
        prefix_len = min(matched, len(req.prefill_ids) - 1)
        return (kv_cache.pages_needed(req.total_tokens, cfg.page_size)
                - prefix_len // cfg.page_size)

    def _assert_cow_writable(self, slot: int, start: int,
                             count: int) -> None:
        """Pages about to receive in-place writes for positions
        ``[start, start+count)`` of ``slot`` must be exclusively held —
        the runtime half of the COW discipline (a shared page here means
        admission mapped a page it should have cloned)."""
        if self.prefix is None or count <= 0:
            return
        ps = self.config.page_size
        row = self._page_table[slot]
        pages = {int(row[j]) for j in range(start // ps,
                                            (start + count - 1) // ps + 1)}
        self.allocator.assert_writable(pages)

    def _run_prefill(self, padded: np.ndarray, *, plen: int,
                     page_row: np.ndarray) -> int:
        import jax.numpy as jnp

        bucket = padded.shape[1]
        tok, pools = self._prefill_program(bucket)(
            self._fresh, jnp.asarray(padded), jnp.int32(plen),
            jnp.asarray(page_row), self._pools)
        self._pools = pools
        return int(tok)

    def _admit(self, req: Request) -> None:
        from distributeddeeplearning_tpu.observability import flight

        cfg = self.config
        tr = self._tracer
        t_adm0 = self._clock() if tr is not None else 0.0
        if tr is not None:
            # Time from step start to here served OTHER requests
            # (expire/preempt handling, earlier admissions' prefills).
            tr.on_admit_start(req, t_adm0)
        slot = next(i for i, s in enumerate(self._slots) if s is None)
        ids = req.prefill_ids
        plen = len(ids)

        # Radix walk: full matched pages map in shared; the partially
        # reused trailing page of a fully-cached prompt is cloned
        # copy-on-write (at least one suffix token always re-runs so the
        # prefill can emit). Matched pages are pinned (incref) up front
        # so the eviction below can never free them out from under us.
        prefix_len = 0
        shared: list = []
        cow_src: Optional[int] = None
        if self.prefix is not None:
            matched, mpages = self.prefix.match(ids)
            prefix_len = min(matched, plen - 1)
            full = prefix_len // cfg.page_size
            shared = [int(p) for p in mpages[:full]]
            self.allocator.incref(shared)
            if prefix_len % cfg.page_size:
                cow_src = int(mpages[full])
                self.allocator.incref([cow_src])
        need_total = kv_cache.pages_needed(req.total_tokens, cfg.page_size)
        need_new = need_total - len(shared)
        new_pages = self.allocator.alloc(need_new)
        if new_pages is None and self.prefix is not None:
            # The free list is short but the tree holds reclaimable
            # pages: evict LRU refcount-1 nodes and retry.
            self.prefix.evict(need_new - self.allocator.free_pages)
            new_pages = self.allocator.alloc(need_new)
        if new_pages is None:  # scheduler raced itself — re-queue
            self.allocator.decref(shared)
            if cow_src is not None:
                self.allocator.decref([cow_src])
            self.waiting.appendleft(req)
            if tr is not None:
                tr.on_requeue(req, self._clock(), step=self.steps)
            return
        pages = shared + new_pages
        self._admitted_seq += 1
        self._slots[slot] = _Slot(request=req, pages=pages,
                                  admitted_seq=self._admitted_seq)
        page_row = np.zeros((cfg.max_pages_per_slot,), np.int32)
        page_row[:need_total] = pages
        self._page_table[slot] = page_row

        if self.prefix is not None:
            if prefix_len > 0:
                self.prefix_hits += 1
                self.prefix_tokens_reused += prefix_len
            else:
                self.prefix_misses += 1
        flight.get().record("serve_admit", request=req.uid,
                            tenant=req.tenant, slot=slot, pages=need_total,
                            new_pages=need_new, prefix_tokens=prefix_len,
                            resumed=bool(req.tokens))
        if tr is not None:
            tr.on_alloc(req, t_adm0, self._clock(), step=self.steps,
                        slot=slot, new_pages=need_new,
                        shared_pages=len(shared),
                        prefix_tokens=prefix_len,
                        prefix_cache=self.prefix is not None,
                        cow=cow_src is not None)
        if cow_src is not None:
            t_cow0 = self._clock() if tr is not None else 0.0
            self._run_page_copy(cow_src, pages[len(shared)])
            self.allocator.decref([cow_src])  # unpin the clone source
            if tr is not None:
                tr.on_cow_copy(req, t_cow0, self._clock(),
                               step=self.steps, src=cow_src,
                               dst=pages[len(shared)])
        n_suffix = plen - prefix_len
        t_pf0 = self._clock() if tr is not None else 0.0
        if self.prefix is not None:
            self._assert_cow_writable(slot, prefix_len, n_suffix)
            bucket = self._bucket_for(n_suffix)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n_suffix] = ids[prefix_len:]
            tok = self._run_block_prefill(padded, n_suffix=n_suffix,
                                          prefix_len=prefix_len,
                                          page_row=page_row, draft=False)
        else:
            bucket = self._bucket_for(plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = ids
            tok = self._run_prefill(padded, plen=plen, page_row=page_row)
        if self._draft_model is not None:
            # Drafter prefills the same suffix over its own pools (shared
            # prefix pages already hold drafter K/V from their original
            # admission), so proposals start from a fully-caught-up
            # drafter.
            dbucket = self._bucket_for(n_suffix)
            dpadded = np.zeros((1, dbucket), np.int32)
            dpadded[0, :n_suffix] = ids[prefix_len:]
            self._run_block_prefill(dpadded, n_suffix=n_suffix,
                                    prefix_len=prefix_len,
                                    page_row=page_row, draft=True)
            self._d_len[slot] = plen
        if self.prefix is not None:
            self.prefix.insert(ids, pages)
        now = self._clock()
        flight.get().record("serve_prefill", request=req.uid, slot=slot,
                            bucket=bucket, prompt_tokens=plen)
        first = req.ttft_s is None
        resumed = bool(req.tokens)  # read BEFORE emit appends
        req.emit(tok, now)
        if tr is not None:
            tr.on_prefill(req, t_pf0, now, step=self.steps, slot=slot,
                          bucket=bucket,
                          prefill_tokens=(n_suffix
                                          if self.prefix is not None
                                          else plen),
                          prefix_tokens=prefix_len, first=first,
                          resumed=resumed)
        if first:
            from distributeddeeplearning_tpu.observability import metrics
            metrics.get().observe("serve_ttft_s", req.ttft_s,
                                  step=self.steps)
            flight.get().record("serve_first_token", request=req.uid,
                                slot=slot, ttft_s=round(req.ttft_s, 6))
        self._lengths[slot] = plen
        self._live[slot] = True
        self._feed[slot, 0] = tok
        if req.remaining == 0:
            self._retire(slot, now)

    def _decode_step(self) -> None:
        import jax.numpy as jnp

        tr = self._tracer
        t_d0 = self._clock() if tr is not None else 0.0
        for i in np.flatnonzero(self._live):
            self._assert_cow_writable(int(i), int(self._lengths[i]), 1)
        toks, pools = self._decode_program()(
            self._fresh, jnp.asarray(self._feed),
            jnp.asarray(self._page_table), jnp.asarray(self._lengths),
            jnp.asarray(self._live), self._pools)
        self._pools = pools
        toks = np.asarray(toks)
        now = self._clock()
        if tr is not None:
            # Accrues decode for every participant BEFORE the retire
            # loop below finalizes any of them.
            tr.on_decode(t_d0, now, step=self.steps,
                         slots=[(int(i), self._slots[i].request)
                                for i in np.flatnonzero(self._live)])
        for i in np.flatnonzero(self._live):
            req = self._slots[i].request
            req.emit(toks[i], now)
            self._lengths[i] += 1
            self._feed[i, 0] = toks[i]
            if req.remaining == 0:
                self._retire(int(i), now)

    def _spec_decode_step(self) -> None:
        """One speculative round for every live slot: the drafter
        proposes up to ``spec_k`` tokens (catching up its one-token lag
        first), one batched target forward verifies the whole
        ``[feed, proposals]`` block, and the longest prefix of proposals
        matching the target's own greedy output is accepted — plus the
        target's next token after the accepted prefix (the "bonus"
        token), so even an all-rejected round advances one token exactly
        like ``_decode_step``. Token-identical to sequential greedy by
        construction: every emitted token is the target's argmax given
        the same cached context.

        Per-slot bounds: ``n <= remaining - 1`` (the round emits at most
        ``n + 1`` tokens), and the drafter only steps while a slot still
        needs catch-up or proposals (``active`` mask) so its writes can
        never run past the slot's page budget."""
        import jax.numpy as jnp

        cfg = self.config
        tr = self._tracer
        t_d0 = self._clock() if tr is not None else 0.0
        live_idx = [int(i) for i in np.flatnonzero(self._live)]
        L = self._lengths.copy()
        d = self._d_len.copy()
        n_prop = np.zeros((cfg.max_slots,), np.int32)
        steps_needed = np.zeros((cfg.max_slots,), np.int32)
        proposals: list = [[] for _ in range(cfg.max_slots)]
        for i in live_idx:
            req = self._slots[i].request
            lag = int(L[i]) - int(d[i])
            n_prop[i] = min(cfg.spec_k, req.remaining - 1)
            steps_needed[i] = lag + int(n_prop[i])
            # Drafter writes [d, L+n) and verify writes [L, L+n]: all of
            # it must be exclusively-held pages (COW discipline).
            self._assert_cow_writable(i, int(d[i]),
                                      int(L[i]) + int(n_prop[i]) + 1
                                      - int(d[i]))
        feed = np.zeros((cfg.max_slots, 1), np.int32)
        for r in range(int(steps_needed.max()) if live_idx else 0):
            active = np.zeros((cfg.max_slots,), bool)
            for i in live_idx:
                if r >= steps_needed[i]:
                    continue
                active[i] = True
                pos = int(d[i])
                if pos <= int(L[i]):
                    # Catch-up / first proposal: the token at this
                    # position is already known (prompt + emitted).
                    feed[i, 0] = self._slots[i].request.output_ids[pos]
                else:
                    feed[i, 0] = proposals[i][pos - int(L[i]) - 1]
            toks, dpools = self._draft_decode_program()(
                self._draft_fresh, jnp.asarray(feed),
                jnp.asarray(self._page_table), jnp.asarray(d),
                jnp.asarray(active), self._draft_pools)
            self._draft_pools = dpools
            toks = np.asarray(toks)
            for i in live_idx:
                if active[i]:
                    if int(d[i]) >= int(L[i]):
                        proposals[i].append(int(toks[i]))
                    d[i] += 1
        t_draft1 = self._clock() if tr is not None else 0.0
        block = np.zeros((cfg.max_slots, cfg.spec_k + 1), np.int32)
        n_new = np.zeros((cfg.max_slots,), np.int32)
        for i in live_idx:
            block[i, 0] = self._feed[i, 0]
            for j in range(int(n_prop[i])):
                block[i, 1 + j] = proposals[i][j]
            n_new[i] = int(n_prop[i]) + 1
        greedy, pools = self._verify_program()(
            self._fresh, jnp.asarray(block), jnp.asarray(self._page_table),
            jnp.asarray(self._lengths), jnp.asarray(self._live),
            jnp.asarray(n_new), self._pools)
        self._pools = pools
        greedy = np.asarray(greedy)
        now = self._clock()
        self.spec_rounds += 1
        round_proposed = round_accepted = 0
        if tr is not None:
            tr.on_decode(t_d0, now, step=self.steps,
                         slots=[(i, self._slots[i].request,
                                 {"spec": True,
                                  "proposed": int(n_prop[i])})
                                for i in live_idx])
        for i in live_idx:
            req = self._slots[i].request
            n = int(n_prop[i])
            m = 0
            while m < n and proposals[i][m] == int(greedy[i, m]):
                m += 1
            self.spec_proposed += n
            self.spec_accepted += m
            round_proposed += n
            round_accepted += m
            for j in range(m + 1):
                req.emit(int(greedy[i, j]), now)
            new_len = int(L[i]) + m + 1
            self._lengths[i] = new_len
            self._feed[i, 0] = int(greedy[i, m])
            # Drafter cache is valid through the last position fed a
            # true token — at most one behind the target after a fully
            # accepted round.
            self._d_len[i] = min(int(d[i]), new_len)
            if req.remaining == 0:
                self._retire(i, now)
        if tr is not None:
            tr.on_spec_phases(
                t_d0, t_draft1, now, step=self.steps,
                rounds=int(steps_needed.max()) if live_idx else 0,
                proposed=round_proposed, accepted=round_accepted)

    def _retire(self, slot: int, now: float) -> None:
        from distributeddeeplearning_tpu.observability import flight

        entry = self._slots[slot]
        req = entry.request
        req.finished_s = now
        # release() + pages=[]: retirement is idempotent — a request that
        # already walked a victim path cannot double-free (the one bug the
        # strict free() exists to catch in non-victim paths).
        self.allocator.release(entry.pages)
        entry.pages = []
        self._clear_slot(slot)
        self.finished.append(req)
        flight.get().record("serve_retire", request=req.uid, slot=slot,
                            tokens=len(req.tokens),
                            preemptions=req.preemptions)
        if self._tracer is not None:
            self._tracer.finalize(req, now, status="ok")

    def _preempt(self, slot: int, now: float) -> None:
        from distributeddeeplearning_tpu.observability import flight

        entry = self._slots[slot]
        req = entry.request
        req.preemptions += 1
        req._last_emit_s = None  # the gap back through the queue is not ITL
        self.allocator.release(entry.pages)
        entry.pages = []
        self._clear_slot(slot)
        self.preemptions += 1
        flight.get().record("serve_preempt", request=req.uid, slot=slot,
                            tenant=req.tenant,
                            tokens_done=len(req.tokens))
        if self._tracer is not None:
            self._tracer.on_preempt(req, now, step=self.steps, slot=slot)
        # Bounded retry with exponential backoff: the scheduler owns the
        # policy, the engine applies it on every re-queue.
        req.retries += 1
        self.retries += 1
        max_r = self.scheduler.max_retries
        if max_r is not None and req.retries > max_r:
            self._fail(req, "retries_exhausted", now)
            return
        delay = self.scheduler.retry_delay_s(req.retries)
        if delay > 0:
            req.not_before_s = now + delay
        self.waiting.append(req)

    def _cancel(self, slot: int, now: float) -> None:
        """A live slot whose request blew its total-latency deadline:
        return the slot and pages, fail the request as a deadline miss."""
        entry = self._slots[slot]
        req = entry.request
        self.allocator.release(entry.pages)
        entry.pages = []
        self._clear_slot(slot)
        if self._tracer is not None:
            self._tracer.on_cancel(req, now)
        self._fail(req, "deadline", now)

    def _fail(self, req: Request, reason: str, now: float) -> None:
        from distributeddeeplearning_tpu.observability import flight

        req.failed = reason
        req.finished_s = now
        self.failed.append(req)
        if reason == "deadline":
            self.deadline_misses += 1
            flight.get().record("serve_deadline_miss", request=req.uid,
                                tenant=req.tenant,
                                waited_s=round(now - req.arrival_s, 6),
                                tokens_done=len(req.tokens))
        else:
            self.sheds += 1
            flight.get().record("serve_shed", request=req.uid,
                                tenant=req.tenant, reason=reason,
                                tokens_done=len(req.tokens))
        if self._tracer is not None:
            self._tracer.on_fail(req, now, reason=reason)

    def _clear_slot(self, slot: int) -> None:
        self._slots[slot] = None
        self._live[slot] = False
        self._lengths[slot] = 0
        self._d_len[slot] = 0
        self._feed[slot, 0] = 0
        self._page_table[slot] = 0

    # -- integrity / chaos hooks ------------------------------------------

    def check_integrity(self) -> None:
        """Reconcile the three views of page ownership — slot page-table
        rows, slot owned-page lists, allocator accounting — and raise on
        any divergence. Runs before every dispatch under an active fault
        plan and unconditionally at shutdown: a leaked page starves
        admission later; a corrupt row serves another slot's K/V now."""
        owned: list = []
        for i, entry in enumerate(self._slots):
            if entry is None:
                continue
            row = [int(p) for p in self._page_table[i, :len(entry.pages)]]
            pages = [int(p) for p in entry.pages]
            if row != pages:
                raise RuntimeError(
                    f"page-table corruption: slot {i} row {row} != owned "
                    f"pages {pages}")
            owned.extend(pages)
        if self.prefix is not None:
            # Tree nodes hold their own claims: one per node, and a page
            # shared with live slots must be counted once per holder.
            owned.extend(self.prefix.owned_pages())
        self.allocator.check_leaks(owned)

    def corrupt_page_table(self) -> Optional[int]:
        """Fault-injection hook (``corrupt_page_table@N``): scribble over
        the first live slot's page-table row. Returns the slot hit, or
        None when nothing is live to corrupt."""
        for i, entry in enumerate(self._slots):
            if entry is not None and entry.pages:
                self._page_table[i, 0] = (
                    int(self._page_table[i, 0]) + 1) % self.config.num_pages
                return i
        return None

    def shutdown(self) -> None:
        """Final gate: flight-record the lifetime counters, then assert
        page accounting balances (allocated == sum of live page tables).
        Raises RuntimeError on a leak — a replica that leaks pages must
        exit loudly, not report success."""
        from distributeddeeplearning_tpu.observability import flight

        flight.get().record("serve_shutdown", steps=self.steps,
                            finished=len(self.finished),
                            failed=len(self.failed),
                            preemptions=self.preemptions,
                            sheds=self.sheds,
                            deadline_misses=self.deadline_misses,
                            prefix_hits=self.prefix_hits,
                            prefix_misses=self.prefix_misses,
                            prefix_tokens_reused=self.prefix_tokens_reused,
                            prefix_evictions=(self.prefix.evictions
                                              if self.prefix is not None
                                              else 0),
                            cow_copies=self.cow_copies,
                            spec_rounds=self.spec_rounds,
                            spec_proposed=self.spec_proposed,
                            spec_accepted=self.spec_accepted)
        self.check_integrity()
