"""DenseNet family in Flax, TPU-first (acceptance config 3, BASELINE.json:9).

Same mixed-precision policy as resnet.py (bf16 compute / f32 params+BN
stats, NHWC). Dense connectivity is expressed as a rolling ``jnp.concatenate``
on the channel axis — static shapes throughout, so XLA tiles every conv onto
the MXU. Parameter counts match torchvision densenet{121,169}
(tests/test_models.py).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class DenseLayer(nn.Module):
    """BN-ReLU-1x1 bottleneck (4k) -> BN-ReLU-3x3 (k); returns new features."""

    growth_rate: int
    conv: Any
    norm: Any

    @nn.compact
    def __call__(self, x):
        y = self.norm(name="bn1")(x)
        y = nn.relu(y)
        y = self.conv(4 * self.growth_rate, (1, 1), name="conv1")(y)
        y = self.norm(name="bn2")(y)
        y = nn.relu(y)
        y = self.conv(self.growth_rate, (3, 3), name="conv2")(y)
        return y


class DenseNet(nn.Module):
    """ImageNet DenseNet-BC. NHWC in, float32 logits out."""

    block_sizes: Sequence[int]
    growth_rate: int = 32
    num_init_features: int = 64
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    # SyncBN: mesh axis name(s) for cross-replica statistics (pmean),
    # bound only inside the shard_map DP step; None = per-shard BN.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.variance_scaling(
                2.0, "fan_out", "normal"),
            padding="SAME")
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
            axis_name=self.bn_axis_name if train else None)

        x = jnp.asarray(x, self.dtype)
        # Explicit (3,3) stem padding: torch-symmetric, like models/resnet.py
        # (XLA SAME would pad (2,3) at stride 2 — a one-pixel shift).
        x = conv(self.num_init_features, (7, 7), strides=(2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_stem")(x)
        x = norm(name="bn_stem")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        num_features = self.num_init_features
        for i, num_layers in enumerate(self.block_sizes):
            for j in range(num_layers):
                new = DenseLayer(self.growth_rate, conv, norm,
                                 name=f"block{i + 1}_layer{j + 1}")(x)
                x = jnp.concatenate([x, new], axis=-1)
            num_features += num_layers * self.growth_rate
            if i != len(self.block_sizes) - 1:
                # Transition: BN-ReLU-1x1 (halve channels) -> 2x2 avg pool.
                x = norm(name=f"transition{i + 1}_bn")(x)
                x = nn.relu(x)
                num_features //= 2
                x = conv(num_features, (1, 1), name=f"transition{i + 1}_conv")(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))

        x = norm(name="bn_final")(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32,
                     kernel_init=nn.initializers.variance_scaling(
                         1.0, "fan_in", "truncated_normal"),
                     name="classifier")(x)
        return jnp.asarray(x, jnp.float32)


def densenet121(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
                bn_axis_name: Any = None) -> DenseNet:
    return DenseNet([6, 12, 24, 16], num_classes=num_classes, dtype=dtype,
                    bn_axis_name=bn_axis_name)


def densenet169(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
                bn_axis_name: Any = None) -> DenseNet:
    return DenseNet([6, 12, 32, 32], num_classes=num_classes, dtype=dtype,
                    bn_axis_name=bn_axis_name)
