"""BERT encoder + masked-LM head in Flax, TPU-first (config 4, BASELINE.json:10).

Sharding-aware by construction: every kernel is annotated with *logical* axis
names via ``nn.with_logical_partitioning``; parallel/sharding.py maps logical
axes onto the device mesh (tp shards "mlp"/"heads" on the ``model`` axis, sp
shards activations on the ``seq`` axis). With a trivial mesh the annotations
are inert, so single-chip and sharded paths share one module.

The MLM decoder is weight-tied to the word embedding (transpose), matching
the canonical BERT-base parameterization (109,514,298 params including the
tied head — asserted in tests/test_models.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from distributeddeeplearning_tpu.ops.embedding import embedding_lookup

Dtype = Any


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12
    # "dense": materialized (S, S) scores, XLA-fused — right for short seqs.
    # "ring": blockwise ring attention over the `seq` mesh axis
    #   (parallel/ring_attention.py) — O(S_local) memory, exact, long-context.
    # Like flash kernels, "ring" skips attention-probability dropout (the
    # probs are never materialized); all other dropouts apply unchanged.
    attention_impl: str = "dense"
    # Mixture-of-Experts: num_experts > 0 replaces the FFN of every
    # ``moe_every``-th layer with a top-1-routed expert MLP (models/moe.py),
    # expert-parallel over the ``expert`` mesh axis.
    num_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1              # 1 = Switch, 2 = GShard routing
    moe_aux_weight: float = 0.01
    # Pipeline parallelism: pipeline_stages > 1 runs the encoder stack as a
    # pipeline schedule over the ``pipeline`` mesh axis (models/pipeline.py);
    # num_layers must divide evenly into stages. Incompatible with MoE
    # layers (the stages must be homogeneous). Schedule "gpipe" is
    # fill/drain; "1f1b" interleaves pipeline_virtual_stages chunks per
    # stage to shrink the bubble (docs/pipeline.md).
    pipeline_stages: int = 1
    pipeline_microbatches: int = 4
    pipeline_schedule: str = "gpipe"
    pipeline_virtual_stages: int = 1
    # Rematerialization: recompute each encoder layer's activations in the
    # backward pass instead of storing them — trades ~1/3 more FLOPs for
    # O(num_layers) less activation HBM (the long-context/deep-model knob).
    remat: bool = False


def _dense(features, logical_axes, name, dtype, use_bias=True):
    return nn.Dense(
        features, dtype=dtype, param_dtype=jnp.float32, use_bias=use_bias,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(0.02), logical_axes),
        name=name)


class SelfAttention(nn.Module):
    cfg: BertConfig
    dtype: Dtype

    @nn.compact
    def __call__(self, x, mask, *, deterministic: bool):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        # QKV projections: output dim shards on the tensor-parallel axis.
        q = _dense(cfg.hidden_size, ("embed", "heads"), "query", self.dtype)(x)
        k = _dense(cfg.hidden_size, ("embed", "heads"), "key", self.dtype)(x)
        v = _dense(cfg.hidden_size, ("embed", "heads"), "value", self.dtype)(x)

        b, s, _ = q.shape
        q = q.reshape(b, s, cfg.num_heads, head_dim)
        k = k.reshape(b, s, cfg.num_heads, head_dim)
        v = v.reshape(b, s, cfg.num_heads, head_dim)

        from distributeddeeplearning_tpu.ops.attention import (
            multihead_attention)
        out = multihead_attention(
            q, k, v, mask, impl=cfg.attention_impl, causal=False,
            dtype=self.dtype, dropout_rate=cfg.dropout_rate,
            dropout_rng=(self.make_rng("dropout")
                         if not deterministic and cfg.dropout_rate > 0
                         else None),
            deterministic=deterministic)
        # Output projection: input dim sharded -> XLA reduces over tp axis.
        return _dense(cfg.hidden_size, ("heads", "embed"), "output", self.dtype)(out)


class EncoderLayer(nn.Module):
    cfg: BertConfig
    dtype: Dtype
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, mask, *, deterministic: bool):
        cfg = self.cfg
        attn = SelfAttention(cfg, self.dtype, name="attention")(
            x, mask, deterministic=deterministic)
        attn = nn.Dropout(cfg.dropout_rate)(attn, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="attention_ln")(x + attn)
        if self.use_moe:
            from distributeddeeplearning_tpu.models.moe import MoeMlp
            h = MoeMlp(hidden_size=cfg.hidden_size,
                       intermediate_size=cfg.intermediate_size,
                       num_experts=cfg.num_experts,
                       capacity_factor=cfg.moe_capacity_factor,
                       router_top_k=cfg.moe_top_k,
                       dtype=self.dtype, name="moe_mlp")(
                           x, deterministic=deterministic)
        else:
            h = _dense(cfg.intermediate_size, ("embed", "mlp"), "intermediate",
                       self.dtype)(x)
            h = nn.gelu(h, approximate=False)
            h = _dense(cfg.hidden_size, ("mlp", "embed"), "mlp_output",
                       self.dtype)(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                            param_dtype=jnp.float32, name="mlp_ln")(x + h)


class BertMLM(nn.Module):
    """Encoder + transform + tied decoder; returns f32 logits of shape
    (B, S, vocab), or (B, P, vocab) when ``masked_positions`` (B, P) selects
    the gather-mode head."""

    cfg: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 masked_positions=None, *, train: bool = True):
        cfg = self.cfg
        deterministic = not train
        b, s = input_ids.shape
        if s > cfg.max_position:
            raise ValueError(
                f"sequence length {s} exceeds max_position "
                f"{cfg.max_position}; build the model with seq_len={s}")
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), jnp.bool_)
        else:
            attention_mask = attention_mask.astype(jnp.bool_)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, s), jnp.int32)

        word_emb = self.param(
            "word_embeddings",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        pos_emb = self.param(
            "position_embeddings",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         (None, "embed")),
            (cfg.max_position, cfg.hidden_size), jnp.float32)
        type_emb = self.param(
            "type_embeddings",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         (None, "embed")),
            (cfg.type_vocab_size, cfg.hidden_size), jnp.float32)

        # embedding_lookup (not table[ids]): its custom backward keeps the
        # fsdp-sharded table gradient off XLA's replicate-the-updates
        # scatter path (ops/embedding.py; VERDICT r4 Missing #5).
        x = (embedding_lookup(word_emb, input_ids) + pos_emb[None, :s]
             + embedding_lookup(type_emb, token_type_ids))
        x = x.astype(self.dtype)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="embeddings_ln")(x)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)
        # Sequence-parallel hint: activations shard (data, seq, -).
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        if cfg.pipeline_stages > 1:
            import functools

            from distributeddeeplearning_tpu.models.pipeline import (
                build_pipelined)
            if cfg.num_experts > 0:
                raise ValueError(
                    "pipeline_stages > 1 requires homogeneous layers; "
                    "disable MoE (num_experts=0)")
            x = build_pipelined(
                functools.partial(EncoderLayer, cfg, self.dtype),
                num_layers=cfg.num_layers, num_stages=cfg.pipeline_stages,
                num_microbatches=cfg.pipeline_microbatches,
                schedule=cfg.pipeline_schedule,
                virtual_stages=cfg.pipeline_virtual_stages,
                remat=cfg.remat, dtype=self.dtype)(
                    x, attention_mask, deterministic=deterministic)
            x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        else:
            for i in range(cfg.num_layers):
                use_moe = (cfg.num_experts > 0
                           and i % cfg.moe_every == cfg.moe_every - 1)
                layer = EncoderLayer(cfg, self.dtype, use_moe=use_moe,
                                     name=f"layer{i}")
                if cfg.remat:
                    # Function-lift form: `deterministic` stays a closed-over
                    # Python bool (a traced bool would concretize inside
                    # Dropout), x/mask are the remat-checkpointed inputs.
                    x = nn.remat(
                        lambda mdl, h, msk: mdl(
                            h, msk, deterministic=deterministic))(
                        layer, x, attention_mask)
                else:
                    x = layer(x, attention_mask,
                              deterministic=deterministic)
                x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        # Gather-mode head (config.data.mlm_max_predictions): project only
        # the masked positions to vocab. Every head op below is per-position,
        # so gathering before the head equals gathering dense logits after it
        # — at 15% masking that is ~6.7x less head matmul FLOPs and f32
        # logits traffic (the canonical BERT/MLPerf structure).
        if masked_positions is not None:
            x = jnp.take_along_axis(
                x, masked_positions[:, :, None].astype(jnp.int32), axis=1)
        # MLM head: transform -> LayerNorm -> tied decoder + bias.
        h = _dense(cfg.hidden_size, ("embed", "embed_out"), "mlm_transform",
                   self.dtype)(x)
        h = nn.gelu(h, approximate=False)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="mlm_ln")(h)
        logits = jnp.einsum("bsh,vh->bsv", h, word_emb.astype(self.dtype))
        bias = self.param("mlm_bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.float32)
        return logits.astype(jnp.float32) + bias


def _fit_positions(cfg: BertConfig, seq_len: Optional[int]) -> BertConfig:
    """Grow the position table when the run's sequence outsizes it; the
    canonical table (and so the canonical param count) is kept otherwise."""
    if seq_len and seq_len > cfg.max_position:
        cfg = dataclasses.replace(cfg, max_position=seq_len)
    return cfg


def bert_base_mlm(vocab_size: int = 30522, dtype: Dtype = jnp.bfloat16,
                  seq_len: Optional[int] = None, **overrides: Any) -> BertMLM:
    cfg = BertConfig(vocab_size=vocab_size, **overrides)
    return BertMLM(_fit_positions(cfg, seq_len), dtype=dtype)


def bert_large_mlm(vocab_size: int = 30522, dtype: Dtype = jnp.bfloat16,
                   seq_len: Optional[int] = None, **overrides: Any) -> BertMLM:
    cfg = BertConfig(vocab_size=vocab_size, hidden_size=1024, num_layers=24,
                     num_heads=16, intermediate_size=4096, **overrides)
    return BertMLM(_fit_positions(cfg, seq_len), dtype=dtype)


def tiny_bert_mlm(vocab_size: int = 1024, dtype: Dtype = jnp.float32,
                  seq_len: Optional[int] = None, **overrides: Any) -> BertMLM:
    """Test-sized BERT (used by unit tests and dryrun_multichip)."""
    cfg = BertConfig(vocab_size=vocab_size,
                     **{"hidden_size": 64, "num_layers": 2, "num_heads": 4,
                        "intermediate_size": 128, "max_position": 128,
                        **overrides})
    return BertMLM(_fit_positions(cfg, seq_len), dtype=dtype)
