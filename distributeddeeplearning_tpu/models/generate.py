"""Autoregressive generation for the causal LM families (GPT, Llama).

Two paths:

- default (``use_cache=False``): one fixed-shape padded forward per
  emitted token inside a single jitted ``lax.scan`` — no cache plumbing,
  so it works unchanged for every causal variant (dense/flash attention,
  remat, pipelined, Llama). O(S^2) per token.
- ``use_cache=True`` (GPT and Llama families): KV-cache incremental
  decoding — the model's ``decode=True`` mode appends each token's K/V to
  per-layer caches (GPT: (B, max_position, H, D); Llama: kv-head width,
  the GQA saving, sized by ``cfg.decode_cache_len`` — size it to
  prompt+new tokens, as the CLI does) and attends over the live prefix,
  O(S) per token. The prompt primes the cache in ONE batched prefill
  forward. Outputs are identical to the full-refeed path at the same
  seed, greedy and sampled (tests/test_generate.py asserts both).

Sampling: greedy (temperature=0) or temperature softmax with optional
top-k truncation. Fully deterministic given (params, prompt, seed).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def decode_capacity(model) -> Optional[int]:
    """The model's static decode position/cache bound: ``max_position``
    (GPT — the learned position table is the binding constraint) or
    ``decode_cache_len`` (Llama — RoPE has no table, only the KV buffer).
    None for models without either (no decode mode, or unbounded)."""
    mcfg = getattr(model, "cfg", None)
    return (getattr(mcfg, "max_position", None)
            or getattr(mcfg, "decode_cache_len", None))


def _require_decode(model, total: int, *, request_totals=None) -> None:
    """Shared use_cache preconditions for the sampling and beam paths.

    The models validate only the PREFILL block length themselves; the
    single-token emission steps afterwards would write past the cache
    (clamped by dynamic_update_slice — silently degenerate), so the full
    prompt+new budget is checked here, against ``max_position`` (GPT) or
    ``decode_cache_len`` (Llama — size it to prompt+new, as the CLI does).

    ``request_totals``: optional per-request (prompt + max_new) budgets for
    ragged callers (the serve engine's admission path) — the error then
    names WHICH request overflows and by how much, not just the batch
    aggregate, so the engine can reject exactly the offending request.
    """
    import inspect

    if "decode" not in inspect.signature(model.__call__).parameters:
        raise ValueError(
            f"use_cache=True needs a model with a decode (KV-cache) mode — "
            f"the GPT/Llama families; {type(model).__name__} has none. "
            f"Use the default full-refeed path.")
    max_pos = decode_capacity(model)
    if max_pos is None:
        return
    if request_totals is not None:
        over = [(i, int(t)) for i, t in enumerate(request_totals)
                if t > max_pos]
        if over:
            i, t = over[0]
            raise ValueError(
                f"request {i} needs cache/position capacity {t} (prompt + "
                f"max_new_tokens) but the model's "
                f"max_position/decode_cache_len is {max_pos} — over by "
                f"{t - max_pos} tokens ({len(over)} of "
                f"{len(list(request_totals))} requests overflow)")
    if total > max_pos:
        raise ValueError(
            f"this decode needs cache/position capacity {total} (prompt + "
            f"max_new_tokens, plus draft_len slack on the speculative "
            f"path) but the model's max_position/decode_cache_len is "
            f"{max_pos}")


def _make_sampler(temperature: float, top_k: int):
    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        k = min(top_k, logits.shape[-1])  # top_k >= vocab = full sampling
        if k > 0:
            kth = jnp.sort(logits, axis=-1)[:, -k][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)
    return sample


def generate(model, variables, prompt_ids, *, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0,
             rng: Optional[jax.Array] = None, pad_id: int = 0,
             use_cache: bool = False):
    """Extend ``prompt_ids`` (B, P) by ``max_new_tokens`` tokens.

    Returns (B, P + max_new_tokens) int32. The sequence buffer is padded to
    the final length up front; the attention mask marks the live prefix, so
    every scan step runs the same fixed-shape forward (one compile).
    ``use_cache=True`` switches to KV-cache incremental decoding (models
    with a ``decode`` mode — the GPT and Llama families).
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    b, p = prompt_ids.shape
    total = p + max_new_tokens
    if rng is None:
        rng = jax.random.key(0)
    sample = _make_sampler(temperature, top_k)

    if use_cache:
        _require_decode(model, total)
        return _generate_cached(model, variables, prompt_ids, total=total,
                                pad_id=pad_id, sample=sample, rng=rng)

    ids0 = jnp.full((b, total), pad_id, jnp.int32).at[:, :p].set(prompt_ids)
    mask0 = (jnp.arange(total)[None, :] < p).astype(jnp.int32)
    mask0 = jnp.broadcast_to(mask0, (b, total))

    def step(carry, _):
        ids, mask, pos, key = carry
        logits = model.apply(variables, ids, attention_mask=mask,
                             train=False)                  # (B, total, V)
        next_logits = jax.lax.dynamic_slice_in_dim(
            logits, pos - 1, 1, axis=1)[:, 0]              # (B, V)
        key, sub = jax.random.split(key)
        tok = sample(next_logits, sub)
        ids = ids.at[:, pos].set(tok)
        mask = mask.at[:, pos].set(1)
        return (ids, mask, pos + 1, key), tok

    (ids, _, _, _), _ = jax.lax.scan(
        step, (ids0, mask0, jnp.int32(p), rng), None, length=max_new_tokens)
    return ids


def generate_beam(model, variables, prompt_ids, *, max_new_tokens: int,
                  num_beams: int = 4, length_penalty: float = 1.0,
                  eos_id: Optional[int] = None, pad_id: int = 0,
                  use_cache: bool = False):
    """Beam-search decoding: (B, P) -> (B, P + max_new_tokens) int32.

    Fixed-shape throughout (one compile): beams live as a flattened
    (B*K, total) batch through the same padded forward the sampling path
    uses, so every causal variant (dense/flash, GPT/Llama) works
    unchanged. Per step, each batch row ranks its K*V candidate
    extensions by accumulated log-probability and keeps the top K;
    finished beams (emitted ``eos_id``) are frozen — they extend only
    with ``pad_id`` at unchanged score. Final ranking divides scores by
    (emitted length)**length_penalty (>1 favors longer hypotheses;
    identical lengths make it a no-op). Deterministic: no RNG anywhere.

    ``use_cache=True`` (GPT/Llama decode mode) keeps per-beam KV caches:
    one batched prefill primes a (B,)-cache that is expanded to (B*K,);
    each step reorders the caches by surviving parent beam
    (take_along_axis over the batch dim) and runs one single-token
    forward — O(S) per token instead of the full-refeed O(S^2). Emitted
    tokens are identical to the refeed beam (tests pin this).
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    b, p = prompt_ids.shape
    k = num_beams
    total = p + max_new_tokens
    neg = jnp.float32(-1e9)

    # (B, K, total) flattened to (B*K, total); beam 0 holds the prompt,
    # beams 1..K-1 start dead so step 1 fans out from the prompt alone.
    ids0 = jnp.full((b, k, total), pad_id, jnp.int32)
    ids0 = ids0.at[:, :, :p].set(prompt_ids[:, None, :])
    scores0 = jnp.full((b, k), neg).at[:, 0].set(0.0)
    finished0 = jnp.zeros((b, k), bool)

    def select(next_logits, ids, scores, finished, pos):
        """Shared candidate ranking: extend every live beam by its top
        continuations, keep K per batch row, reorder survivors."""
        logp = jax.nn.log_softmax(next_logits).reshape(b, k, -1)
        v = logp.shape[-1]
        if eos_id is not None:
            # A finished beam contributes exactly one candidate: itself,
            # extended by pad at unchanged score (scored on the pad lane).
            frozen = jnp.full((b, k, v), neg).at[:, :, pad_id].set(0.0)
            logp = jnp.where(finished[:, :, None], frozen, logp)
        cand = scores[:, :, None] + logp                   # (B, K, V)
        top_scores, flat = jax.lax.top_k(cand.reshape(b, k * v), k)
        beam_idx, tok = flat // v, (flat % v).astype(jnp.int32)
        ids = jnp.take_along_axis(ids, beam_idx[:, :, None], axis=1)
        ids = ids.at[:, :, pos].set(tok)
        if eos_id is not None:
            was_done = jnp.take_along_axis(finished, beam_idx, axis=1)
            finished = was_done | (tok == eos_id)
        return ids, top_scores, finished, beam_idx, tok

    if use_cache:
        ids, scores, finished = _beam_cached(
            model, variables, prompt_ids, ids0, scores0, finished0,
            select, total=total, num_beams=k)
    else:
        mask0 = jnp.broadcast_to(
            (jnp.arange(total)[None, :] < p).astype(jnp.int32),
            (b * k, total))

        def step(carry, _):
            ids, scores, finished, mask, pos = carry
            logits = model.apply(variables, ids.reshape(b * k, total),
                                 attention_mask=mask, train=False)
            next_logits = jax.lax.dynamic_slice_in_dim(
                logits, pos - 1, 1, axis=1)[:, 0]          # (B*K, V)
            ids, scores, finished, _, _ = select(
                next_logits, ids, scores, finished, pos)
            mask = mask.reshape(b, k, total).at[:, :, pos].set(1)
            return (ids, scores, finished, mask.reshape(b * k, total),
                    pos + 1), None

        (ids, scores, finished, _, _), _ = jax.lax.scan(
            step, (ids0, scores0, finished0, mask0, jnp.int32(p)), None,
            length=max_new_tokens)

    if eos_id is not None:
        # Emitted length = tokens up to and including eos (or the full
        # budget for unfinished beams).
        gen = ids[:, :, p:]
        is_eos = gen == eos_id
        first_eos = jnp.argmax(is_eos, axis=-1)
        length = jnp.where(is_eos.any(axis=-1), first_eos + 1,
                           max_new_tokens)
    else:
        length = jnp.full((b, k), max_new_tokens)
    norm = scores / jnp.maximum(length, 1).astype(
        jnp.float32) ** jnp.float32(length_penalty)
    best = jnp.argmax(norm, axis=1)
    return jnp.take_along_axis(ids, best[:, None, None], axis=1)[:, 0]


# Every decode-cache leaf name, classified by what its leading dim means.
# Consumers (beam reorder below, serve/kv_cache.py's prefill packing) look
# leaves up HERE instead of guessing from shapes, so a new cache entry must
# be taught once, explicitly (ADVICE r3 #3):
#
# - "batched": per-request rows (cached_key/cached_value — dense K/V);
# - "scalar":  shared write indices (cache_index, GPT's position counter);
# - "pool":    slot-shared paged K/V pools (serve/kv_cache.py) — leading
#   dim is PAGES, not requests, so beam expansion/reorder is meaningless.
CACHE_LEAF_KINDS = {
    "cached_key": "batched",
    "cached_value": "batched",
    "cache_index": "scalar",
    "position": "scalar",
    "pages_k": "pool",
    "pages_v": "pool",
}


def _map_batched_cache(cache, fn):
    """Apply ``fn`` to the batched K/V cache leaves (``cached_key`` /
    ``cached_value``), leave the per-layer scalar write indices alone, and
    REJECT any leaf name :data:`CACHE_LEAF_KINDS` has never been taught —
    a new cache entry must be classified there explicitly, not silently
    guessed from its leading-dim size (ADVICE r3 #3)."""
    from flax import traverse_util

    flat = traverse_util.flatten_dict(cache)
    for path, x in flat.items():
        kind = CACHE_LEAF_KINDS.get(path[-1])
        if kind == "batched":
            flat[path] = fn(x)
        elif kind == "pool":
            raise ValueError(
                f"paged-pool cache leaf {'/'.join(map(str, path))} in a "
                f"beam context: pool rows are pages shared across slots, "
                f"not per-request rows — beam search needs the dense "
                f"decode cache (drop paged_state)")
        elif kind != "scalar":
            raise ValueError(
                f"unknown decode-cache leaf {'/'.join(map(str, path))}: "
                f"beam search must know whether to expand/reorder it "
                f"(batched, like cached_key) or share it (scalar, like "
                f"cache_index) — add it to CACHE_LEAF_KINDS")
    return traverse_util.unflatten_dict(flat)


def _beam_cached(model, variables, prompt_ids, ids0, scores0, finished0,
                 select, *, total: int, num_beams: int):
    """KV-cache beam search: prefill once at batch B, expand the cache to
    B*K beam rows, then per step reorder caches by surviving parent beam
    and run one single-token forward. The final token needs only a
    selection, not a forward, so the scan stops one step early and the
    last ``select`` runs outside it — no wasted forward, and no write at
    cache index == capacity (whose dynamic_update_slice start-clamp would
    silently corrupt the last K/V slot, ADVICE r3 #4)."""
    b, p = prompt_ids.shape
    k = num_beams
    if total == p:  # max_new_tokens == 0: nothing to select or forward —
        return ids0, scores0, finished0  # the trailing select below would
        # otherwise overwrite the last PROMPT token at position p-1.
    _require_decode(model, total)

    fresh = {key: v for key, v in variables.items() if key != "cache"}
    logits0, mut = model.apply(fresh, prompt_ids, train=False,
                               decode=True, mutable=["cache"])

    # (B, ...) cache rows -> (B*K, ...): row b*K+j is beam j of batch b.
    cache0 = _map_batched_cache(mut["cache"],
                                lambda x: jnp.repeat(x, k, axis=0))
    next0 = jnp.repeat(logits0[:, -1], k, axis=0)           # (B*K, V)
    batch_base = jnp.arange(b)[:, None] * k

    def step(carry, t):
        ids, scores, finished, cache, next_logits = carry
        ids, scores, finished, beam_idx, tok = select(
            next_logits, ids, scores, finished, t)
        flat = (batch_base + beam_idx).reshape(-1)
        cache = _map_batched_cache(cache,
                                   lambda x: jnp.take(x, flat, axis=0))
        logits, mut = model.apply(
            {**fresh, "cache": cache}, tok.reshape(b * k, 1),
            train=False, decode=True, mutable=["cache"])
        return (ids, scores, finished, mut["cache"], logits[:, -1]), None

    (ids, scores, finished, _, next_logits), _ = jax.lax.scan(
        step, (ids0, scores0, finished0, cache0, next0),
        jnp.arange(p, total - 1))
    ids, scores, finished, _, _ = select(
        next_logits, ids, scores, finished, total - 1)
    return ids, scores, finished


def _rewind_cache(cache, to_index):
    """Set every per-layer write index (``cache_index``, and GPT's shared
    ``position`` counter) to ``to_index``. Stale K/V entries past the index
    are dead: attention masks slots >= index and the next write overwrites
    them — so a rewind is just the scalars."""
    from flax import traverse_util

    flat = traverse_util.flatten_dict(cache)
    for path in flat:
        if path[-1] in ("cache_index", "position"):
            flat[path] = jnp.asarray(to_index, jnp.int32)
    return traverse_util.unflatten_dict(flat)


def generate_speculative(target_model, target_variables, draft_model,
                         draft_variables, prompt_ids, *,
                         max_new_tokens: int, draft_len: int = 4,
                         pad_id: int = 0):
    """Speculative greedy decoding: draft proposes, target verifies.

    Each round the draft model emits ``draft_len`` greedy tokens with
    cheap single-token forwards; the target model scores them all in ONE
    block forward and accepts the longest prefix that matches its own
    greedy choices, emitting a correction token at the first mismatch.
    Every round advances at least one token, and the output is EXACTLY
    the target model's greedy continuation (the acceptance rule never
    admits a token the target would not have picked) — tests pin this
    token-for-token. Rounds where all ``draft_len`` tokens are accepted
    emit them without a bonus token, which keeps both caches' invariants
    one-scalar simple (see :func:`_rewind_cache`).

    Batch 1 only: acceptance lengths are data-dependent per row, and the
    cache write indices are shared scalars per layer. Greedy only (the
    standard rejection-sampling extension needs per-token RNG plumbing).
    Both models must share a vocabulary.
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    b, p = prompt_ids.shape
    if b != 1:
        raise ValueError(
            f"speculative decoding is batch-1 only (got batch {b}): "
            f"per-row acceptance lengths cannot share the per-layer "
            f"scalar cache indices")
    if p < 2:
        raise ValueError("speculative decoding needs a prompt of >= 2 "
                         "tokens (the prefill feeds all but the last)")
    if draft_len < 1:
        raise ValueError(f"draft_len={draft_len}: need >= 1 proposal per "
                         f"verify round")
    total = p + max_new_tokens
    k = draft_len
    # The last verify round can write up to k cache slots past `total`
    # before the rewind; both caches must hold them.
    _require_decode(target_model, total + k)
    _require_decode(draft_model, total + k)

    t_fresh = {key: v for key, v in target_variables.items()
               if key != "cache"}
    d_fresh = {key: v for key, v in draft_variables.items()
               if key != "cache"}

    # Prefill both models on all but the last prompt token; the last token
    # becomes the first round's block head, keeping the invariant "caches
    # hold positions [0, pos-1); `last` is decided but unfed".
    t_logits, t_mut = target_model.apply(
        t_fresh, prompt_ids[:, :-1], train=False, decode=True,
        mutable=["cache"])
    _, d_mut = draft_model.apply(
        d_fresh, prompt_ids[:, :-1], train=False, decode=True,
        mutable=["cache"])
    # k slack columns so the block write near the end never triggers
    # dynamic_update_slice's start-clamping (which would shift the write);
    # trimmed before returning.
    ids0 = jnp.full((1, total + k), pad_id,
                    jnp.int32).at[:, :p].set(prompt_ids)
    if max_new_tokens <= 0:
        return ids0[:, :total]

    def cond(carry):
        return carry[1] < total

    def body(carry):
        ids, pos, last, t_cache, d_cache = carry
        # --- draft k tokens: k cheap single-token forwards ---------------
        d_toks = []
        feed = last
        dc = d_cache
        for _ in range(k):
            dl, dm = draft_model.apply(
                {**d_fresh, "cache": dc}, feed[:, None], train=False,
                decode=True, mutable=["cache"])
            feed = jnp.argmax(dl[:, -1], axis=-1).astype(jnp.int32)
            d_toks.append(feed)
            dc = dm["cache"]
        d_block = jnp.stack(d_toks, axis=1)                # (1, K)
        # --- target verifies the whole block in one forward --------------
        block = jnp.concatenate([last[:, None], d_block], axis=1)  # (1,K+1)
        tl, tm = target_model.apply(
            {**t_fresh, "cache": t_cache}, block, train=False,
            decode=True, mutable=["cache"])
        greedy = jnp.argmax(tl, axis=-1).astype(jnp.int32)  # (1, K+1)
        # greedy[:, j] is the target's choice for position pos+j; accept
        # the longest draft prefix matching it.
        match = d_block == greedy[:, :k]                    # (1, K)
        m = jnp.argmin(match, axis=1)                       # first mismatch
        m = jnp.where(match.all(axis=1), k, m)[0]
        # Emit d_0..d_{m-1} then (if m < K) the correction greedy[:, m].
        emit = jnp.where(jnp.arange(k)[None, :] < m, d_block,
                         jnp.where(jnp.arange(k)[None, :] == m,
                                   greedy[:, :k], pad_id))
        n_emit = jnp.minimum(jnp.where(m == k, k, m + 1), total - pos)
        keep = jnp.arange(k)[None, :] < n_emit
        cur = jax.lax.dynamic_slice(ids, (0, pos), (1, k))
        ids = jax.lax.dynamic_update_slice(
            ids, jnp.where(keep, emit, cur), (0, pos))
        new_pos = pos + n_emit
        last = jax.lax.dynamic_slice(ids, (0, new_pos - 1), (1, 1))[:, 0]
        return (ids, new_pos, last,
                _rewind_cache(tm["cache"], new_pos - 1),
                _rewind_cache(dc, new_pos - 1))

    last0 = prompt_ids[:, -1]
    ids, _, _, _, _ = jax.lax.while_loop(
        cond, body, (ids0, jnp.int32(p), last0, t_mut["cache"],
                     d_mut["cache"]))
    return ids[:, :total]


def _generate_cached(model, variables, prompt_ids, *, total: int,
                     pad_id: int, sample, rng):
    """KV-cache decode: ONE batched prefill forward primes the cache with
    the whole prompt (its last logits predict position p), then one
    single-token forward per emitted token. The prefill creates the cache
    collection; the scan carries it as a fixed-shape pytree."""
    b, p = prompt_ids.shape
    ids0 = jnp.full((b, total), pad_id, jnp.int32).at[:, :p].set(prompt_ids)
    if total == p:  # max_new_tokens == 0: nothing to emit
        return ids0

    # Any caller-supplied 'cache' collection is dropped — decoding must
    # start from index 0, not a stale cache.
    fresh = {k: v for k, v in variables.items() if k != "cache"}
    logits0, mut = model.apply(fresh, prompt_ids, train=False,
                               decode=True, mutable=["cache"])

    def step(carry, t):
        ids, cache, logits, key = carry
        # One RNG split per emitted token — the same consumption sequence
        # as the full-refeed path, so temperature>0 sampling is
        # path-identical at the same seed.
        key, sub = jax.random.split(key)
        tok = sample(logits, sub)
        ids = jax.lax.dynamic_update_slice(ids, tok[:, None], (0, t))
        logits, mut = model.apply(
            {**fresh, "cache": cache},
            tok[:, None], train=False, decode=True, mutable=["cache"])
        return (ids, mut["cache"], logits[:, -1], key), None

    # Scan emits tokens p..total-2; the LAST token is sampled from the
    # carried logits outside the scan — feeding it would run one forward
    # whose logits nobody consumes.
    (ids, _, logits, key), _ = jax.lax.scan(
        step, (ids0, mut["cache"], logits0[:, -1], rng),
        jnp.arange(p, total - 1))
    _, last = jax.random.split(key)
    ids = jax.lax.dynamic_update_slice(
        ids, sample(logits, last)[:, None], (0, total - 1))
    return ids
