"""Autoregressive generation for the causal LM families (GPT, Llama).

Deliberately the simple-and-correct formulation: one fixed-shape padded
forward per emitted token inside a single jitted ``lax.scan`` — no KV-cache
plumbing in the models, so it works unchanged for every causal variant
(dense/flash attention, remat, pipelined). O(S^2) per token is irrelevant
at eval-demo scale; a cached decode path is a later optimization, not a
correctness requirement.

Sampling: greedy (temperature=0) or temperature softmax with optional
top-k truncation. Fully deterministic given (params, prompt, seed).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def generate(model, variables, prompt_ids, *, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0,
             rng: Optional[jax.Array] = None, pad_id: int = 0):
    """Extend ``prompt_ids`` (B, P) by ``max_new_tokens`` tokens.

    Returns (B, P + max_new_tokens) int32. The sequence buffer is padded to
    the final length up front; the attention mask marks the live prefix, so
    every scan step runs the same fixed-shape forward (one compile).
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    b, p = prompt_ids.shape
    total = p + max_new_tokens
    if rng is None:
        rng = jax.random.key(0)

    ids0 = jnp.full((b, total), pad_id, jnp.int32).at[:, :p].set(prompt_ids)
    mask0 = (jnp.arange(total)[None, :] < p).astype(jnp.int32)
    mask0 = jnp.broadcast_to(mask0, (b, total))

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        k = min(top_k, logits.shape[-1])  # top_k >= vocab = full sampling
        if k > 0:
            kth = jnp.sort(logits, axis=-1)[:, -k][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)

    def step(carry, _):
        ids, mask, pos, key = carry
        logits = model.apply(variables, ids, attention_mask=mask,
                             train=False)                  # (B, total, V)
        next_logits = jax.lax.dynamic_slice_in_dim(
            logits, pos - 1, 1, axis=1)[:, 0]              # (B, V)
        key, sub = jax.random.split(key)
        tok = sample(next_logits, sub)
        ids = ids.at[:, pos].set(tok)
        mask = mask.at[:, pos].set(1)
        return (ids, mask, pos + 1, key), tok

    (ids, _, _, _), _ = jax.lax.scan(
        step, (ids0, mask0, jnp.int32(p), rng), None, length=max_new_tokens)
    return ids
