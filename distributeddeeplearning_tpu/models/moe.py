"""Mixture-of-Experts FFN with expert parallelism over the ``expert`` mesh
axis (wires ParallelConfig.expert — VERDICT r1 "dead config" item).

TPU-first design (GShard/Switch pattern): routing is expressed as dense
einsums over one-hot dispatch/combine tensors — no gather/scatter, no
dynamic shapes — so the whole layer is MXU work that XLA can shard. Expert
kernels carry a leading ``experts`` logical axis mapped to the ``expert``
mesh axis (parallel/sharding.py); with tokens sharded over ``data`` and
experts over ``expert``, XLA lowers the dispatch/combine einsums to
all-to-alls over ICI — the compiler-emitted equivalent of hand-written MoE
dispatch kernels.

Top-1 (Switch) or top-2 (GShard) routing with per-row capacity; dropped
tokens (over capacity) pass through the residual unchanged. With top-2,
second-choice assignments queue for capacity AFTER all first choices (the
GShard priority rule) and the two gates are renormalized over the chosen
pair. The load-balance auxiliary loss is ``sow``-n into the ``moe_losses``
collection; train/steps.py adds it to the objective.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class MoeMlp(nn.Module):
    """Drop-in replacement for the transformer FFN block.

    x: (B, S, H) -> (B, S, H); top-1 (Switch) or top-2 (GShard) routing
    over ``num_experts`` experts (``router_top_k``), each a gelu MLP of
    width ``intermediate_size``. Per-row expert capacity scales with k
    (the GShard convention) so second choices aren't starved by a
    first-choice-sized buffer.
    """

    hidden_size: int
    intermediate_size: int
    num_experts: int
    capacity_factor: float = 1.25
    router_top_k: int = 1           # 1 = Switch, 2 = GShard
    router_jitter: float = 0.01
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, deterministic: bool):
        b, s, h = x.shape
        e = self.num_experts
        # Per-row capacity: how many tokens each expert accepts from one
        # sequence. Static (compile-time) — no dynamic shapes on the MXU.
        # Scales with router_top_k (GShard): top-2 produces 2S assignments
        # per row, and a k=1-sized buffer would drop most second choices.
        cap = max(int(s / e * self.capacity_factor * self.router_top_k), 1)

        # Router (tiny, replicated). f32 for a stable softmax.
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("embed", None)),
            name="router")(x.astype(jnp.float32))
        if not deterministic and self.router_jitter > 0:
            noise = jax.random.uniform(
                self.make_rng("dropout"), router_logits.shape,
                minval=1.0 - self.router_jitter,
                maxval=1.0 + self.router_jitter)
            router_logits = router_logits * noise
        probs = jax.nn.softmax(router_logits, axis=-1)        # (B, S, E)

        if self.router_top_k not in (1, 2):
            raise ValueError(
                f"router_top_k={self.router_top_k}; only 1 (Switch) and "
                f"2 (GShard) are implemented")
        expert_idx = jnp.argmax(probs, axis=-1)               # (B, S)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        gate1_raw = jnp.sum(probs * onehot, axis=-1)          # (B, S)

        # Load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e, with
        # f_e from FIRST choices (the GShard convention for top-2 too).
        frac_tokens = onehot.mean(axis=(0, 1))                # (E,)
        frac_probs = probs.mean(axis=(0, 1))                  # (E,)
        aux = e * jnp.sum(frac_tokens * frac_probs)
        self.sow("moe_losses", "load_balance", aux)

        # Position of each token within its expert's capacity (per row);
        # tokens beyond capacity are dropped (residual passes them through).
        pos1 = jnp.cumsum(onehot, axis=1) * onehot            # (B, S, E)
        keep1 = (pos1 > 0) & (pos1 <= cap)

        def make_dispatch(onehot_k, pos_k, keep_k):
            # (B, S, E, C) dispatch in compute dtype, not f32: these are
            # the largest tensors in the layer (B·S·E·C) and hold only 0/1
            # and gate values — bf16 halves their HBM footprint and keeps
            # the dispatch einsums (the all-to-alls) on the fast MXU path
            # (VERDICT r2 Weak #8).
            return jnp.einsum(
                "bse,bsec->bsec", (onehot_k * keep_k).astype(self.dtype),
                jax.nn.one_hot(pos_k - 1.0, cap, dtype=self.dtype))

        if self.router_top_k == 1:
            dispatch = make_dispatch(onehot, pos1, keep1)
            combine = dispatch * gate1_raw[..., None, None].astype(self.dtype)
        else:
            # Second choice: argmax with the first choice masked out.
            probs2 = probs * (1.0 - onehot)
            expert_idx2 = jnp.argmax(probs2, axis=-1)
            onehot2 = jax.nn.one_hot(expert_idx2, e, dtype=jnp.float32)
            gate2_raw = jnp.sum(probs * onehot2, axis=-1)
            # GShard priority: every first-choice assignment takes capacity
            # before any second choice — pos2 continues each expert's count
            # from the row's total first-choice load.
            total1 = jnp.sum(onehot * keep1, axis=1, keepdims=True)  # (B,1,E)
            pos2 = (jnp.cumsum(onehot2, axis=1) + total1) * onehot2
            keep2 = (pos2 > 0) & (pos2 <= cap)
            # Renormalize the surviving gates over the chosen pair, so the
            # combine weights sum to <= 1 per token.
            denom = jnp.maximum(gate1_raw + gate2_raw, 1e-9)
            dispatch1 = make_dispatch(onehot, pos1, keep1)
            dispatch2 = make_dispatch(onehot2, pos2, keep2)
            dispatch = dispatch1 + dispatch2  # disjoint capacity slots
            combine = (
                dispatch1 * (gate1_raw / denom)[..., None, None]
                .astype(self.dtype)
                + dispatch2 * (gate2_raw / denom)[..., None, None]
                .astype(self.dtype))

        # Expert kernels: leading logical axis "experts" -> mesh "expert".
        wi = self.param(
            "wi", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("experts", "embed", "mlp")),
            (e, h, self.intermediate_size), jnp.float32)
        wo = self.param(
            "wo", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("experts", "mlp", "embed")),
            (e, self.intermediate_size, h), jnp.float32)

        # Dispatch tokens to experts — with tokens dp-sharded and experts
        # ep-sharded this einsum is the all-to-all.
        xin = jnp.einsum("bsec,bsh->ebch", dispatch, x.astype(self.dtype))
        xin = nn.with_logical_constraint(
            xin, ("experts", "batch", None, "embed"))
        hmid = jnp.einsum("ebch,ehf->ebcf", xin, wi.astype(self.dtype))
        hmid = nn.gelu(hmid, approximate=False)
        xout = jnp.einsum("ebcf,efh->ebch", hmid, wo.astype(self.dtype))
        # Combine back to token order — the return all-to-all.
        out = jnp.einsum("bsec,ebch->bsh", combine, xout)
        return out.astype(self.dtype)
