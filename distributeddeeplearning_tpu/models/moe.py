"""Mixture-of-Experts FFN with expert parallelism over the ``expert`` mesh
axis (wires ParallelConfig.expert — VERDICT r1 "dead config" item).

TPU-first design (GShard/Switch pattern): routing is expressed as dense
einsums over one-hot dispatch/combine tensors — no gather/scatter, no
dynamic shapes — so the whole layer is MXU work that XLA can shard. Expert
kernels carry a leading ``experts`` logical axis mapped to the ``expert``
mesh axis (parallel/sharding.py); with tokens sharded over ``data`` and
experts over ``expert``, XLA lowers the dispatch/combine einsums to
all-to-alls over ICI — the compiler-emitted equivalent of hand-written MoE
dispatch kernels.

Top-1 (Switch) routing with per-row capacity; dropped tokens (over capacity)
pass through the residual unchanged. The load-balance auxiliary loss is
``sow``-n into the ``moe_losses`` collection; train/steps.py adds it to the
objective.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class MoeMlp(nn.Module):
    """Drop-in replacement for the transformer FFN block.

    x: (B, S, H) -> (B, S, H); top-1 routing over ``num_experts`` experts,
    each a gelu MLP of width ``intermediate_size``.
    """

    hidden_size: int
    intermediate_size: int
    num_experts: int
    capacity_factor: float = 1.25
    router_jitter: float = 0.01
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, deterministic: bool):
        b, s, h = x.shape
        e = self.num_experts
        # Per-row capacity: how many tokens each expert accepts from one
        # sequence. Static (compile-time) — no dynamic shapes on the MXU.
        cap = max(int(s / e * self.capacity_factor), 1)

        # Router (tiny, replicated). f32 for a stable softmax.
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("embed", None)),
            name="router")(x.astype(jnp.float32))
        if not deterministic and self.router_jitter > 0:
            noise = jax.random.uniform(
                self.make_rng("dropout"), router_logits.shape,
                minval=1.0 - self.router_jitter,
                maxval=1.0 + self.router_jitter)
            router_logits = router_logits * noise
        probs = jax.nn.softmax(router_logits, axis=-1)        # (B, S, E)

        expert_idx = jnp.argmax(probs, axis=-1)               # (B, S)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        gate = jnp.sum(probs * onehot, axis=-1)               # (B, S)

        # Load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e.
        frac_tokens = onehot.mean(axis=(0, 1))                # (E,)
        frac_probs = probs.mean(axis=(0, 1))                  # (E,)
        aux = e * jnp.sum(frac_tokens * frac_probs)
        self.sow("moe_losses", "load_balance", aux)

        # Position of each token within its expert's capacity (per row);
        # tokens beyond capacity are dropped (residual passes them through).
        pos = jnp.cumsum(onehot, axis=1) * onehot             # (B, S, E)
        keep = (pos > 0) & (pos <= cap)
        # (B, S, E, C) dispatch/combine in compute dtype, not f32: these are
        # the largest tensors in the layer (B·S·E·C) and hold only 0/1 and
        # gate values — bf16 halves their HBM footprint and keeps the
        # dispatch einsums (the all-to-alls) on the fast MXU path
        # (VERDICT r2 Weak #8).
        dispatch = jnp.einsum(                                # (B, S, E, C)
            "bse,bsec->bsec", (onehot * keep).astype(self.dtype),
            jax.nn.one_hot(pos - 1.0, cap, dtype=self.dtype))
        combine = dispatch * gate[..., None, None].astype(self.dtype)

        # Expert kernels: leading logical axis "experts" -> mesh "expert".
        wi = self.param(
            "wi", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("experts", "embed", "mlp")),
            (e, h, self.intermediate_size), jnp.float32)
        wo = self.param(
            "wo", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("experts", "mlp", "embed")),
            (e, self.intermediate_size, h), jnp.float32)

        # Dispatch tokens to experts — with tokens dp-sharded and experts
        # ep-sharded this einsum is the all-to-all.
        xin = jnp.einsum("bsec,bsh->ebch", dispatch, x.astype(self.dtype))
        xin = nn.with_logical_constraint(
            xin, ("experts", "batch", None, "embed"))
        hmid = jnp.einsum("ebch,ehf->ebcf", xin, wi.astype(self.dtype))
        hmid = nn.gelu(hmid, approximate=False)
        xout = jnp.einsum("ebcf,efh->ebch", hmid, wo.astype(self.dtype))
        # Combine back to token order — the return all-to-all.
        out = jnp.einsum("bsec,ebch->bsh", combine, xout)
        return out.astype(self.dtype)
