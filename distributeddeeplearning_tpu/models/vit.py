"""Vision Transformer (ViT-B/16, ViT-L/16) in Flax, TPU-first.

Beyond the reference's CNN-era zoo (SURVEY.md §2 #4 lists ResNet/DenseNet/
BERT): ViT is the MXU-friendliest image model — the whole network is large
matmuls over a 197-token sequence, no BatchNorm bandwidth tax (the measured
ResNet50 bottleneck, BASELINE.md). Canonical pre-LN blocks; parameter counts
match timm's ``vit_{base,large}_patch16_224`` exactly (86,567,656 /
304,326,632 — asserted in tests/test_models.py).

Reuses the sharding-annotated ``SelfAttention`` from models/bert.py, so
tensor-parallel (``model`` axis) and flash-attention configs work unchanged;
with image inputs the trainer picks the explicit-DP shard_map path unless
tp/fsdp axes are requested (train/loop.py::uses_gspmd).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributeddeeplearning_tpu.models import bert

Dtype = Any


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    num_classes: int = 1000
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    dropout_rate: float = 0.0     # DeiT-style default; ViT-paper used 0.1
    layer_norm_eps: float = 1e-6
    attention_impl: str = "dense"  # 197 tokens: dense scores are cheap
    remat: bool = False

    def as_bert_cfg(self) -> bert.BertConfig:
        """The attention-relevant slice, for reusing bert.SelfAttention."""
        return bert.BertConfig(
            hidden_size=self.hidden_size, num_heads=self.num_heads,
            dropout_rate=self.dropout_rate,
            attention_impl=self.attention_impl)


class ViTBlock(nn.Module):
    """Pre-LN transformer block: x + Attn(LN(x)); x + MLP(LN(x))."""

    cfg: ViTConfig
    dtype: Dtype

    @nn.compact
    def __call__(self, x, *, deterministic: bool):
        cfg = self.cfg
        y = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="attention_ln")(x)
        y = bert.SelfAttention(cfg.as_bert_cfg(), self.dtype,
                               name="attention")(
            y, None, deterministic=deterministic)
        y = nn.Dropout(cfg.dropout_rate)(y, deterministic=deterministic)
        x = x + y
        y = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="mlp_ln")(x)
        y = bert._dense(cfg.intermediate_size, ("embed", "mlp"),
                        "intermediate", self.dtype)(y)
        y = nn.gelu(y, approximate=False)
        y = bert._dense(cfg.hidden_size, ("mlp", "embed"), "mlp_output",
                        self.dtype)(y)
        y = nn.Dropout(cfg.dropout_rate)(y, deterministic=deterministic)
        return x + y


class VisionTransformer(nn.Module):
    """NHWC image in, (B, num_classes) f32 logits out.

    The position table is sized at init from the example input's patch grid
    (224 -> 14x14+cls = 197), so test-sized inputs init small without a
    resize path.
    """

    cfg: ViTConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        cfg = self.cfg
        deterministic = not train
        x = jnp.asarray(x, self.dtype)
        p = cfg.patch_size
        x = nn.Conv(cfg.hidden_size, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, param_dtype=jnp.float32,
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.xavier_uniform(),
                        (None, None, None, "embed")),
                    name="patch_embed")(x)
        b, h, w, d = x.shape
        x = x.reshape(b, h * w, d)

        cls = self.param(
            "cls_token",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         (None, "embed")),
            (1, cfg.hidden_size), jnp.float32)
        pos = self.param(
            "pos_embedding",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         (None, "embed")),
            (h * w + 1, cfg.hidden_size), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, d)), x], axis=1)
        x = x + pos[None].astype(self.dtype)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        for i in range(cfg.num_layers):
            block = ViTBlock(cfg, self.dtype, name=f"block{i}")
            if cfg.remat:
                # Same function-lift as models/bert.py: `deterministic` stays
                # a closed-over Python bool.
                x = nn.remat(lambda mdl, hdn: mdl(
                    hdn, deterministic=deterministic))(block, x)
            else:
                x = block(x, deterministic=deterministic)
            x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="final_ln")(x)
        logits = nn.Dense(
            cfg.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed", None)),
            name="classifier")(x[:, 0])
        return logits.astype(jnp.float32)


def vit_b16(num_classes: int = 1000, dtype: Dtype = jnp.bfloat16,
            **overrides: Any) -> VisionTransformer:
    return VisionTransformer(
        ViTConfig(num_classes=num_classes, **overrides), dtype=dtype)


def vit_l16(num_classes: int = 1000, dtype: Dtype = jnp.bfloat16,
            **overrides: Any) -> VisionTransformer:
    return VisionTransformer(
        ViTConfig(num_classes=num_classes, hidden_size=1024, num_layers=24,
                  num_heads=16, intermediate_size=4096, **overrides),
        dtype=dtype)


def tiny_vit(num_classes: int = 10, dtype: Dtype = jnp.float32,
             **overrides: Any) -> VisionTransformer:
    """Test-sized ViT (8px patches on small test images)."""
    return VisionTransformer(
        ViTConfig(num_classes=num_classes, patch_size=8, hidden_size=64,
                  num_layers=2, num_heads=4, intermediate_size=128,
                  **overrides),
        dtype=dtype)
