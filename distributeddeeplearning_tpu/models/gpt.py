"""GPT-2-style decoder-only causal LM in Flax, TPU-first.

Beyond the reference's CNN+BERT scope: the causal counterpart to
models/bert.py, sharing the same logical-axis sharding rules (tp via
``heads``/``mlp``/``vocab``, sp activations, fsdp ``embed``) and the same
train loop — one more family behind the one trainer. Pre-LN residual
blocks, learned positions, gelu MLP, weight-tied LM head; the parameter
layout matches the public GPT-2 124M checkpoint's shapes (param count
asserted in tests).

Attention: dense causal by default; ``attention_impl='flash'`` uses the
Pallas kernel with ``causal=True`` (ops/flash_attention.py), which skips
above-diagonal blocks — the long-context training path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu import compat
from distributeddeeplearning_tpu.ops.embedding import embedding_lookup

Dtype = Any


@dataclasses.dataclass(frozen=True)
class GptConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position: int = 1024
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-5
    attention_impl: str = "dense"   # dense | flash (causal Pallas kernel) |
                                    # ring (causal ring over the `seq` axis) |
                                    # zigzag (load-balanced causal ring)
    remat: bool = False
    # Pipeline over the `pipeline` mesh axis (models/pipeline.py);
    # num_layers must divide evenly into stages. Schedule "gpipe" or
    # interleaved "1f1b" with pipeline_virtual_stages chunks per stage.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 4
    pipeline_schedule: str = "gpipe"
    pipeline_virtual_stages: int = 1

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size


def _dense(features, logical_axes, name, dtype):
    return nn.Dense(
        features, dtype=dtype, param_dtype=jnp.float32,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(0.02), logical_axes),
        name=name)


class CausalSelfAttention(nn.Module):
    cfg: GptConfig
    dtype: Dtype

    @nn.compact
    def __call__(self, x, pad_mask, *, deterministic: bool,
                 decode: bool = False, paged_state=None):
        cfg = self.cfg
        b, s, _ = x.shape
        head_dim = cfg.hidden_size // cfg.num_heads
        q = _dense(cfg.hidden_size, ("embed", "heads"), "query", self.dtype)(x)
        k = _dense(cfg.hidden_size, ("embed", "heads"), "key", self.dtype)(x)
        v = _dense(cfg.hidden_size, ("embed", "heads"), "value", self.dtype)(x)
        q = q.reshape(b, s, cfg.num_heads, head_dim)
        k = k.reshape(b, s, cfg.num_heads, head_dim)
        v = v.reshape(b, s, cfg.num_heads, head_dim)

        if decode and paged_state is not None:
            # Paged decode (serve/kv_cache.py): rows are decode SLOTS, each
            # at its own position paged_state.lengths[i], K/V scattered
            # into pool pages instead of a per-request dense cache. The
            # pools are engine-seeded cache leaves — same softmax/mask
            # numerics as the dense branch below (token-identity pinned by
            # tests/test_serve.py). A PagedBlockState advances each slot
            # up to s tokens at once (suffix prefill / speculative
            # verify); a plain PagedState is the one-token step.
            from distributeddeeplearning_tpu.serve import kv_cache as paged
            pk = self.variable("cache", "pages_k",
                               paged.unseeded_pool("pages_k"))
            pv = self.variable("cache", "pages_v",
                               paged.unseeded_pool("pages_v"))
            if isinstance(paged_state, paged.PagedBlockState):
                out, pk.value, pv.value = paged.paged_attention_block(
                    q, k, v, pk.value, pv.value, paged_state)
            else:
                out, pk.value, pv.value = paged.paged_attention_step(
                    q, k, v, pk.value, pv.value, paged_state)
        elif decode:
            # Incremental decoding: a block of s tokens (s = prompt length
            # on the prefill call, 1 per step after) is appended to a
            # (B, max_position, H, D) cache and attends over the live
            # prefix — O(S) per emitted token vs the full-refeed O(S^2)
            # (models/generate.py use_cache=True). Each attention module
            # keeps its own write index, the standard flax cache layout.
            ck = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b, cfg.max_position, cfg.num_heads, head_dim), self.dtype)
            cv = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b, cfg.max_position, cfg.num_heads, head_dim), self.dtype)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            idx = ci.value
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(self.dtype), (0, idx, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(self.dtype), (0, idx, 0, 0))
            ci.value = idx + s
            # Query j (global position idx+j) sees cache slots <= idx+j:
            # causal within the written block, everything before it.
            live = (jnp.arange(cfg.max_position)[None, :]
                    <= (idx + jnp.arange(s))[:, None])[None, None]
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck.value) \
                * (head_dim ** -0.5)
            scores = jnp.where(live, scores, jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(scores.astype(jnp.float32),
                                   axis=-1).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, cv.value)
            out = out.reshape(b, s, cfg.hidden_size)
        else:
            from distributeddeeplearning_tpu.ops.attention import (
                multihead_attention)
            out = multihead_attention(
                q, k, v, pad_mask, impl=cfg.attention_impl, causal=True,
                dtype=self.dtype, dropout_rate=cfg.dropout_rate,
                dropout_rng=(self.make_rng("dropout")
                             if not deterministic and cfg.dropout_rate > 0
                             else None),
                deterministic=deterministic)
        return _dense(cfg.hidden_size, ("heads", "embed"), "output",
                      self.dtype)(out)


class DecoderBlock(nn.Module):
    """Pre-LN transformer block (GPT-2 ordering)."""

    cfg: GptConfig
    dtype: Dtype

    @nn.compact
    def __call__(self, x, pad_mask, *, deterministic: bool,
                 decode: bool = False, paged_state=None):
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="ln1")(x)
        h = CausalSelfAttention(cfg, self.dtype, name="attention")(
            h, pad_mask, deterministic=deterministic, decode=decode,
            paged_state=paged_state)
        x = x + nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="ln2")(x)
        h = _dense(cfg.intermediate_size, ("embed", "mlp"), "mlp_in",
                   self.dtype)(h)
        h = nn.gelu(h, approximate=True)  # GPT-2 uses the tanh approximation
        h = _dense(cfg.hidden_size, ("mlp", "embed"), "mlp_out",
                   self.dtype)(h)
        return x + nn.Dropout(cfg.dropout_rate)(
            h, deterministic=deterministic)


class GptLM(nn.Module):
    """Decoder-only LM; returns (B, S, vocab) f32 logits (tied head)."""

    cfg: GptConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, *,
                 train: bool = True, decode: bool = False,
                 paged_state=None):
        cfg = self.cfg
        deterministic = not train
        b, s = input_ids.shape
        if paged_state is not None and not decode:
            raise ValueError("paged_state is a decode-mode construct; "
                             "call with decode=True")
        paged_block = paged_state is not None and hasattr(paged_state,
                                                         "n_new")
        if paged_state is not None and not paged_block and s != 1:
            raise ValueError(
                f"paged decode advances exactly one token per slot per "
                f"step (got a block of {s}); prompts prefill through the "
                f"dense decode path and are packed into pages "
                f"(serve/kv_cache.pack_prefill_cache), or pass a "
                f"PagedBlockState for the block fast path")
        if decode and cfg.pipeline_stages > 1:
            raise ValueError("decode (KV-cache) mode is not supported for "
                             "pipelined models; generate with the "
                             "non-pipelined variant")
        if s > cfg.max_position:
            raise ValueError(
                f"sequence length {s} exceeds max_position "
                f"{cfg.max_position}; build the model with seq_len={s}")
        pad_mask = (jnp.ones((b, s), jnp.bool_) if attention_mask is None
                    else attention_mask.astype(jnp.bool_))

        # Zigzag layout (load-balanced causal ring, parallel/ring_attention):
        # the whole transformer runs in zigzag order — ids/mask/positions
        # permuted once here, hidden states unpermuted once before the LM
        # head — so each layer's causal attention is balanced across the
        # seq shards without per-layer relayout. The permutation is a
        # trace-time constant from the ambient mesh's seq size; everything
        # between (LN, MLP, residuals, dropout) is positionwise and thus
        # permutation-oblivious.
        inv = None
        if cfg.attention_impl == "zigzag" and not decode:
            from distributeddeeplearning_tpu.parallel.ring_attention import (
                zigzag_indices)
            ambient = compat.get_abstract_mesh()
            n_seq = (ambient.shape.get("seq", 1)
                     if ambient is not None and not ambient.empty else 1)
            if n_seq > 1:
                if s % (2 * n_seq):
                    raise ValueError(
                        f"attention_impl='zigzag' needs seq_len divisible "
                        f"by 2*seq_shards (= {2 * n_seq}); got {s}")
                perm, inv = zigzag_indices(s, n_seq)
                input_ids = input_ids[:, perm]
                pad_mask = pad_mask[:, perm]
        if decode and paged_state is not None:
            # Paged decode: every slot sits at its OWN position (the
            # engine's per-slot lengths), so the shared scalar counter the
            # dense branch keeps cannot exist — positions come from the
            # state, shaped (B, s) for a per-row wpe lookup (s == 1 for
            # the step path; block column t sits at lengths + t, columns
            # past n_new are masked garbage whose lookup clips).
            pos_index = paged_state.lengths[:, None] + jnp.arange(s)[None]
            if paged_block:
                pos_index = jnp.clip(pos_index, 0, cfg.max_position - 1)
        elif decode:
            # Positions continue from the decode counter (a top-level cache
            # variable advanced by the block length; per-attention cache
            # indices advance in lockstep) — s = prompt length on prefill,
            # 1 per emitted token after.
            pos_var = self.variable("cache", "position",
                                    lambda: jnp.zeros((), jnp.int32))
            pos_index = pos_var.value + jnp.arange(s)
            pos_var.value = pos_var.value + s
        else:
            pos_index = (jnp.asarray(perm) if inv is not None
                         else jnp.arange(s))

        wte = self.param(
            "wte", nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                                ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        wpe = self.param(
            "wpe", nn.with_logical_partitioning(nn.initializers.normal(0.01),
                                                (None, "embed")),
            (cfg.max_position, cfg.hidden_size), jnp.float32)
        # embedding_lookup: fsdp-friendly scatter-add backward
        # (ops/embedding.py; VERDICT r4 Missing #5). Shared 1D positions
        # broadcast over the batch; paged per-row (B, 1) positions already
        # carry the batch dim.
        pos_emb = embedding_lookup(wpe, pos_index)
        x = (embedding_lookup(wte, input_ids)
             + (pos_emb if pos_emb.ndim == 3 else pos_emb[None])
             ).astype(self.dtype)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        if cfg.pipeline_stages > 1:
            import functools

            from distributeddeeplearning_tpu.models.pipeline import (
                build_pipelined)
            x = build_pipelined(
                functools.partial(DecoderBlock, cfg, self.dtype),
                num_layers=cfg.num_layers, num_stages=cfg.pipeline_stages,
                num_microbatches=cfg.pipeline_microbatches,
                schedule=cfg.pipeline_schedule,
                virtual_stages=cfg.pipeline_virtual_stages,
                remat=cfg.remat, dtype=self.dtype)(
                    x, pad_mask, deterministic=deterministic)
            x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        else:
            for i in range(cfg.num_layers):
                block = DecoderBlock(cfg, self.dtype, name=f"layer{i}")
                if cfg.remat and not decode:
                    x = nn.remat(
                        lambda mdl, h, m: mdl(
                            h, m, deterministic=deterministic))(
                        block, x, pad_mask)
                else:
                    x = block(x, pad_mask, deterministic=deterministic,
                              decode=decode, paged_state=paged_state)
                x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        if inv is not None:
            # Back to natural order BEFORE the head: unpermuting the (B,S,H)
            # hidden states costs vocab/hidden (~65x) less traffic than
            # unpermuting logits, and callers (loss, eval, generation) see
            # the standard position-aligned contract.
            x = x[:, jnp.asarray(inv)]
            x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        logits = jnp.einsum("bsh,vh->bsv", x, wte.astype(self.dtype))
        return logits.astype(jnp.float32)


def _fit_positions(cfg: GptConfig, seq_len: Optional[int]) -> GptConfig:
    if seq_len and seq_len > cfg.max_position:
        cfg = dataclasses.replace(cfg, max_position=seq_len)
    return cfg


def gpt2_small(vocab_size: int = 50257, dtype: Dtype = jnp.bfloat16,
               seq_len: Optional[int] = None, **overrides: Any) -> GptLM:
    """GPT-2 124M geometry (12L/768H/12 heads, 1024 positions)."""
    cfg = GptConfig(vocab_size=vocab_size, **overrides)
    return GptLM(_fit_positions(cfg, seq_len), dtype=dtype)


def gpt2_medium(vocab_size: int = 50257, dtype: Dtype = jnp.bfloat16,
                seq_len: Optional[int] = None, **overrides: Any) -> GptLM:
    cfg = GptConfig(vocab_size=vocab_size, hidden_size=1024, num_layers=24,
                    num_heads=16, **overrides)
    return GptLM(_fit_positions(cfg, seq_len), dtype=dtype)


def tiny_gpt(vocab_size: int = 1024, dtype: Dtype = jnp.float32,
             seq_len: Optional[int] = None, **overrides: Any) -> GptLM:
    cfg = GptConfig(vocab_size=vocab_size,
                    **{"hidden_size": 64, "num_layers": 2, "num_heads": 4,
                       "max_position": 128, **overrides})
    return GptLM(_fit_positions(cfg, seq_len), dtype=dtype)
