"""Analytic training FLOPs per example, for MFU reporting (bench.py).

Convention: a multiply-accumulate counts as 2 FLOPs — the same convention
as both XLA's cost analysis and published chip peaks, so
``mfu = rate * train_flops_per_example / peak`` is dimensionally honest.
``train ≈ 3 x forward`` (backward is two matmuls per forward matmul); the
optimizer update is O(params) — three orders of magnitude below the matmul
term at batch>=1 — and is deliberately not counted, matching the standard
MFU definition (model FLOPs, not executed FLOPs: remat recompute is also
NOT counted, so a remat run's MFU honestly reports the efficiency loss).

CNN entries are the conv-sum constants at 224x224 (the literature MAC
counts x2); transformer FLOPs are enumerated exactly from each model's
config dataclass (qkv/out/ffn matmuls + the two S^2 attention matmuls +
the LM/MLM head). Both are validated against XLA lowered-HLO cost
analysis on CPU by tests/test_flops.py (tools/calibrate_flops.py is the
standalone calibration harness).
"""

from __future__ import annotations

# Forward FLOPs per image at 224x224, 2 x the canonical conv+fc MAC sums
# (torchvision geometry — enforced by the param-count tests in
# tests/test_models.py).
_CNN_FWD_FLOPS_224 = {
    "resnet18": 3.64e9,
    "resnet34": 7.34e9,
    "resnet50": 8.18e9,
    "resnet101": 15.6e9,
    "resnet152": 23.0e9,
    "densenet121": 5.74e9,
    "densenet169": 6.81e9,
}

# bf16 systolic-array peak FLOP/s per chip, keyed by substrings of
# ``jax.devices()[0].device_kind`` (lowercased). Sources: published TPU
# spec sheets; v5e ("TPU v5 lite") = 197 TFLOP/s bf16.
_BF16_PEAK_BY_KIND = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def bf16_peak_flops(device_kind: str) -> float | None:
    """Per-chip bf16 peak for a jax device_kind, or None if unknown."""
    kind = device_kind.lower()
    for sub, peak in _BF16_PEAK_BY_KIND:
        if sub in kind:
            return peak
    return None


def _transformer_fwd_flops(*, num_layers: int, hidden: int, ffn: int,
                           seq_len: int, vocab: int, head_positions: int,
                           kv_heads_frac: float = 1.0,
                           ffn_matmuls: int = 2,
                           mlm_transform: bool = False,
                           patch_embed_in: int = 0,
                           num_classes: int = 0) -> float:
    """Exact matmul enumeration for one example (2 x MAC).

    ``head_positions``: rows hitting the vocab projection (S for causal /
    dense MLM, the gather width for gather-mode MLM, 0 for classifiers).
    ``kv_heads_frac``: num_kv_heads / num_heads (GQA shrinks the KV proj).
    ``ffn_matmuls``: 2 for GELU MLPs, 3 for SwiGLU.
    ``patch_embed_in``: ViT patch-embedding input dim (P*P*3), else 0.
    """
    s, d = seq_len, hidden
    per_layer = (
        2 * s * d * d            # Q proj
        + 2 * 2 * s * d * (d * kv_heads_frac)  # K and V proj
        + 2 * s * s * d          # scores Q @ K^T (all heads)
        + 2 * s * s * d          # probs @ V
        + 2 * s * d * d          # output proj
        + ffn_matmuls * 2 * s * d * ffn)
    head = 2 * head_positions * d * vocab
    if mlm_transform:
        head += 2 * head_positions * d * d
    if num_classes:
        head += 2 * d * num_classes
    embed = 2 * s * patch_embed_in * d if patch_embed_in else 0.0
    return num_layers * per_layer + head + embed


def fwd_flops_per_example(model: str, *, seq_len: int | None = None,
                          mlm_positions: int = 0) -> float | None:
    """Analytic forward FLOPs for one example, or None if the model has no
    entry (tiny/test models are deliberately absent). ``mlm_positions`` is
    the gather-head width (0 = dense full-sequence logits)."""
    if model in _CNN_FWD_FLOPS_224:
        return _CNN_FWD_FLOPS_224[model]
    if model == "vit_b16":
        return _transformer_fwd_flops(
            num_layers=12, hidden=768, ffn=3072, seq_len=197, vocab=0,
            head_positions=0, patch_embed_in=16 * 16 * 3, num_classes=1000)
    if model == "vit_l16":
        return _transformer_fwd_flops(
            num_layers=24, hidden=1024, ffn=4096, seq_len=197, vocab=0,
            head_positions=0, patch_embed_in=16 * 16 * 3, num_classes=1000)
    if seq_len is None:
        return None
    if model in ("bert_base", "bert_large"):
        large = model == "bert_large"
        return _transformer_fwd_flops(
            num_layers=24 if large else 12, hidden=1024 if large else 768,
            ffn=4096 if large else 3072, seq_len=seq_len, vocab=30522,
            head_positions=mlm_positions or seq_len, mlm_transform=True)
    if model in ("gpt2_small", "gpt2_medium"):
        med = model == "gpt2_medium"
        return _transformer_fwd_flops(
            num_layers=24 if med else 12, hidden=1024 if med else 768,
            ffn=4096 if med else 3072, seq_len=seq_len, vocab=50257,
            head_positions=seq_len)
    if model == "llama2_7b":
        return _transformer_fwd_flops(
            num_layers=32, hidden=4096, ffn=11008, seq_len=seq_len,
            vocab=32000, head_positions=seq_len, ffn_matmuls=3)
    if model == "tinyllama_1b":
        return _transformer_fwd_flops(
            num_layers=22, hidden=2048, ffn=5632, seq_len=seq_len,
            vocab=32000, head_positions=seq_len, ffn_matmuls=3,
            kv_heads_frac=4 / 32)
    return None


def train_flops_per_example(model: str, *, seq_len: int | None = None,
                            mlm_positions: int = 0) -> float | None:
    """fwd+bwd model FLOPs per example (3 x forward), or None."""
    fwd = fwd_flops_per_example(model, seq_len=seq_len,
                                mlm_positions=mlm_positions)
    return None if fwd is None else 3.0 * fwd
