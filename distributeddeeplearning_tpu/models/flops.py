"""Analytic training FLOPs per example, for MFU reporting (bench.py).

Convention: a multiply-accumulate counts as 2 FLOPs — the same convention
as both XLA's cost analysis and published chip peaks, so
``mfu = rate * train_flops_per_example / peak`` is dimensionally honest.
``train ≈ 3 x forward`` (backward is two matmuls per forward matmul); the
optimizer update is O(params) — three orders of magnitude below the matmul
term at batch>=1 — and is deliberately not counted, matching the standard
MFU definition (model FLOPs, not executed FLOPs: remat recompute is also
NOT counted, so a remat run's MFU honestly reports the efficiency loss).

CNN entries are the conv-sum constants at 224x224 (the literature MAC
counts x2); transformer FLOPs are enumerated exactly from each model's
config dataclass (qkv/out/ffn matmuls + the two S^2 attention matmuls +
the LM/MLM head). Both are validated against XLA lowered-HLO cost
analysis on CPU by tests/test_flops.py (tools/calibrate_flops.py is the
standalone calibration harness).
"""

from __future__ import annotations

# Forward FLOPs per image at 224x224, 2 x the canonical conv+fc MAC sums
# (torchvision geometry — enforced by the param-count tests in
# tests/test_models.py).
_CNN_FWD_FLOPS_224 = {
    "resnet18": 3.64e9,
    "resnet34": 7.34e9,
    "resnet50": 8.18e9,
    "resnet101": 15.6e9,
    "resnet152": 23.0e9,
    "densenet121": 5.74e9,
    "densenet169": 6.81e9,
}

# bf16 systolic-array peak FLOP/s per chip, keyed by substrings of
# ``jax.devices()[0].device_kind`` (lowercased). Sources: published TPU
# spec sheets; v5e ("TPU v5 lite") = 197 TFLOP/s bf16.
_BF16_PEAK_BY_KIND = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)

# HBM bandwidth bytes/s per chip, same key scheme. Decode at small batch
# is memory-bound (every token re-reads the weights and the KV cache), so
# this is the roof serving numbers are scored against. Sources: published
# TPU spec sheets (v5e 819 GB/s, v5p 2765, v4 1228, v3 900, v2 700,
# Trillium ~1640).
_HBM_BW_BY_KIND = (
    ("v5 lite", 819e9),
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v6 lite", 1640e9),
    ("v6e", 1640e9),
    ("trillium", 1640e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def _by_kind(table, device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, value in table:
        if sub in kind:
            return value
    return None


def bf16_peak_flops(device_kind: str) -> float | None:
    """Per-chip bf16 peak for a jax device_kind, or None if unknown."""
    return _by_kind(_BF16_PEAK_BY_KIND, device_kind)


# TPU MXUs natively multiply bf16; XLA executes a true-f32 matmul as a
# 6-pass bf16x6 decomposition (each operand split into three bf16 terms),
# so the sustainable f32 matmul peak is the bf16 peak / 6 across
# generations. Published spec sheets quote bf16 only, which is why the
# ratio is a convention here rather than a per-chip table.
_F32_PEAK_DIVISOR = 6.0


def peak_flops(device_kind: str, dtype: str = "bfloat16") -> float | None:
    """Per-chip matmul peak for a compute dtype, or None if unknown.

    The dtype-aware roofline denominator (docs/perf_measurement.md): an
    fp32 arm is scored against the fp32 roof and a mixed/bf16 arm against
    the bf16 roof, so ``pct_of_peak`` measures distance from what the
    chip could do AT THAT PRECISION — while raw ``examples_per_sec``
    still shows the mixed arm's absolute win.
    """
    peak = _by_kind(_BF16_PEAK_BY_KIND, device_kind)
    if peak is None:
        return None
    if dtype in ("float32", "f32"):
        return peak / _F32_PEAK_DIVISOR
    if dtype in ("bfloat16", "bf16", "float16", "f16"):
        return peak
    raise ValueError(f"unknown compute dtype {dtype!r} for peak_flops "
                     f"(expected float32 or bfloat16)")


def hbm_bw_bytes(device_kind: str) -> float | None:
    """Per-chip HBM bandwidth (bytes/s), or None if unknown."""
    return _by_kind(_HBM_BW_BY_KIND, device_kind)


def _transformer_fwd_flops(*, num_layers: int, hidden: int, ffn: int,
                           seq_len: int, vocab: int, head_positions: int,
                           kv_heads_frac: float = 1.0,
                           ffn_matmuls: int = 2,
                           mlm_transform: bool = False,
                           patch_embed_in: int = 0,
                           num_classes: int = 0) -> float:
    """Exact matmul enumeration for one example (2 x MAC).

    ``head_positions``: rows hitting the vocab projection (S for causal /
    dense MLM, the gather width for gather-mode MLM, 0 for classifiers).
    ``kv_heads_frac``: num_kv_heads / num_heads (GQA shrinks the KV proj).
    ``ffn_matmuls``: 2 for GELU MLPs, 3 for SwiGLU.
    ``patch_embed_in``: ViT patch-embedding input dim (P*P*3), else 0.
    """
    s, d = seq_len, hidden
    per_layer = (
        2 * s * d * d            # Q proj
        + 2 * 2 * s * d * (d * kv_heads_frac)  # K and V proj
        + 2 * s * s * d          # scores Q @ K^T (all heads)
        + 2 * s * s * d          # probs @ V
        + 2 * s * d * d          # output proj
        + ffn_matmuls * 2 * s * d * ffn)
    head = 2 * head_positions * d * vocab
    if mlm_transform:
        head += 2 * head_positions * d * d
    if num_classes:
        head += 2 * d * num_classes
    embed = 2 * s * patch_embed_in * d if patch_embed_in else 0.0
    return num_layers * per_layer + head + embed


# Causal-LM geometries, shared by the training-FLOPs path (below) and the
# decode FLOPs/bytes model: one source of truth so a serving roofline and
# a training MFU for the same model can never disagree on shapes. Tiny
# test models are deliberately absent, like everywhere else in this file.
_CAUSAL_GEOM = {
    "gpt2_small": dict(num_layers=12, hidden=768, ffn=3072, vocab=50257),
    "gpt2_medium": dict(num_layers=24, hidden=1024, ffn=4096, vocab=50257),
    "llama2_7b": dict(num_layers=32, hidden=4096, ffn=11008, vocab=32000,
                      ffn_matmuls=3),
    "tinyllama_1b": dict(num_layers=22, hidden=2048, ffn=5632, vocab=32000,
                         ffn_matmuls=3, kv_heads_frac=4 / 32),
}


def fwd_flops_per_example(model: str, *, seq_len: int | None = None,
                          mlm_positions: int = 0) -> float | None:
    """Analytic forward FLOPs for one example, or None if the model has no
    entry (tiny/test models are deliberately absent). ``mlm_positions`` is
    the gather-head width (0 = dense full-sequence logits)."""
    if model in _CNN_FWD_FLOPS_224:
        return _CNN_FWD_FLOPS_224[model]
    if model == "vit_b16":
        return _transformer_fwd_flops(
            num_layers=12, hidden=768, ffn=3072, seq_len=197, vocab=0,
            head_positions=0, patch_embed_in=16 * 16 * 3, num_classes=1000)
    if model == "vit_l16":
        return _transformer_fwd_flops(
            num_layers=24, hidden=1024, ffn=4096, seq_len=197, vocab=0,
            head_positions=0, patch_embed_in=16 * 16 * 3, num_classes=1000)
    if seq_len is None:
        return None
    if model in ("bert_base", "bert_large"):
        large = model == "bert_large"
        return _transformer_fwd_flops(
            num_layers=24 if large else 12, hidden=1024 if large else 768,
            ffn=4096 if large else 3072, seq_len=seq_len, vocab=30522,
            head_positions=mlm_positions or seq_len, mlm_transform=True)
    geom = _CAUSAL_GEOM.get(model)
    if geom is not None:
        return _transformer_fwd_flops(seq_len=seq_len,
                                      head_positions=seq_len, **geom)
    return None


def train_flops_per_example(model: str, *, seq_len: int | None = None,
                            mlm_positions: int = 0) -> float | None:
    """fwd+bwd model FLOPs per example (3 x forward), or None."""
    fwd = fwd_flops_per_example(model, seq_len=seq_len,
                                mlm_positions=mlm_positions)
    return None if fwd is None else 3.0 * fwd


def decode_flops_per_token(model: str, *,
                           context_len: int) -> float | None:
    """Model FLOPs to emit ONE token at batch 1 with a KV cache holding
    ``context_len`` positions: every weight matmul at seq=1 plus the two
    attention products against the cached context. None for models with
    no causal geometry entry."""
    g = _CAUSAL_GEOM.get(model)
    if g is None:
        return None
    d, ffn = g["hidden"], g["ffn"]
    kv = g.get("kv_heads_frac", 1.0)
    per_layer = (
        2 * d * d                      # Q proj
        + 2 * 2 * d * (d * kv)         # K and V proj
        + 2 * context_len * d          # q @ K^T over the cache (all heads)
        + 2 * context_len * d          # probs @ V
        + 2 * d * d                    # output proj
        + g.get("ffn_matmuls", 2) * 2 * d * ffn)
    return g["num_layers"] * per_layer + 2 * d * g["vocab"]


def _decode_weight_and_kv_bytes(model: str, *, context_len: int,
                                dtype_bytes: int = 2):
    """(weight_bytes, kv_bytes) per decode step row: the full weight set
    and one row's KV-cache read (+ its one-position write). Split out
    because batching amortizes the first and multiplies the second."""
    g = _CAUSAL_GEOM.get(model)
    if g is None:
        return None
    d, ffn = g["hidden"], g["ffn"]
    kv = g.get("kv_heads_frac", 1.0)
    weight_params = g["num_layers"] * (
        d * d * 2                      # Q + output proj
        + 2 * d * (d * kv)             # K and V proj
        + g.get("ffn_matmuls", 2) * d * ffn) + d * g["vocab"]  # LM head
    kv_traffic = g["num_layers"] * 2 * (context_len + 1) * (d * kv)
    return (weight_params * float(dtype_bytes),
            kv_traffic * float(dtype_bytes))


def decode_bytes_per_token(model: str, *, context_len: int,
                           dtype_bytes: int = 2) -> float | None:
    """HBM bytes moved to emit ONE token at batch 1: the full weight set
    (read once per token — nothing amortizes it at batch 1) plus the KV
    cache read (2 x context x kv-width per layer) and the one-position
    write. This is why small-batch decode is memory-bound: FLOPs shrink
    with seq=1 but the weight traffic does not."""
    traffic = _decode_weight_and_kv_bytes(model, context_len=context_len,
                                          dtype_bytes=dtype_bytes)
    return None if traffic is None else traffic[0] + traffic[1]


def decode_roofline(model: str, *, context_len: int,
                    tokens_per_sec: float | None,
                    device_kind: str | None,
                    dtype_bytes: int = 2, batch: int = 1) -> dict:
    """Roofline fields for a decode token rate (tokens/sec/chip).

    Per decode step at batch B the chip moves ``weights + B x kv`` bytes
    and does ``B x flops_per_token`` FLOPs, so the attainable rate is
    ``B / max(B*flops/peak, (weights + B*kv)/bw)`` — at batch 1 the
    weight traffic dominates (``bound == "memory"``), and growing B
    amortizes exactly that term, which is the whole motivation for
    continuous batching. Unknown model/chip omits the respective fields;
    never raises."""
    flops = decode_flops_per_token(model, context_len=context_len)
    traffic = _decode_weight_and_kv_bytes(model, context_len=context_len,
                                          dtype_bytes=dtype_bytes)
    if flops is None or traffic is None or tokens_per_sec is None:
        return {}
    weight_bytes, kv_bytes = traffic
    batch = max(1, int(batch))
    out = {"decode_flops_per_token": flops,
           "decode_bytes_per_token": weight_bytes + kv_bytes,
           "context_len": int(context_len), "batch": batch,
           "gflops_per_sec": round(tokens_per_sec * flops / 1e9, 2)}
    if not device_kind:
        return out
    peak, bw = bf16_peak_flops(device_kind), hbm_bw_bytes(device_kind)
    if not peak or not bw:
        return out
    compute_s = batch * flops / peak
    memory_s = (weight_bytes + batch * kv_bytes) / bw
    out["bound"] = "memory" if memory_s >= compute_s else "compute"
    attainable = batch / max(compute_s, memory_s)
    out["attainable_tokens_per_sec"] = round(attainable, 1)
    out["pct_of_peak"] = round(100.0 * tokens_per_sec / attainable, 1)
    return out
