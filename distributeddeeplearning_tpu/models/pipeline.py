"""Pipeline parallelism over the ``pipeline`` mesh axis (wires
ParallelConfig.pipeline — VERDICT r1 "dead config" item).

TPU-first design (the GSPMD pipelining pattern used by production JAX LLM
stacks): instead of per-stage processes exchanging activations (the
GPU/NCCL shape of pipeline parallelism), the whole GPipe schedule is ONE
XLA program —

- encoder layers are created with ``nn.vmap``(stages) of ``nn.scan``(layers
  per stage), so every layer parameter has a leading ``(num_stages,
  layers_per_stage, ...)`` block whose stage dim carries the ``layers``
  logical axis -> ``pipeline`` mesh axis (parallel/sharding.py);
- a ``(num_stages, microbatch, S, H)`` state buffer holds the activation
  each stage is working on, sharded over ``pipeline`` on dim 0;
- each schedule tick applies all stages at once (the vmapped chunk — each
  stage's compute lands on that stage's devices) and then *shifts* the
  buffer one stage forward, injecting the next microbatch at stage 0. XLA
  lowers the shift of a pipeline-sharded buffer to a ``collective-permute``
  over ICI — the TPU-native replacement for point-to-point activation sends.

The classic GPipe bubble (stages idle for P-1 of the M+P-1 ticks) applies;
choose ``num_microbatches >> num_stages`` to amortize it.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


def build_pipelined(layer_factory, *, num_layers: int, num_stages: int,
                    num_microbatches: int, remat: bool = False,
                    dtype: Dtype = jnp.bfloat16,
                    name: str = "pipeline") -> "PipelinedEncoder":
    """Shared model-side wiring (BERT and GPT use identical logic): validate
    the stage split and construct the pipelined encoder."""
    if num_layers % num_stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by "
            f"pipeline_stages={num_stages}")
    return PipelinedEncoder(
        layer_factory=layer_factory, num_stages=num_stages,
        layers_per_stage=num_layers // num_stages,
        num_microbatches=num_microbatches, remat=remat, dtype=dtype,
        name=name)


class _LayerStep(nn.Module):
    """scan body: carry=(x, mask) -> one encoder layer applied."""

    layer_factory: Callable[..., nn.Module]
    deterministic: bool
    remat: bool = False

    @nn.compact
    def __call__(self, carry, _):
        x, mask = carry
        layer = self.layer_factory(name="layer")
        if self.remat:
            det = self.deterministic
            x = nn.remat(
                lambda mdl, h, msk: mdl(h, msk, deterministic=det))(
                layer, x, mask)
        else:
            x = layer(x, mask, deterministic=self.deterministic)
        return (x, mask), None


class PipelinedEncoder(nn.Module):
    """Runs ``num_stages * layers_per_stage`` transformer layers as a GPipe
    pipeline. ``layer_factory(name=...)`` must build one encoder layer
    module with signature (x, mask, deterministic=...) -> x — e.g. a partial
    of bert.EncoderLayer.
    """

    layer_factory: Callable[..., nn.Module]
    num_stages: int
    layers_per_stage: int
    num_microbatches: int
    remat: bool = False
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, mask, *, deterministic: bool):
        p, m = self.num_stages, self.num_microbatches
        b, s, h = x.shape
        if b % m:
            raise ValueError(
                f"batch {b} not divisible by num_microbatches={m}")
        mb = b // m

        # Inner: scan over one stage's layers (params stacked on the
        # replicated "layers_chunk" dim). Outer: vmap over stages (params
        # and activations stacked on "layers" -> `pipeline` mesh axis).
        chunk = nn.scan(
            _LayerStep,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=self.layers_per_stage,
            metadata_params={nn.PARTITION_NAME: "layers_chunk"})
        stages_cls = nn.vmap(
            chunk,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=((0, 0), None), out_axes=((0, 0), None),
            metadata_params={nn.PARTITION_NAME: "layers"})
        stages = stages_cls(self.layer_factory, deterministic,
                            remat=self.remat, name="stages")

        micro = x.reshape(m, mb, s, h)
        micro_mask = mask.reshape(m, mb, s)
        state = jnp.zeros((p, mb, s, h), x.dtype)
        state_mask = jnp.ones((p, mb, s), mask.dtype)
        zeros_in = jnp.zeros_like(micro[0])

        outputs = []
        # M + P - 1 schedule ticks; the Python loop is static and short, and
        # keeps stage-0 injection a pure concatenate.
        for t in range(m + p - 1):
            inject = micro[t] if t < m else zeros_in
            inject_mask = micro_mask[t] if t < m else micro_mask[m - 1]
            # Shift the pipeline: stage k takes stage k-1's output; stage 0
            # takes the next microbatch. XLA: collective-permute over ICI.
            state = jnp.concatenate([inject[None], state[:-1]], axis=0)
            state_mask = jnp.concatenate(
                [inject_mask[None], state_mask[:-1]], axis=0)
            state = nn.with_logical_constraint(
                state, ("layers", "batch", "seq", "embed"))
            (state, state_mask), _ = stages((state, state_mask), None)
            if t >= p - 1:
                # Stage P-1 just finished microbatch t - (P-1).
                outputs.append(state[-1])

        out = jnp.concatenate(outputs, axis=0)  # (M*mb, S, H), in order
        return out.reshape(b, s, h)
