"""Pipeline parallelism over the ``pipeline`` mesh axis (wires
ParallelConfig.pipeline — VERDICT r1 "dead config" item).

TPU-first design (the GSPMD pipelining pattern used by production JAX LLM
stacks): instead of per-stage processes exchanging activations (the
GPU/NCCL shape of pipeline parallelism), the whole schedule is ONE XLA
program —

- encoder layers are created with ``nn.vmap``(stages) of ``nn.scan``(layers
  per stage), so every layer parameter has a leading ``(num_stages,
  layers_per_stage, ...)`` block whose stage dim carries the ``layers``
  logical axis -> ``pipeline`` mesh axis (parallel/sharding.py);
- a ``(num_stages, microbatch, S, H)`` state buffer holds the activation
  each stage is working on, sharded over ``pipeline`` on dim 0;
- each schedule tick applies all stages at once (the vmapped chunk — each
  stage's compute lands on that stage's devices) and then *shifts* the
  buffer one stage forward. XLA lowers the shift of a pipeline-sharded
  buffer to a ``collective-permute`` over ICI — the TPU-native replacement
  for point-to-point activation sends.

Two schedules, both generated from one host-side table (PipelineSchedule):

``gpipe``  — fill/drain: microbatch m enters stage 0 at tick m, exits stage
  P-1 at tick m+P-1; M+P-1 ticks total, bubble (P-1)/(M+P-1).

``1f1b``   — interleaved virtual stages (the MPMD 1F1B pattern, arXiv
  2412.14374): each stage holds V *virtual chunks* of layers_per_stage/V
  layers; activations travel the stage ring V times (the shift becomes a
  *circular* permute, P-1 -> 0 wraps), so microbatches re-enter stage 0 at
  deeper chunks while younger microbatches are still filling. Microbatches
  are injected in groups of P — microbatch m = g*P + j enters at tick
  g*P*V + j — which interleaves the steady state exactly one microbatch
  deep per stage per tick. M*V + P - 1 ticks total for P | M, bubble
  (P-1)/(M*V+P-1): the same P-1 fill/drain ticks amortized over V times
  more work-ticks. V=1 degenerates to the GPipe occupancy.

Occupancy is closed-form: stage k at tick t works on chunk
``((t-k)//P) mod V`` of microbatch ``((t-k)//(P*V))*P + (t-k)%P`` (valid
when t >= k and the microbatch index is < M). At any fixed tick the chunk
index takes at most two distinct values across stages (boundary at
k = t mod P), so per-tick chunk selection is a static slice + masked
select — no dynamic gather, and the Python tick loop stays static.

Parameter layout is schedule-invariant: the canonical checkpoint layout is
the GPipe stage-major one (stage k's row holds global layers
[k*layers_per_stage, (k+1)*layers_per_stage)). The 1F1B traversal visits
layer blocks in stage-minor order, so the interleaved apply re-lays the
layer dim once per call (a static reshape/transpose; one cross-stage
shuffle under GSPMD) — checkpoints, the sharding spec, and cross-schedule
resume all see one layout. Gradients for a weight chunk accumulate in-place
across the microbatches that visit it, as autodiff of the tick loop.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any

SCHEDULES = ("gpipe", "1f1b")


# ---------------------------------------------------------------------------
# Host-side schedule table — single source of truth for the tick loop, the
# measured bubble gauge (observability/telemetry.pipeline_bubble_fraction)
# and the ddl-lint pairing rule (analysis/collectives.py).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class StageTick:
    """One schedule tick: per-stage occupancy and the shift that precedes
    the compute. ``occupancy[k]`` is ``(microbatch, chunk)`` or None when
    stage k idles this tick; ``chunks[k]`` is the chunk whose parameters
    stage k applies (defined for idle stages too — they compute on dead
    state that is never read, exactly like GPipe's drain phase)."""

    index: int
    occupancy: tuple
    chunks: tuple
    inject_mb: Optional[int]
    emit_mb: Optional[int]

    @property
    def idle_stages(self) -> int:
        return sum(1 for o in self.occupancy if o is None)


@dataclasses.dataclass(frozen=True)
class PipelineSchedule:
    """The full tick table for one (schedule, P, M, V) geometry."""

    name: str
    num_stages: int
    num_microbatches: int
    virtual_stages: int
    ticks: tuple

    @property
    def num_ticks(self) -> int:
        return len(self.ticks)

    def bubble_fraction(self) -> float:
        """Idle stage-ticks / total stage-ticks of the executed table. For
        P | M this equals the analytic (P-1)/(M*V+P-1)."""
        total = self.num_stages * self.num_ticks
        idle = sum(t.idle_stages for t in self.ticks)
        return idle / total if total else 0.0

    def analytic_bubble_fraction(self) -> float:
        p, m, v = self.num_stages, self.num_microbatches, self.virtual_stages
        return (p - 1) / (m * v + p - 1)

    def shift_pairs(self, tick_index: int) -> tuple:
        """(source, target) collective-permute pairs of the activation shift
        entering tick ``tick_index``. The forward ring is k -> k+1; the
        wrap pair (P-1, 0) exists only when stage 0 *receives* from the ring
        (a 1F1B chunk re-entry) rather than taking a fresh microbatch — on
        inject ticks row 0 is overwritten, so the wrap edge carries no
        data. Every stage's view of this list must be identical; divergence
        is the MPMD deadlock class ddl-lint's pipeline-schedule-pairing
        rule rejects."""
        p = self.num_stages
        pairs = [(k, k + 1) for k in range(p - 1)]
        if self.ticks[tick_index].inject_mb is None:
            pairs.append((p - 1, 0))
        return tuple(pairs)


def build_schedule(name: str, *, num_stages: int, num_microbatches: int,
                   virtual_stages: int = 1) -> PipelineSchedule:
    """Generate the tick table. Both schedules come from the one closed-form
    occupancy above; gpipe is the V=1 special case with no wrap traffic."""
    p, m, v = num_stages, num_microbatches, virtual_stages
    if name not in SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {name!r}; expected one of "
            f"{SCHEDULES}")
    if v < 1:
        raise ValueError(f"pipeline_virtual_stages={v} must be >= 1")
    if name == "gpipe" and v != 1:
        raise ValueError(
            f"schedule='gpipe' runs one chunk per stage; "
            f"pipeline_virtual_stages={v} requires schedule='1f1b'")
    if v > 1 and m % p:
        raise ValueError(
            f"schedule='1f1b' injects microbatches in groups of "
            f"num_stages: num_microbatches={m} must be divisible by "
            f"num_stages={p}")
    # Last microbatch M-1 = g*P + j enters at g*P*V + j and finishes
    # P*V - 1 ticks later.
    last_g, last_j = divmod(m - 1, p)
    num_ticks = last_g * p * v + last_j + p * v
    ticks = []
    for t in range(num_ticks):
        occ, chunks = [], []
        for k in range(p):
            q, j = divmod(t - k, p)          # floor semantics for t < k
            chunk = q % v
            mb = (q // v) * p + j
            valid = t >= k and 0 <= mb < m
            occ.append((mb, chunk) if valid else None)
            chunks.append(chunk)
        inject = occ[0][0] if occ[0] is not None and occ[0][1] == 0 else None
        emit = (occ[p - 1][0]
                if occ[p - 1] is not None and occ[p - 1][1] == v - 1
                else None)
        ticks.append(StageTick(index=t, occupancy=tuple(occ),
                               chunks=tuple(chunks), inject_mb=inject,
                               emit_mb=emit))
    return PipelineSchedule(name=name, num_stages=p, num_microbatches=m,
                            virtual_stages=v, ticks=tuple(ticks))


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

def build_pipelined(layer_factory, *, num_layers: int, num_stages: int,
                    num_microbatches: int, schedule: str = "gpipe",
                    virtual_stages: int = 1, remat: bool = False,
                    dtype: Dtype = jnp.bfloat16,
                    name: str = "pipeline") -> "PipelinedEncoder":
    """Shared model-side wiring (BERT and GPT use identical logic): validate
    the stage/chunk split and construct the pipelined encoder."""
    if num_layers % num_stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by "
            f"pipeline_stages={num_stages}")
    layers_per_stage = num_layers // num_stages
    if layers_per_stage % virtual_stages:
        raise ValueError(
            f"layers_per_stage={layers_per_stage} not divisible by "
            f"pipeline_virtual_stages={virtual_stages}")
    # Validate the (schedule, V) pairing eagerly — a bad combination should
    # fail at model build, not first trace.
    build_schedule(schedule, num_stages=num_stages,
                   num_microbatches=num_microbatches,
                   virtual_stages=virtual_stages)
    return PipelinedEncoder(
        layer_factory=layer_factory, num_stages=num_stages,
        layers_per_stage=layers_per_stage,
        num_microbatches=num_microbatches, schedule=schedule,
        virtual_stages=virtual_stages, remat=remat, dtype=dtype,
        name=name)


class _LayerStep(nn.Module):
    """scan body: carry=(x, mask) -> one encoder layer applied."""

    layer_factory: Callable[..., nn.Module]
    deterministic: bool
    remat: bool = False

    @nn.compact
    def __call__(self, carry, _):
        x, mask = carry
        layer = self.layer_factory(name="layer")
        if self.remat:
            det = self.deterministic
            x = nn.remat(
                lambda mdl, h, msk: mdl(h, msk, deterministic=det))(
                layer, x, mask)
        else:
            x = layer(x, mask, deterministic=self.deterministic)
        return (x, mask), None


class PipelinedEncoder(nn.Module):
    """Runs ``num_stages * layers_per_stage`` transformer layers as a
    schedule-table-driven pipeline (gpipe or interleaved 1f1b).
    ``layer_factory(name=...)`` must build one encoder layer module with
    signature (x, mask, deterministic=...) -> x — e.g. a partial of
    bert.EncoderLayer.
    """

    layer_factory: Callable[..., nn.Module]
    num_stages: int
    layers_per_stage: int
    num_microbatches: int
    schedule: str = "gpipe"
    virtual_stages: int = 1
    remat: bool = False
    dtype: Dtype = jnp.bfloat16

    def _stacked_cls(self, scan_length: int):
        """vmap(stages) of scan(layers): the stage dim carries the
        ``layers`` logical axis -> ``pipeline`` mesh axis, the scan dim the
        replicated ``layers_chunk`` axis."""
        chunk = nn.scan(
            _LayerStep,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=scan_length,
            metadata_params={nn.PARTITION_NAME: "layers_chunk"})
        return nn.vmap(
            chunk,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=((0, 0), None), out_axes=((0, 0), None),
            metadata_params={nn.PARTITION_NAME: "layers"})

    @nn.compact
    def __call__(self, x, mask, *, deterministic: bool):
        p, m, v = self.num_stages, self.num_microbatches, self.virtual_stages
        b, s, h = x.shape
        if b % m:
            raise ValueError(
                f"batch {b} not divisible by num_microbatches={m}")
        mb = b // m

        # Strided microbatching: microbatch j = examples j::m, i.e. the
        # (B,) -> (mb, m) reshape keeps the *batch* dim outermost. Under a
        # data-sharded batch this is a shard-local relabel — no cross-shard
        # movement — which both saves an all-to-all and sidesteps an XLA
        # SPMD propagation miscompile observed on jax 0.4.37: the
        # contiguous (m, mb) split of a sharded batch dim materializes the
        # shard-local grouping while the program's global semantics name
        # the contiguous one, silently feeding each microbatch a different
        # set of examples per mesh (the dp1-vs-dp4 forward then disagrees
        # at activation scale; see tests/test_pipeline.py's dp-invariance
        # test). Per-example math is grouping-invariant, so which examples
        # share a microbatch is semantically free — strided makes it also
        # layout-free.
        micro = nn.with_logical_constraint(
            x.reshape(mb, m, s, h), ("batch", None, "seq", "embed"))
        micro_mask = mask.reshape(mb, m, s)
        state = jnp.zeros((p, mb, s, h), x.dtype)
        state_mask = jnp.ones((p, mb, s), mask.dtype)

        if self.is_initializing():
            # Parameter creation: one bound call of the full stack defines
            # the canonical (num_stages, layers_per_stage, ...) tree — the
            # same tree for every schedule, so checkpoints and the sharding
            # spec are schedule-portable. The returned activations are
            # shape-correct placeholders; init output values feed nothing
            # but downstream *shapes*.
            stages = self._stacked_cls(self.layers_per_stage)(
                self.layer_factory, deterministic, remat=self.remat,
                name="stages")
            (state, state_mask), _ = stages((state, state_mask), None)
            del state, state_mask
            return jnp.zeros((b, s, h), x.dtype)

        sched = build_schedule(self.schedule, num_stages=p,
                               num_microbatches=m, virtual_stages=v)
        lc = self.layers_per_stage // v
        full = nn.meta.unbox(self.get_variable("params", "stages"))
        if v > 1:
            full = jax.tree_util.tree_map(self._interleave, full)

        chunk_mod = self._stacked_cls(lc)(
            self.layer_factory, deterministic, remat=self.remat)

        from distributeddeeplearning_tpu.observability import telemetry
        tele = telemetry.get()

        outputs = []
        # Static Python tick loop, codegen'd from the table. The per-tick
        # telemetry instants fire at trace time — like the ZeRO overlap
        # gauge, an AOT cache hit leaves no events and the measured bubble
        # honestly reads absent (docs/pipeline.md).
        for tick in sched.ticks:
            inject = tick.inject_mb
            if inject is not None:
                # Stage 0 takes a fresh microbatch; k -> k+1 shift behind
                # it. XLA: collective-permute over ICI.
                state = jnp.concatenate([micro[:, inject][None], state[:-1]],
                                        axis=0)
                state_mask = jnp.concatenate(
                    [micro_mask[:, inject][None], state_mask[:-1]], axis=0)
            else:
                # Circular shift: stage 0 re-enters the ring at the next
                # chunk (1f1b wrap) or chews dead state (gpipe drain).
                state = jnp.concatenate([state[-1:], state[:-1]], axis=0)
                state_mask = jnp.concatenate(
                    [state_mask[-1:], state_mask[:-1]], axis=0)
            state = nn.with_logical_constraint(
                state, ("layers", "batch", "seq", "embed"))
            tick_params = self._tick_params(full, tick.chunks, lc)
            rngs = {}
            if not deterministic and self.has_rng("dropout"):
                rngs["dropout"] = self.make_rng("dropout")
            (state, state_mask), _ = chunk_mod.apply(
                {"params": tick_params}, (state, state_mask), None,
                rngs=rngs)
            tele.instant("pipeline_tick", tick=tick.index,
                         idle=tick.idle_stages, stages=p, microbatches=m,
                         schedule=self.schedule, virtual_stages=v)
            if tick.emit_mb is not None:
                outputs.append((tick.emit_mb, state[-1]))

        outputs.sort(key=lambda kv: kv[0])  # already monotone; belt+braces
        # Inverse of the strided split: stack microbatches on dim 1 so
        # row i*m + j recovers input example i*m + j — a local reshape
        # again, output rows stay aligned with input rows on every mesh.
        out = jnp.stack([o for _, o in outputs], axis=1)
        return out.reshape(b, s, h)

    def _interleave(self, leaf):
        """Canonical stage-major layout -> 1F1B visit order. Stage k's row
        must hold, at chunk slot c, global layer block c*P + k (blocks of
        layers_per_stage/V layers): a static (V, P, Lc) transpose of the
        layer dims. V=1 is the identity."""
        p, v = self.num_stages, self.virtual_stages
        lc = self.layers_per_stage // v
        rest = leaf.shape[2:]
        a = leaf.reshape((v, p, lc) + rest)
        return jnp.moveaxis(a, 0, 1).reshape((p, v * lc) + rest)

    def _tick_params(self, full, chunks, lc):
        """Per-stage chunk selection for one tick. ``chunks`` has at most
        two distinct values with a single boundary at k = t mod P (module
        docstring), so the gather is one or two static slices on the
        *unsharded* layer dim plus a per-stage select — the pipeline-sharded
        stage dim is never sliced, keeping every byte stage-local."""
        p = self.num_stages
        c_lo, c_hi = chunks[0], chunks[-1]
        if c_lo == c_hi:
            return jax.tree_util.tree_map(
                lambda a: jax.lax.slice_in_dim(
                    a, c_lo * lc, (c_lo + 1) * lc, axis=1), full)
        boundary = max(k for k in range(p) if chunks[k] == c_lo)
        row_is_lo = jnp.arange(p) <= boundary

        def select(a):
            s_lo = jax.lax.slice_in_dim(a, c_lo * lc, (c_lo + 1) * lc, axis=1)
            s_hi = jax.lax.slice_in_dim(a, c_hi * lc, (c_hi + 1) * lc, axis=1)
            m = row_is_lo.reshape((p,) + (1,) * (a.ndim - 1))
            return jnp.where(m, s_lo, s_hi)

        return jax.tree_util.tree_map(select, full)
