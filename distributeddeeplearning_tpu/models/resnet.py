"""ResNet v1.5 family in Flax, TPU-first.

Covers the reference's ResNet-50 ImageNet trainers and the deeper ResNet-152
acceptance config (BASELINE.json:5,7-9). Design notes for the MXU:

- NHWC layout end-to-end (XLA:TPU's native conv layout; no transposes).
- compute in ``dtype`` (bfloat16 by default) with float32 parameters and
  float32 BatchNorm statistics — the standard TPU mixed-precision policy.
- v1.5 variant (stride-2 on the 3x3 conv of downsampling bottlenecks), the
  variant used by the throughput benchmarks the north star targets.
- No data-dependent control flow: the whole forward is one traceable graph.

Parameter counts match torchvision's resnet{18,34,50,101,152} exactly
(tests/test_models.py asserts this), which substitutes for reference-parity
checks while /root/reference is empty (SURVEY.md §4 "Numerics").
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with expansion 4 (ResNet-50/101/152)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm_act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm_act(y, name="bn1")
        # v1.5: stride lives on the 3x3, not the first 1x1. Explicit (1,1)
        # padding: XLA's SAME pads (0,1) at stride 2, torch pads (1,1) —
        # symmetric keeps us numerically identical to the reference-era
        # torch trainers (tests/test_torch_parity.py).
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                      padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = self.norm_act(y, name="bn2")
        y = self.conv(self.filters * 4, (1, 1), name="conv3")(y)
        if residual.shape[-1] != self.filters * 4 or self.strides != 1:
            residual = self.conv(self.filters * 4, (1, 1),
                                 strides=(self.strides, self.strides),
                                 name="downsample_conv")(x)
            residual = self.norm_act(residual, name="downsample_bn",
                                     relu=False)
        # Block exit: BN + residual add + ReLU in one fused pass.
        return self.norm_act(y, name="bn3", residual=residual,
                             scale_init=nn.initializers.zeros)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm_act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                      padding=[(1, 1), (1, 1)], name="conv1")(x)
        y = self.norm_act(y, name="bn1")
        y = self.conv(self.filters, (3, 3), name="conv2")(y)
        if residual.shape[-1] != self.filters or self.strides != 1:
            residual = self.conv(self.filters, (1, 1),
                                 strides=(self.strides, self.strides),
                                 name="downsample_conv")(x)
            residual = self.norm_act(residual, name="downsample_bn",
                                     relu=False)
        return self.norm_act(y, name="bn2", residual=residual,
                             scale_init=nn.initializers.zeros)


class ResNet(nn.Module):
    """ImageNet ResNet. ``stage_sizes`` picks the depth; NHWC in, logits out."""

    stage_sizes: Sequence[int]
    block: ModuleDef
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    # Pallas fused BN(+residual)+ReLU kernels (ops/fused_batchnorm.py) for
    # the BN bandwidth tax (BASELINE.md profile: 113 ms of a 209 ms batch-512
    # step in BN-statistics/dγ/dβ/dx reductions). Same variable layout and
    # numerics as the unfused path; off by default until measured on-chip.
    fused_bn: bool = False
    # Conv-epilogue fusion (ops/fused_linear_bn.py): bottleneck 1x1 convs
    # run as Pallas matmuls carrying BN statistics in their epilogue and
    # bn2's apply in conv3's prologue (models/fused_block.py). Bottleneck
    # nets only; variable-compatible with the unfused path.
    fused_block: bool = False
    # fused_block v2 (ops/fused_conv_bn.py): additionally run stride-1 3x3
    # convs as Pallas kernels with bn1-apply prologue + bn2-stats epilogue;
    # requires fused_block. Stride-2 blocks keep the XLA conv.
    fused_conv3: bool = False
    # Cross-replica BatchNorm (torch SyncBatchNorm semantics): mesh axis
    # name(s) to pmean the batch statistics over. Only meaningful inside
    # the shard_map DP train step, where those axes are bound; None keeps
    # the default per-shard statistics (per-GPU BN under Horovod).
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.variance_scaling(
                2.0, "fan_out", "normal"),
            padding="SAME")

        def norm_act(y, *, name, residual=None, relu=True,
                     scale_init=nn.initializers.ones):
            """BN [+ residual add] [+ ReLU] — one fused Pallas pass when
            ``fused_bn``, the classic composition otherwise. Both create
            identical variables under ``name``."""
            if self.fused_bn:
                if self.bn_axis_name is not None:
                    raise ValueError(
                        "sync_bn is not supported with fused_bn (the fused "
                        "kernel computes statistics inside its custom VJP); "
                        "use --sync-bn with the default BN or --fused-block")
                from distributeddeeplearning_tpu.ops.fused_batchnorm import (
                    FusedBatchNormAct)
                return FusedBatchNormAct(
                    use_running_average=not train, momentum=0.9, epsilon=1e-5,
                    dtype=self.dtype, relu=relu, scale_init=scale_init,
                    name=name)(y, residual=residual)
            y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             param_dtype=jnp.float32, scale_init=scale_init,
                             axis_name=self.bn_axis_name if train else None,
                             name=name)(y)
            if residual is not None:
                y = y + residual
            return nn.relu(y) if relu else y

        x = jnp.asarray(x, self.dtype)
        # Explicit (3,3): torch's symmetric stem padding (SAME would pad
        # (2,3) on 224 at stride 2 — a one-pixel shift vs the reference).
        x = conv(self.width, (7, 7), strides=(2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_stem")(x)
        x = norm_act(x, name="bn_stem")
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        use_fused_block = self.fused_block and self.block is BottleneckBlock
        if self.fused_block and not use_fused_block:
            raise ValueError("fused_block requires bottleneck blocks "
                             "(resnet50/101/152); basic blocks have no 1x1 "
                             "convolutions to fuse")
        if self.fused_conv3 and not use_fused_block:
            raise ValueError("fused_conv3 extends fused_block (the 3x3 "
                             "kernel shares its statistics plumbing); pass "
                             "fused_block=True on a bottleneck net")
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                name = f"stage{i + 1}_block{j + 1}"
                if use_fused_block:
                    from distributeddeeplearning_tpu.models.fused_block \
                        import FusedBottleneckBlock
                    x = FusedBottleneckBlock(
                        filters=self.width * 2 ** i, strides=strides,
                        dtype=self.dtype, axis_name=self.bn_axis_name,
                        conv3_fused=self.fused_conv3,
                        name=name)(x, train=train)
                else:
                    x = self.block(filters=self.width * 2 ** i,
                                   strides=strides, conv=conv,
                                   norm_act=norm_act, name=name)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32,
                     kernel_init=nn.initializers.variance_scaling(
                         1.0, "fan_in", "truncated_normal"),
                     name="classifier")(x)
        return jnp.asarray(x, jnp.float32)


def resnet18(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
            fused_bn: bool = False, fused_block: bool = False,
            fused_conv3: bool = False,
            bn_axis_name: Any = None) -> ResNet:
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes, dtype=dtype,
                  fused_bn=fused_bn, fused_block=fused_block,
                  fused_conv3=fused_conv3, bn_axis_name=bn_axis_name)


def resnet18_thin(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
                  fused_bn: bool = False, fused_block: bool = False,
            fused_conv3: bool = False,
            bn_axis_name: Any = None) -> ResNet:
    """Width-16 ResNet-18 (1/16th the conv FLOPs): the CPU-tractable stand-in
    for convergence-recipe demonstrations (tools/convergence_lars.py) and
    fast tests — same depth, blocks, and BN structure as the real thing."""
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes, width=16,
                  dtype=dtype, fused_bn=fused_bn, fused_block=fused_block,
                  fused_conv3=fused_conv3, bn_axis_name=bn_axis_name)


def resnet26_thin(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
                  fused_bn: bool = False, fused_block: bool = False,
            fused_conv3: bool = False,
            bn_axis_name: Any = None) -> ResNet:
    """Width-16 bottleneck ResNet-26 ([2,2,2,2] Bottleneck): the
    CPU-tractable stand-in with the SAME block structure as resnet50 —
    what fused_block tests and bottleneck recipe demos run on."""
    return ResNet([2, 2, 2, 2], BottleneckBlock, num_classes, width=16,
                  dtype=dtype, fused_bn=fused_bn, fused_block=fused_block,
                  fused_conv3=fused_conv3, bn_axis_name=bn_axis_name)


def resnet34(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
            fused_bn: bool = False, fused_block: bool = False,
            fused_conv3: bool = False,
            bn_axis_name: Any = None) -> ResNet:
    return ResNet([3, 4, 6, 3], BasicBlock, num_classes, dtype=dtype,
                  fused_bn=fused_bn, fused_block=fused_block,
                  fused_conv3=fused_conv3, bn_axis_name=bn_axis_name)


def resnet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
            fused_bn: bool = False, fused_block: bool = False,
            fused_conv3: bool = False,
            bn_axis_name: Any = None) -> ResNet:
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes, dtype=dtype,
                  fused_bn=fused_bn, fused_block=fused_block,
                  fused_conv3=fused_conv3, bn_axis_name=bn_axis_name)


def resnet101(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
            fused_bn: bool = False, fused_block: bool = False,
            fused_conv3: bool = False,
            bn_axis_name: Any = None) -> ResNet:
    return ResNet([3, 4, 23, 3], BottleneckBlock, num_classes, dtype=dtype,
                  fused_bn=fused_bn, fused_block=fused_block,
                  fused_conv3=fused_conv3, bn_axis_name=bn_axis_name)


def resnet152(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
            fused_bn: bool = False, fused_block: bool = False,
            fused_conv3: bool = False,
            bn_axis_name: Any = None) -> ResNet:
    return ResNet([3, 8, 36, 3], BottleneckBlock, num_classes, dtype=dtype,
                  fused_bn=fused_bn, fused_block=fused_block,
                  fused_conv3=fused_conv3, bn_axis_name=bn_axis_name)
