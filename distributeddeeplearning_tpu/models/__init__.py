"""Model zoo: the architectures named by the acceptance configs
(BASELINE.json:6-12): ResNet-50/152, DenseNet-121, BERT-base MLM.

``get_model`` is the single registry the trainer/CLI uses; every entry is a
Flax module plus metadata about its input signature so the trainer stays
model-agnostic (one trainer, many models — SURVEY.md §2 #1/#2).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Registry entry: module factory + input kind ('image' or 'tokens')."""

    name: str
    build: Callable[..., Any]          # (num_classes/vocab, dtype) -> nn.Module
    input_kind: str                    # "image" | "tokens"
    param_count: int                   # known-good total, used by tests
    objective: str = "classify"        # classify | mlm | causal — selects the
                                       # loss (train/steps.py) and, for token
                                       # pipelines, masking vs plain ids


def _registry() -> dict[str, ModelSpec]:
    from distributeddeeplearning_tpu.models import (bert, densenet, gpt,
                                                    llama, resnet, vit)

    def img(build, name, params):
        return ModelSpec(name=name, build=build, input_kind="image",
                         param_count=params)

    return {
        "resnet18": img(resnet.resnet18, "resnet18", 11_689_512),
        "resnet18_thin": img(resnet.resnet18_thin, "resnet18_thin", 831_096),
        "resnet26_thin": img(resnet.resnet26_thin, "resnet26_thin",
                             1_392_184),
        "resnet34": img(resnet.resnet34, "resnet34", 21_797_672),
        "resnet50": img(resnet.resnet50, "resnet50", 25_557_032),
        "resnet101": img(resnet.resnet101, "resnet101", 44_549_160),
        "resnet152": img(resnet.resnet152, "resnet152", 60_192_808),
        "densenet121": img(densenet.densenet121, "densenet121", 7_978_856),
        "densenet169": img(densenet.densenet169, "densenet169", 14_149_480),
        # Vision transformers (beyond reference scope): the MXU-friendliest
        # image models — all matmuls, no BatchNorm bandwidth tax. Param
        # counts match timm vit_{base,large}_patch16_224 at 224px init.
        "vit_b16": img(vit.vit_b16, "vit_b16", 86_567_656),
        "vit_l16": img(vit.vit_l16, "vit_l16", 304_326_632),
        "vit_tiny": img(vit.tiny_vit, "vit_tiny", 0),
        "bert_base": ModelSpec(
            name="bert_base", build=bert.bert_base_mlm, input_kind="tokens",
            param_count=109_514_298, objective="mlm"),
        "bert_large": ModelSpec(
            name="bert_large", build=bert.bert_large_mlm, input_kind="tokens",
            param_count=335_174_458, objective="mlm"),
        # Decoder-only causal LMs (beyond reference scope): GPT-2 geometry,
        # same trainer/sharding rules, causal Pallas flash kernel available.
        "gpt2_small": ModelSpec(
            name="gpt2_small", build=gpt.gpt2_small, input_kind="tokens",
            param_count=124_439_808, objective="causal"),
        "gpt2_medium": ModelSpec(
            name="gpt2_medium", build=gpt.gpt2_medium, input_kind="tokens",
            param_count=354_823_168, objective="causal"),
        "gpt_tiny": ModelSpec(
            name="gpt_tiny", build=gpt.tiny_gpt, input_kind="tokens",
            param_count=0, objective="causal"),
        # Llama family (RMSNorm/RoPE/SwiGLU/GQA) — the modern-LM shapes;
        # llama2_7b's count matches the canonical checkpoint exactly.
        "llama2_7b": ModelSpec(
            name="llama2_7b", build=llama.llama2_7b, input_kind="tokens",
            param_count=6_738_415_616, objective="causal"),
        "tinyllama_1b": ModelSpec(
            name="tinyllama_1b", build=llama.tinyllama_1b,
            input_kind="tokens", param_count=1_100_048_384,
            objective="causal"),
        "llama_tiny": ModelSpec(
            name="llama_tiny", build=llama.tiny_llama, input_kind="tokens",
            param_count=0, objective="causal"),
        # Nano drafters for speculative decoding (serve/engine.py): a
        # shrunk config of the same family — cheap to step, same
        # tokenizer/vocab, verified by the full target model so output
        # stays token-identical regardless of drafter quality.
        "gpt_nano": ModelSpec(
            name="gpt_nano", objective="causal",
            build=lambda **kw: gpt.tiny_gpt(
                **{"hidden_size": 32, "num_layers": 1, "num_heads": 2,
                   **kw}),
            input_kind="tokens", param_count=0),
        "llama_nano": ModelSpec(
            name="llama_nano", objective="causal",
            build=lambda **kw: llama.tiny_llama(
                **{"hidden_size": 32, "num_layers": 1, "num_heads": 2,
                   "num_kv_heads": 1, "intermediate_size": 64, **kw}),
            input_kind="tokens", param_count=0),
        # GPT-2 124M as a 4-stage GPipe pipeline over the `pipeline` axis.
        "gpt2_small_pp": ModelSpec(
            name="gpt2_small_pp", objective="causal",
            build=lambda **kw: gpt.gpt2_small(
                **{"pipeline_stages": 4,
                   "pipeline_microbatches": 8, **kw}),
            input_kind="tokens", param_count=0),
        "gpt_tiny_pp": ModelSpec(
            name="gpt_tiny_pp", objective="causal",
            build=lambda **kw: gpt.tiny_gpt(
                **{"pipeline_stages": 2,
                   "pipeline_microbatches": 4, **kw}),
            input_kind="tokens", param_count=0),
        # BERT-base with a top-1-routed 8-expert MoE FFN every other layer
        # (models/moe.py), expert-parallel over the `expert` mesh axis.
        "bert_base_moe": ModelSpec(
            name="bert_base_moe", objective="mlm",
            build=lambda **kw: bert.bert_base_mlm(num_experts=8, **kw),
            input_kind="tokens", param_count=0),
        # Test/dry-run sized transformer; param_count=0 means "unchecked".
        "bert_tiny": ModelSpec(
            name="bert_tiny", build=bert.tiny_bert_mlm, input_kind="tokens",
            param_count=0, objective="mlm"),
        "bert_tiny_moe": ModelSpec(
            name="bert_tiny_moe", objective="mlm",
            build=lambda **kw: bert.tiny_bert_mlm(num_experts=4, **kw),
            input_kind="tokens", param_count=0),
        "bert_tiny_moe2": ModelSpec(
            name="bert_tiny_moe2", objective="mlm",
            build=lambda **kw: bert.tiny_bert_mlm(num_experts=4,
                                                  moe_top_k=2, **kw),
            input_kind="tokens", param_count=0),
        # BERT-base as a 4-stage GPipe pipeline over the `pipeline` axis.
        "bert_base_pp": ModelSpec(
            name="bert_base_pp", objective="mlm",
            build=lambda **kw: bert.bert_base_mlm(
                **{"pipeline_stages": 4,
                   "pipeline_microbatches": 8, **kw}),
            input_kind="tokens", param_count=0),
        "bert_tiny_pp": ModelSpec(
            name="bert_tiny_pp", objective="mlm",
            build=lambda **kw: bert.tiny_bert_mlm(
                **{"pipeline_stages": 2,
                   "pipeline_microbatches": 4, **kw}),
            input_kind="tokens", param_count=0),
        # 4-layer variant: layers_per_stage=2 admits interleaved 1f1b with
        # pipeline_virtual_stages=2 — the schedule A/B geometry used by
        # tests/test_pipeline.py, bench.py and the pipeline_1f1b perf-gate
        # workload.
        "bert_tiny_pp4": ModelSpec(
            name="bert_tiny_pp4", objective="mlm",
            build=lambda **kw: bert.tiny_bert_mlm(
                **{"num_layers": 4, "pipeline_stages": 2,
                   "pipeline_microbatches": 4, **kw}),
            input_kind="tokens", param_count=0),
        # 4 layers over 4 stages (1 layer/stage): stage count divisible by
        # pipeline mesh axes 1/2/4, so one model can re-form across
        # pipeline degrees — the cross-axis elastic soak geometry
        # (tests/test_elastic_resume.py, launch.py --elastic-geometry).
        # 2 microbatches keeps the tick count (M+P-1) minimal: the soak
        # measures re-formation outage, and the first post-resume step is
        # on that clock. Dropout off: flax derives dropout masks from the
        # module tree, and re-grouping layers into stages changes that
        # tree — so across a pipeline-degree change the masks are
        # legitimately different random draws. Zeroing dropout makes the
        # uninterrupted run a valid parity reference; everything else
        # about the cross-axis path is mask-independent.
        "bert_tiny_pp44": ModelSpec(
            name="bert_tiny_pp44", objective="mlm",
            build=lambda **kw: bert.tiny_bert_mlm(
                **{"num_layers": 4, "pipeline_stages": 4,
                   "pipeline_microbatches": 2, "dropout_rate": 0.0, **kw}),
            input_kind="tokens", param_count=0),
    }


def get_model(name: str, *, dtype: Any = jnp.bfloat16, **kw: Any):
    """Build a model module by registry name."""
    spec = model_spec(name)
    return spec.build(dtype=dtype, **kw)


def model_spec(name: str) -> ModelSpec:
    reg = _registry()
    if name not in reg:
        raise KeyError(f"unknown model {name!r}; have {sorted(reg)}")
    return reg[name]


def available_models() -> tuple[str, ...]:
    return tuple(sorted(_registry()))
