"""Llama-family decoder-only causal LM in Flax, TPU-first.

The modern-LM counterpart to models/gpt.py (beyond the reference's scope,
like GPT): RMSNorm, rotary position embeddings (no position table — any
sequence length), SwiGLU MLP, grouped-query attention, no biases, untied
LM head. Shares the logical-axis sharding rules (tp via ``heads``/``mlp``/
``vocab``, sp activations, fsdp ``embed``), the causal flash/ring attention
impls, and the one trainer. The llama2_7b geometry's parameter count
matches the canonical checkpoint exactly (6,738,415,616 — asserted via
eval_shape in tests/test_llama.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu import compat
from distributeddeeplearning_tpu.ops.embedding import embedding_lookup

Dtype = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32          # < num_heads = grouped-query attention
    intermediate_size: int = 11008
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dropout_rate: float = 0.0       # llama pretraining uses no dropout
    attention_impl: str = "dense"   # dense | flash | ring | zigzag (causal)
    remat: bool = False
    # KV-cache buffer length for decode mode (RoPE has no position table,
    # so this is the only static sequence bound generation needs).
    decode_cache_len: int = 2048

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def _dense(features, logical_axes, name, dtype):
    return nn.Dense(
        features, dtype=dtype, param_dtype=jnp.float32, use_bias=False,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(0.02), logical_axes),
        name=name)


def _rms_norm(cfg: LlamaConfig, dtype, name: str):
    return nn.RMSNorm(epsilon=cfg.rms_eps, dtype=dtype,
                      param_dtype=jnp.float32, name=name)


def apply_rope(x, *, theta: float, offset=0, positions=None):
    """Rotary embedding, half-split (rotate_half) convention: x (B, S, H, D)
    rotated by (offset + index) along dim 1 — ``offset`` (may be traced)
    positions a decode-mode single token at its absolute index, while
    ``positions`` overrides the arange entirely for layouts where slot !=
    absolute position: an (S,) int array shared across the batch (the
    zigzag permutation) or a (B, S) array when every row sits at its own
    position (paged decode — each serve slot's length). f32 rotation
    regardless of storage dtype (sin/cos in bf16 visibly degrades
    long-range phase)."""
    b, s, h, d = x.shape
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    pos = (jnp.asarray(positions, jnp.float32) if positions is not None
           else offset + jnp.arange(s, dtype=jnp.float32))
    ang = pos[..., None] * freqs              # (S, d/2) or (B, S, d/2)
    if ang.ndim == 2:
        ang = ang[None]                       # shared across the batch
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig
    dtype: Dtype

    @nn.compact
    def __call__(self, x, pad_mask, *, deterministic: bool,
                 decode: bool = False, positions=None, paged_state=None):
        cfg = self.cfg
        b, s, _ = x.shape
        d = cfg.head_dim
        q = _dense(cfg.num_heads * d, ("embed", "heads"), "q_proj",
                   self.dtype)(x).reshape(b, s, cfg.num_heads, d)
        k = _dense(cfg.num_kv_heads * d, ("embed", "heads"), "k_proj",
                   self.dtype)(x).reshape(b, s, cfg.num_kv_heads, d)
        v = _dense(cfg.num_kv_heads * d, ("embed", "heads"), "v_proj",
                   self.dtype)(x).reshape(b, s, cfg.num_kv_heads, d)
        if decode and paged_state is not None:
            return self._paged_decode_step(q, k, v, paged_state)
        if decode:
            return self._decode_step(q, k, v)
        # ``positions`` carries the zigzag permutation: in that layout slot
        # i holds absolute token perm[i], and RoPE's rotation must follow
        # the token, not the slot, for the causal geometry to survive the
        # relayout (the attention impl compares permuted *positions*, so
        # q·k phase differences must encode true distances).
        q = apply_rope(q, theta=cfg.rope_theta, positions=positions)
        k = apply_rope(k, theta=cfg.rope_theta, positions=positions)
        if cfg.num_kv_heads != cfg.num_heads:
            # GQA: repeat KV groups to full heads for the shared attention
            # impls (saves KV *parameters/cache*; attention compute matches
            # MHA — the standard training-time treatment).
            rep = cfg.num_heads // cfg.num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        from distributeddeeplearning_tpu.ops.attention import (
            multihead_attention)
        # cfg.dropout_rate defaults to 0 (the canonical Llama recipe); a
        # user who opts in gets the same attention-probability dropout as
        # every other family, in every impl (ops/attention.py contract).
        out = multihead_attention(
            q, k, v, pad_mask, impl=cfg.attention_impl, causal=True,
            dtype=self.dtype, dropout_rate=cfg.dropout_rate,
            dropout_rng=(self.make_rng("dropout")
                         if not deterministic and cfg.dropout_rate > 0
                         else None),
            deterministic=deterministic)
        return _dense(cfg.hidden_size, ("heads", "embed"), "o_proj",
                      self.dtype)(out)

    def _paged_decode_step(self, q, k, v, paged_state):
        """Paged decode (serve/kv_cache.py): rows are serve SLOTS, each at
        its own absolute position ``paged_state.lengths[i]`` — RoPE rotates
        per row ((B, s) positions) before the pool write, same
        absolute-position-before-caching convention as the dense branch.
        Pools are engine-seeded cache leaves at kv-head width. A
        PagedBlockState advances each slot up to s tokens at once (block
        column t rotates at lengths + t); a plain PagedState is the
        one-token step."""
        from distributeddeeplearning_tpu.serve import kv_cache as paged
        cfg = self.cfg
        s = q.shape[1]
        pos = paged_state.lengths[:, None] + jnp.arange(s)[None]  # (B, s)
        q = apply_rope(q, theta=cfg.rope_theta, positions=pos)
        k = apply_rope(k, theta=cfg.rope_theta, positions=pos)
        pk = self.variable("cache", "pages_k",
                           paged.unseeded_pool("pages_k"))
        pv = self.variable("cache", "pages_v",
                           paged.unseeded_pool("pages_v"))
        if isinstance(paged_state, paged.PagedBlockState):
            out, pk.value, pv.value = paged.paged_attention_block(
                q, k, v, pk.value, pv.value, paged_state)
        else:
            out, pk.value, pv.value = paged.paged_attention_step(
                q, k, v, pk.value, pv.value, paged_state)
        return _dense(cfg.hidden_size, ("heads", "embed"), "o_proj",
                      self.dtype)(out)

    def _decode_step(self, q, k, v):
        """KV-cache decode: a block of s tokens (prompt prefill) or one
        token (steady state), K/V cached at kv-head width (the GQA saving
        generation exists for), grouped-einsum attention over the live
        prefix. RoPE rotates q/k at absolute decode indices BEFORE caching
        (absolute-position convention)."""
        cfg = self.cfg
        b, s, _, d = q.shape
        if s > cfg.decode_cache_len:
            raise ValueError(
                f"decode block of {s} tokens exceeds decode_cache_len="
                f"{cfg.decode_cache_len}; rebuild with a larger cache "
                f"(the CLI sizes it to prompt+new automatically)")
        kvh = cfg.num_kv_heads
        rep = cfg.num_heads // kvh
        ck = self.variable("cache", "cached_key", jnp.zeros,
                           (b, cfg.decode_cache_len, kvh, d), self.dtype)
        cv = self.variable("cache", "cached_value", jnp.zeros,
                           (b, cfg.decode_cache_len, kvh, d), self.dtype)
        ci = self.variable("cache", "cache_index",
                           lambda: jnp.zeros((), jnp.int32))
        idx = ci.value
        q = apply_rope(q, theta=cfg.rope_theta, offset=idx)
        k = apply_rope(k, theta=cfg.rope_theta, offset=idx)
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, k.astype(self.dtype), (0, idx, 0, 0))
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, v.astype(self.dtype), (0, idx, 0, 0))
        ci.value = idx + s
        qg = q.reshape(b, s, kvh, rep, d)
        scores = jnp.einsum("bqgrd,bkgd->bgrqk", qg, ck.value) * (d ** -0.5)
        # Query j (global idx+j) sees cache slots <= idx+j.
        live = (jnp.arange(cfg.decode_cache_len)[None, :]
                <= (idx + jnp.arange(s))[:, None])[None, None, None]
        scores = jnp.where(live, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(self.dtype)
        out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, cv.value)
        out = out.reshape(b, s, cfg.num_heads * d)
        return _dense(cfg.hidden_size, ("heads", "embed"), "o_proj",
                      self.dtype)(out)


class LlamaBlock(nn.Module):
    """Pre-RMSNorm block: x + Attn(norm(x)); x + SwiGLU(norm(x))."""

    cfg: LlamaConfig
    dtype: Dtype

    @nn.compact
    def __call__(self, x, pad_mask, *, deterministic: bool,
                 decode: bool = False, positions=None, paged_state=None):
        cfg = self.cfg
        h = _rms_norm(cfg, self.dtype, "attention_norm")(x)
        h = LlamaAttention(cfg, self.dtype, name="attention")(
            h, pad_mask, deterministic=deterministic, decode=decode,
            positions=positions, paged_state=paged_state)
        x = x + nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        h = _rms_norm(cfg, self.dtype, "mlp_norm")(x)
        gate = _dense(cfg.intermediate_size, ("embed", "mlp"), "gate_proj",
                      self.dtype)(h)
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), "up_proj",
                    self.dtype)(h)
        h = _dense(cfg.hidden_size, ("mlp", "embed"), "down_proj",
                   self.dtype)(nn.silu(gate) * up)
        return x + nn.Dropout(cfg.dropout_rate)(
            h, deterministic=deterministic)


class LlamaLM(nn.Module):
    """Decoder-only LM; returns (B, S, vocab) f32 logits (untied head)."""

    cfg: LlamaConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, *,
                 train: bool = True, decode: bool = False,
                 paged_state=None):
        cfg = self.cfg
        deterministic = not train
        b, s = input_ids.shape
        if paged_state is not None and not decode:
            raise ValueError("paged_state is a decode-mode construct; "
                             "call with decode=True")
        paged_block = paged_state is not None and hasattr(paged_state,
                                                          "n_new")
        if paged_state is not None and not paged_block and s != 1:
            raise ValueError(
                f"paged decode advances exactly one token per slot per "
                f"step (got a block of {s}); prompts prefill through the "
                f"dense decode path and are packed into pages "
                f"(serve/kv_cache.pack_prefill_cache), or pass a "
                f"PagedBlockState for the block fast path")
        pad_mask = (jnp.ones((b, s), jnp.bool_) if attention_mask is None
                    else attention_mask.astype(jnp.bool_))

        # Zigzag layout (load-balanced causal ring): same whole-model
        # permuted-layout scheme as models/gpt.py — ids/mask permuted once
        # here, hidden states unpermuted once before the head. GPT feeds the
        # permutation to its learned position TABLE; RoPE has no table, so
        # the permutation rides into every attention layer as the rotation
        # indices instead (``positions``). RMSNorm/SwiGLU/residuals are
        # positionwise and thus permutation-oblivious.
        inv = positions = None
        if cfg.attention_impl == "zigzag" and not decode:
            from distributeddeeplearning_tpu.parallel.ring_attention import (
                zigzag_indices)
            ambient = compat.get_abstract_mesh()
            n_seq = (ambient.shape.get("seq", 1)
                     if ambient is not None and not ambient.empty else 1)
            if n_seq > 1:
                if s % (2 * n_seq):
                    raise ValueError(
                        f"attention_impl='zigzag' needs seq_len divisible "
                        f"by 2*seq_shards (= {2 * n_seq}); got {s}")
                perm, inv = zigzag_indices(s, n_seq)
                input_ids = input_ids[:, perm]
                pad_mask = pad_mask[:, perm]
                positions = jnp.asarray(perm)

        embed = self.param(
            "embed_tokens",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        # embedding_lookup: fsdp-friendly scatter-add backward
        # (ops/embedding.py; VERDICT r4 Missing #5).
        x = embedding_lookup(embed, input_ids).astype(self.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        for i in range(cfg.num_layers):
            block = LlamaBlock(cfg, self.dtype, name=f"layer{i}")
            if cfg.remat and not decode:
                x = nn.remat(
                    lambda mdl, h, m, p: mdl(
                        h, m, deterministic=deterministic, positions=p))(
                    block, x, pad_mask, positions)
            else:
                x = block(x, pad_mask, deterministic=deterministic,
                          decode=decode, positions=positions,
                          paged_state=paged_state)
            x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        if inv is not None:
            # Natural order restored BEFORE the head — callers keep the
            # standard position-aligned logits contract (see models/gpt.py
            # for the hidden-vs-logits traffic argument).
            x = x[:, jnp.asarray(inv)]
            x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        x = _rms_norm(cfg, self.dtype, "final_norm")(x)
        logits = _dense(cfg.vocab_size, ("embed", "vocab"), "lm_head",
                        self.dtype)(x)
        return logits.astype(jnp.float32)


def llama2_7b(vocab_size: int = 32000, dtype: Dtype = jnp.bfloat16,
              seq_len: Optional[int] = None, **overrides: Any) -> LlamaLM:
    """Llama-2-7B geometry (32L/4096H/32 heads, SwiGLU 11008)."""
    del seq_len  # RoPE: no position table, any sequence length
    return LlamaLM(LlamaConfig(vocab_size=vocab_size, **overrides),
                   dtype=dtype)


def tinyllama_1b(vocab_size: int = 32000, dtype: Dtype = jnp.bfloat16,
                 seq_len: Optional[int] = None, **overrides: Any) -> LlamaLM:
    """TinyLlama-1.1B geometry (22L/2048H/32 heads, 4 KV heads, 5632)."""
    del seq_len
    return LlamaLM(
        LlamaConfig(vocab_size=vocab_size, hidden_size=2048, num_layers=22,
                    num_heads=32, num_kv_heads=4, intermediate_size=5632,
                    **overrides), dtype=dtype)


def tiny_llama(vocab_size: int = 1024, dtype: Dtype = jnp.float32,
               seq_len: Optional[int] = None, **overrides: Any) -> LlamaLM:
    """Test-sized llama (GQA 4 heads / 2 KV heads)."""
    del seq_len
    return LlamaLM(
        LlamaConfig(vocab_size=vocab_size,
                    **{"hidden_size": 64, "num_layers": 2, "num_heads": 4,
                       "num_kv_heads": 2, "intermediate_size": 128,
                       **overrides}), dtype=dtype)
