"""Bottleneck block over the fused matmul+BN ops (conv-epilogue fusion).

The TPU-first answer to the BN bandwidth tax (BASELINE.md profile +
on-chip A/B): a ResNet bottleneck's 1x1 convolutions run as Pallas
matmuls that carry the BatchNorm work in their prologue/epilogue
(ops/fused_linear_bn.py), so per block:

- conv1 computes bn1's Σ/Σ² in its epilogue  → bn1 statistics pass gone;
- conv3 normalizes conv2's raw output in its prologue and computes bn3's
  Σ/Σ² in its epilogue → bn2 apply pass (read+write) AND bn3 statistics
  pass gone; bn2's backward reductions ride conv3's backward matmul;
- the downsample 1x1 computes its BN's Σ/Σ² in its epilogue.

What stays on XLA: the 3x3 conv (not a matmul), bn1's apply (its output
must materialize as the 3x3's input), and the block exit
relu(bn3_apply + downsample_bn_apply) — one elementwise pass XLA fuses
well, and its output must materialize as the residual carrier anyway.

Variable layout is IDENTICAL to models/resnet.py's BottleneckBlock
(params conv{1,2,3}/kernel, downsample_conv/kernel, bn*/{scale,bias};
batch_stats bn*/{mean,var}; same momentum/eps/zero-init-γ3 semantics),
so the same checkpoint drives either path and tests can compare the two
numerically with shared weights. Eval mode (running averages) uses the
classic composition — inference BN is elementwise and XLA-optimal.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.ops.fused_linear_bn import (
    bn_linear_stats, linear_stats)


class _Kernel(nn.Module):
    """Bare conv-kernel parameter holder, name/shape-compatible with
    ``nn.Conv`` so checkpoints transfer between paths."""

    shape: tuple
    init: Any = nn.initializers.variance_scaling(2.0, "fan_out", "normal")

    @nn.compact
    def __call__(self):
        return self.param("kernel", self.init, self.shape, jnp.float32)


class _BNVars(nn.Module):
    """BN parameter/state holder matching ``nn.BatchNorm``'s layout."""

    features: int
    scale_init: Any = nn.initializers.ones

    @nn.compact
    def __call__(self):
        c = self.features
        scale = self.param("scale", self.scale_init, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32), (c,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32), (c,))
        return scale, bias, ra_mean, ra_var


class FusedBottleneckBlock(nn.Module):
    """Drop-in BottleneckBlock with 1x1 convs on the fused matmul+BN path."""

    filters: int
    strides: int
    dtype: Any = jnp.bfloat16
    momentum: float = 0.9
    epsilon: float = 1e-5
    # SyncBN: mesh axis name(s) to pmean statistics over (shard_map DP
    # path only). The epilogue sums are per-shard; syncing is two (C,)
    # pmeans per BN — negligible next to the gradient allreduce.
    axis_name: Any = None
    # v2 coverage (VERDICT r4 Next #2): run the 3x3 conv itself as a
    # Pallas kernel with bn1-apply in its prologue and bn2-statistics in
    # its epilogue (ops/fused_conv_bn.py) — the a1 materialization and the
    # bn2 stats pass disappear. Stride-2 blocks (3/16 in ResNet-50) keep
    # the XLA conv path; the kernel is stride-1/pad-1 only.
    conv3_fused: bool = False

    def _stats(self, s, ss, m: int):
        mean, ex2 = s / m, ss / m
        if self.axis_name is not None:
            mean = jax.lax.pmean(mean, self.axis_name)
            ex2 = jax.lax.pmean(ex2, self.axis_name)
        var = jnp.maximum(ex2 - mean * mean, 0.0)
        return mean, var

    def _update_running(self, ra_mean, ra_var, mean, var):
        if not self.is_initializing():
            ra_mean.value = (self.momentum * ra_mean.value
                             + (1.0 - self.momentum)
                             * jax.lax.stop_gradient(mean))
            ra_var.value = (self.momentum * ra_var.value
                            + (1.0 - self.momentum)
                            * jax.lax.stop_gradient(var))

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        f = self.filters
        cin = x.shape[-1]
        need_ds = cin != f * 4 or self.strides != 1
        w1 = _Kernel((1, 1, cin, f), name="conv1")()
        w2k = _Kernel((3, 3, f, f), name="conv2")()
        w3 = _Kernel((1, 1, f, f * 4), name="conv3")()
        g1, b1, rm1, rv1 = _BNVars(f, name="bn1")()
        g2, b2, rm2, rv2 = _BNVars(f, name="bn2")()
        g3, b3, rm3, rv3 = _BNVars(
            f * 4, scale_init=nn.initializers.zeros, name="bn3")()
        if need_ds:
            wd = _Kernel((1, 1, cin, f * 4), name="downsample_conv")()
            gd, bd, rmd, rvd = _BNVars(f * 4, name="downsample_bn")()

        x = jnp.asarray(x, self.dtype)
        if not train:
            return self._eval_path(
                x, w1, w2k, w3, (g1, b1, rm1, rv1), (g2, b2, rm2, rv2),
                (g3, b3, rm3, rv3),
                (wd, gd, bd, rmd, rvd) if need_ds else None)

        eps = self.epsilon
        b, h, w_sp = x.shape[0], x.shape[1], x.shape[2]
        x2d = x.reshape(-1, cin)

        # conv1 (1x1) + bn1-stats epilogue.
        y1, s1, ss1 = linear_stats(
            x2d, w1.reshape(cin, f).astype(self.dtype))
        m1 = y1.shape[0]
        mean1, var1 = self._stats(s1, ss1, m1)
        self._update_running(rm1, rv1, mean1, var1)
        inv1 = jax.lax.rsqrt(var1 + eps)

        if self.conv3_fused and self.strides == 1:
            # v2: the 3x3 consumes RAW y1 — bn1's apply happens in the
            # conv kernel's prologue and bn2's Σ/Σ² in its epilogue
            # (ops/fused_conv_bn.py); neither a1 nor a stats pass touches
            # HBM.
            from distributeddeeplearning_tpu.ops.fused_conv_bn import (
                bn_conv3x3_stats)
            y2, s2, ss2 = bn_conv3x3_stats(
                y1.reshape(b, h, w_sp, f), mean1, inv1, g1, b1,
                w2k.astype(self.dtype), True, True)
            h_out, w_out = y2.shape[1], y2.shape[2]
            y2d = y2.reshape(-1, f)
            m2 = y2d.shape[0]
            mean2, var2 = self._stats(s2, ss2, m2)
        else:
            # bn1 apply must materialize (it feeds the XLA 3x3) — one
            # elementwise pass, XLA-fused.
            a1 = jnp.maximum(
                (y1.astype(jnp.float32) - mean1) * (inv1 * g1) + b1, 0.0
            ).astype(self.dtype).reshape(b, h, w_sp, f)

            # conv2: XLA 3x3 (stride lives here, v1.5), raw output y2.
            y2 = jax.lax.conv_general_dilated(
                a1, w2k.astype(self.dtype),
                window_strides=(self.strides, self.strides),
                padding=[(1, 1), (1, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=self.dtype)
            # Output spatial dims come from the strided conv itself — with
            # odd inputs ceil(h/2) != h//2, and the ::stride shortcut slice
            # agrees with the conv, not with floor division.
            h_out, w_out = y2.shape[1], y2.shape[2]
            y2d = y2.reshape(-1, f)
            m2 = y2d.shape[0]
            # bn2 statistics: one XLA multi-output reduce over y2 (its
            # apply pass is what conv3's prologue absorbs).
            y2f = y2d.astype(jnp.float32)
            mean2, var2 = self._stats(y2f.sum(axis=0),
                                      (y2f * y2f).sum(axis=0), m2)
        self._update_running(rm2, rv2, mean2, var2)
        inv2 = jax.lax.rsqrt(var2 + eps)

        # conv3 (1x1): bn2-apply prologue + bn3-stats epilogue.
        y3, s3, ss3 = bn_linear_stats(
            y2d, mean2, inv2, g2, b2,
            w3.reshape(f, f * 4).astype(self.dtype), True, True)
        mean3, var3 = self._stats(s3, ss3, m2)
        self._update_running(rm3, rv3, mean3, var3)
        inv3 = jax.lax.rsqrt(var3 + eps)

        # Shortcut path.
        if need_ds:
            xs = x[:, ::self.strides, ::self.strides, :] \
                if self.strides != 1 else x
            xs2d = xs.reshape(-1, cin)
            yd, sd, ssd = linear_stats(
                xs2d, wd.reshape(cin, f * 4).astype(self.dtype))
            meand, vard = self._stats(sd, ssd, yd.shape[0])
            self._update_running(rmd, rvd, meand, vard)
            invd = jax.lax.rsqrt(vard + eps)
            shortcut = ((yd.astype(jnp.float32) - meand) * (invd * gd) + bd)
        else:
            shortcut = x2d.astype(jnp.float32)

        # Block exit: bn3-apply + residual + ReLU — one elementwise pass,
        # materialized because it is the next block's input AND residual.
        out = jnp.maximum(
            (y3.astype(jnp.float32) - mean3) * (inv3 * g3) + b3 + shortcut,
            0.0).astype(self.dtype)
        return out.reshape(b, h_out, w_out, f * 4)

    def _eval_path(self, x, w1, w2k, w3, bn1, bn2, bn3, ds):
        """Running-average inference: the classic composition (elementwise
        BN, XLA-fused); numerics identical to the unfused block's eval."""
        eps = self.epsilon
        f = self.filters

        def apply_bn(y, vars_, relu):
            g, bb, rm, rv = vars_
            inv = jax.lax.rsqrt(rv.value + eps)
            out = (y.astype(jnp.float32) - rm.value) * (inv * g) + bb
            if relu:
                out = jnp.maximum(out, 0.0)
            return out.astype(self.dtype)

        y = jax.lax.conv_general_dilated(
            x, w1.astype(self.dtype), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=self.dtype)
        y = apply_bn(y, bn1, True)
        y = jax.lax.conv_general_dilated(
            y, w2k.astype(self.dtype), (self.strides, self.strides),
            [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=self.dtype)
        y = apply_bn(y, bn2, True)
        y = jax.lax.conv_general_dilated(
            y, w3.astype(self.dtype), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=self.dtype)
        if ds is not None:
            wd, gd, bd, rmd, rvd = ds
            sc = jax.lax.conv_general_dilated(
                x, wd.astype(self.dtype),
                (self.strides, self.strides), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=self.dtype)
            sc = apply_bn(sc, (gd, bd, rmd, rvd), False)
        else:
            sc = x
        g3, b3, rm3, rv3 = bn3
        inv3 = jax.lax.rsqrt(rv3.value + eps)
        out = ((y.astype(jnp.float32) - rm3.value) * (inv3 * g3) + b3
               + sc.astype(jnp.float32))
        return jnp.maximum(out, 0.0).astype(self.dtype)
