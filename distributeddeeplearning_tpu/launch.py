"""Pod-slice launcher — the TPU-native replacement for mpirun / Batch-AI.

The reference launched N ranks with ``mpirun`` under a Batch-AI job and let
MPI handle rendezvous (SURVEY.md §2 #9-#10, §3.1). On TPU the moral
equivalents are:

- **rendezvous**: ``jax.distributed.initialize(coordinator, num_processes,
  process_id)`` — replaces ``MPI_Init``; XLA then sees the global device set.
- **process placement**: one Python process per TPU host. On Cloud TPU pod
  slices the TPU runtime supplies topology env vars and
  ``jax.distributed.initialize()`` needs no arguments; everywhere else (and
  for local multi-process development on CPU) this module wires the
  coordinator explicitly through ``DDL_*`` env vars.
- **failure detection** (SURVEY.md §5.3): the reference's mpirun died whole
  when any rank died. ``monitor`` reproduces that for the processes this
  launcher owns: first local child to exit nonzero triggers terminate-all
  and a nonzero launcher exit, so a wrapper can restart the job from the
  last checkpoint (fail-whole + checkpoint-resume semantics). Across hosts
  (``--hostfile``), each host's launcher only sees its own child; a *remote*
  rank's death reaches the survivors through jax.distributed's coordinator
  heartbeat, which tears down their processes — the local launcher then
  reports that nonzero exit. Cross-host detection latency is therefore the
  heartbeat timeout, not this monitor's poll interval.

Usage (local dev, 2 simulated hosts on CPU):
    python launch.py --num-processes 2 -- python train.py --backend cpu ...

Usage (TPU pod slice, run on every host, e.g. via gcloud ssh --worker=all):
    python launch.py -- python train.py --backend tpu ...
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Optional, Sequence

from distributeddeeplearning_tpu.observability import health, telemetry
from distributeddeeplearning_tpu.robustness import faults

ENV_COORDINATOR = "DDL_COORDINATOR"
ENV_NUM_PROCESSES = "DDL_NUM_PROCESSES"
ENV_PROCESS_ID = "DDL_PROCESS_ID"

# Exit codes that mean "the operator stopped the job", never "retry":
# 130 = SIGINT via shell, 143 = SIGTERM via shell (128+15), -15 = SIGTERM
# as reported by subprocess.Popen for a signal-killed child.
_OPERATOR_STOP_RCS = (130, 143, -15)


@dataclasses.dataclass(frozen=True)
class ProcessSpec:
    """One training process in the job (≈ one MPI rank, one TPU host)."""

    process_id: int
    num_processes: int
    coordinator: str  # "host:port"

    def env(self) -> dict[str, str]:
        return {
            ENV_COORDINATOR: self.coordinator,
            ENV_NUM_PROCESSES: str(self.num_processes),
            ENV_PROCESS_ID: str(self.process_id),
        }


def plan_local(num_processes: int, *, port: int = 9531,
               coordinator_host: str = "127.0.0.1") -> list[ProcessSpec]:
    """Specs for N processes on this machine (multi-host simulation)."""
    coord = f"{coordinator_host}:{port}"
    return [ProcessSpec(i, num_processes, coord) for i in range(num_processes)]


def plan_from_hostfile(path: str, *, port: int = 9531) -> list[ProcessSpec]:
    """Specs from a one-host-per-line file (first host is coordinator) —
    the launcher-side analogue of an MPI hostfile. Each host runs the
    launcher with ``--process-id`` matching its line number."""
    with open(path) as f:
        hosts = [ln.strip() for ln in f if ln.strip()
                 and not ln.lstrip().startswith("#")]
    if not hosts:
        raise ValueError(f"hostfile {path!r} lists no hosts")
    coord = f"{hosts[0]}:{port}"
    return [ProcessSpec(i, len(hosts), coord) for i in range(len(hosts))]


def maybe_initialize_distributed() -> Optional[int]:
    """Called by train.py at startup. Joins the job if one is configured.

    Returns the process id when distributed was initialized, else None.
    Resolution order:
    1. ``DDL_*`` env vars (set by this launcher) → explicit initialize;
    2. Cloud TPU pod-slice env (multi-host libtpu topology) → argless
       initialize, deferring to the TPU runtime's own metadata;
    3. otherwise single-process: do nothing.
    """
    import jax

    if os.environ.get(ENV_COORDINATOR):
        spec = ProcessSpec(
            process_id=int(os.environ[ENV_PROCESS_ID]),
            num_processes=int(os.environ[ENV_NUM_PROCESSES]),
            coordinator=os.environ[ENV_COORDINATOR])
        jax.distributed.initialize(
            coordinator_address=spec.coordinator,
            num_processes=spec.num_processes,
            process_id=spec.process_id)
        return spec.process_id
    # Cloud TPU pod slice: the runtime's own topology env lists >1 worker
    # host; defer entirely to it. (A 1-host listing — also what this dev
    # image sets — is single-process and needs no rendezvous.)
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len(workers.split(",")) > 1:
        jax.distributed.initialize()
        return jax.process_index()
    return None


# ---------------------------------------------------------------------------
# Child spawn + monitoring (fail-whole semantics)
# ---------------------------------------------------------------------------

def spawn(spec: ProcessSpec, command: Sequence[str], *,
          extra_env: Optional[dict[str, str]] = None) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(spec.env())
    env.update(extra_env or {})
    return subprocess.Popen(list(command), env=env)


def monitor(children: Sequence[subprocess.Popen], *,
            poll_interval_s: float = 0.2,
            grace_s: float = 10.0,
            heartbeat_dir: Optional[str] = None,
            heartbeat_timeout_s: float = 0.0,
            tele: Optional[telemetry.Telemetry] = None) -> int:
    """Wait for all children; kill the survivors as soon as one fails.

    Returns 0 iff every child exited 0 — the contract a restart wrapper
    checks before deciding to relaunch from the last checkpoint.

    ``heartbeat_dir`` + ``heartbeat_timeout_s > 0`` arm the hang watchdog
    (observability/health.py): a child whose heartbeat file stops aging for
    longer than the timeout is presumed hung (deadlocked collective, wedged
    loader) and SIGKILLed — the next poll then attributes it and tears the
    job down fail-whole, exactly like a crash. A child that never beat is
    never judged, so startup/compile time needs no grace tuning.
    """
    procs = list(children)
    hb_armed = heartbeat_dir is not None and heartbeat_timeout_s > 0
    try:
        while True:
            if hb_armed:
                for idx, age in health.check_stale(
                        heartbeat_dir, len(procs), heartbeat_timeout_s):
                    if idx < len(procs) and procs[idx].poll() is None:
                        print(f"# launcher: child {idx} heartbeat stale "
                              f"({age:.1f}s > {heartbeat_timeout_s:.1f}s) — "
                              f"presumed hung, killing (fail-whole)",
                              file=sys.stderr, flush=True)
                        if tele is not None:
                            tele.instant("launcher:heartbeat_stale",
                                         child=idx, age_s=round(age, 1))
                        procs[idx].kill()
            codes = [p.poll() for p in procs]
            failed = [(i, c) for i, c in enumerate(codes)
                      if c not in (None, 0)]
            if failed:
                # Failure attribution BEFORE tearing the job down: once the
                # survivors are terminated every child is "dead", and the
                # operator can no longer tell the culprit from the victims.
                for idx, c in failed:
                    why = f" (killed by signal {-c})" if c < 0 else ""
                    print(f"# launcher: child {idx} exited rc={c}{why}",
                          file=sys.stderr, flush=True)
                survivors = sum(1 for c in codes if c is None)
                if survivors:
                    print(f"# launcher: terminating {survivors} surviving "
                          "child(ren) (fail-whole)",
                          file=sys.stderr, flush=True)
                _terminate_all(procs, grace_s)
                return int(failed[0][1]) or 1
            if all(c == 0 for c in codes):
                return 0
            time.sleep(poll_interval_s)
    except KeyboardInterrupt:
        _terminate_all(procs, grace_s)
        return 130


def _terminate_all(procs: Sequence[subprocess.Popen], grace_s: float) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()


def run_local(num_processes: int, command: Sequence[str], *,
              port: int = 9531,
              child_env: Optional[dict[int, dict[str, str]]] = None,
              heartbeat_dir: Optional[str] = None,
              heartbeat_timeout_s: float = 0.0,
              tele: Optional[telemetry.Telemetry] = None) -> int:
    """Spawn + monitor N local processes (the `mpirun -np N` replacement).

    ``child_env`` maps process_id → extra env vars for that child only —
    how ``--child-fault-plan`` targets one rank of a simulated pod.
    With a ``heartbeat_dir``, children are told to beat there
    (``DDL_HEARTBEAT_DIR``; the train loop beats on log cadence) and the
    monitor watches for staleness.
    """
    specs = plan_local(num_processes, port=port)
    if heartbeat_dir is not None:
        # A restarted attempt must not be judged by the previous attempt's
        # (now frozen) heartbeats: each attempt re-arms from nothing.
        for s in specs:
            try:
                os.remove(health.heartbeat_path(heartbeat_dir, s.process_id))
            except OSError:
                pass
    children = []
    for s in specs:
        extra = dict((child_env or {}).get(s.process_id) or {})
        if heartbeat_dir is not None:
            extra[health.ENV_HEARTBEAT_DIR] = heartbeat_dir
        children.append(spawn(s, command, extra_env=extra))
    return monitor(children, heartbeat_dir=heartbeat_dir,
                   heartbeat_timeout_s=heartbeat_timeout_s, tele=tele)


def _backoff_delay(attempt: int, base_s: float, cap_s: float) -> float:
    """Exponential backoff with deterministic jitter.

    Jitter de-synchronises many launchers restarting after a shared-cause
    failure (coordinator blip) without randomness — a Knuth-hash fraction of
    the attempt number, so reruns of the same job back off identically.
    """
    delay = base_s * 2.0 ** max(attempt - 1, 0)
    frac = ((attempt * 2654435761) & 0xFFFFFFFF) / 2.0 ** 32
    return min(delay * (1.0 + 0.25 * frac), cap_s)


def _latest_ckpt_step(directory: str) -> Optional[int]:
    """Largest numeric subdirectory of an orbax root, stdlib-only (the
    launcher must not import jax/orbax — children own the accelerator)."""
    try:
        steps = [int(n) for n in os.listdir(directory) if n.isdigit()]
    except OSError:
        return None
    return max(steps, default=None)


def run_with_restarts(run_once, max_restarts: int, *,
                      backoff_s: float = 3.0,
                      backoff_cap_s: float = 60.0,
                      progress_fn: Optional[Callable[[], object]] = None,
                      sleep=None,
                      tele: Optional[telemetry.Telemetry] = None) -> int:
    """Fail-whole + auto-relaunch: the in-launcher restart wrapper.

    The reference's failure story was "mpirun dies whole, Batch AI resubmits
    the job" (SURVEY.md §5.3); ``run_once`` is one whole-job attempt, and a
    nonzero exit relaunches it with exponential backoff (``backoff_s``
    doubling per consecutive failure, capped at ``backoff_cap_s``, with
    deterministic jitter). Paired with checkpoint-resume
    (train/checkpoint.py restores latest and the data stream repositions),
    each relaunch continues from the last saved step.

    ``max_restarts`` is a *restart budget between progress*, not a lifetime
    total: when ``progress_fn`` (e.g. latest checkpoint step) returns a new
    value after an attempt, the budget refills — a job that keeps advancing
    survives any number of transient faults, while a crash-loop that never
    reaches the next checkpoint exhausts the budget and stops. Without a
    ``progress_fn`` the budget is a plain lifetime cap (old behaviour).

    Operator stops (rc 130 = SIGINT, 143/-15 = SIGTERM) are never retried —
    a preempted child that saved and exited via its SIGTERM handler, or an
    operator ^C, must not resurrect the job.

    Each attempt exports its index via ``DDL_FAULT_PLAN``'s companion env
    (``DDL_RESTART_ATTEMPT``) so attempt-scoped fault injection
    (robustness/faults.py) fires only on the intended attempt.

    ``sleep`` is injectable for tests (defaults to ``time.sleep``).
    """
    do_sleep = sleep if sleep is not None else time.sleep
    total = 0          # attempts so far (exported to children)
    window_used = 0    # restarts consumed since the last observed progress
    last_progress = progress_fn() if progress_fn is not None else None
    prev_attempt = os.environ.get(faults.ENV_ATTEMPT)
    try:
        while True:
            os.environ[faults.ENV_ATTEMPT] = str(total)
            rc = run_once()
            total += 1
            if rc == 0:
                return rc
            if tele is not None:
                tele.instant("launcher:attempt_failed", rc=rc,
                             attempt=total - 1)
            if rc in _OPERATOR_STOP_RCS:
                print(f"# launcher: operator stop (rc={rc}); not retrying",
                      file=sys.stderr, flush=True)
                return rc
            if progress_fn is not None:
                progress = progress_fn()
                if progress != last_progress and window_used:
                    print(f"# launcher: progress observed "
                          f"({last_progress!r} -> {progress!r}); restart "
                          "budget refilled",
                          file=sys.stderr, flush=True)
                    window_used = 0
                last_progress = progress
            if window_used >= max_restarts:
                if progress_fn is not None and max_restarts > 0:
                    print(f"# launcher: no progress across {window_used} "
                          f"consecutive restarts (budget={max_restarts}) — "
                          "crash loop, giving up",
                          file=sys.stderr, flush=True)
                return rc
            window_used += 1
            delay = _backoff_delay(window_used, backoff_s, backoff_cap_s)
            if tele is not None:
                tele.instant("launcher:restart", attempt=total,
                             restart=window_used, backoff_s=round(delay, 2))
            print(f"# launcher: job failed (rc={rc}); restart "
                  f"{window_used}/{max_restarts} in {delay:.1f}s "
                  f"(resumes from the latest checkpoint)",
                  file=sys.stderr, flush=True)
            do_sleep(delay)
    finally:
        if prev_attempt is None:
            os.environ.pop(faults.ENV_ATTEMPT, None)
        else:
            os.environ[faults.ENV_ATTEMPT] = prev_attempt


def run_from_hostfile(path: str, process_id: int, command: Sequence[str], *,
                      port: int = 9531) -> int:
    """Run this host's single process of a hostfile-defined job."""
    specs = plan_from_hostfile(path, port=port)
    if not 0 <= process_id < len(specs):
        raise ValueError(
            f"process_id {process_id} out of range for {len(specs)} hosts")
    child = spawn(specs[process_id], command)
    return monitor([child])


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--num-processes", type=int, default=None,
                   help="spawn N local processes (multi-host simulation / "
                        "single-host multi-process)")
    p.add_argument("--hostfile", default=None,
                   help="one host per line; first is coordinator")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's line number in --hostfile")
    p.add_argument("--port", type=int, default=9531,
                   help="coordinator port")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="relaunch the whole job up to N times after a "
                        "failure (resumes from the latest checkpoint); when "
                        "the command names a --checkpoint-dir, N is a budget "
                        "*between checkpoints* — progress refills it, a "
                        "crash loop exhausts it")
    p.add_argument("--backoff", type=float, default=3.0,
                   help="base restart delay in seconds (doubles per "
                        "consecutive failure, deterministic jitter)")
    p.add_argument("--backoff-cap", type=float, default=60.0,
                   help="maximum restart delay in seconds")
    p.add_argument("--child-fault-plan", action="append", default=[],
                   metavar="IDX:PLAN",
                   help="inject a fault plan (robustness/faults.py grammar) "
                        "into one local child, e.g. 0:sigkill@20 "
                        "(repeatable; local --num-processes jobs only)")
    p.add_argument("--heartbeat-timeout", type=float, default=0.0,
                   help="kill a child whose heartbeat file "
                        "(observability/health.py; children beat on their "
                        "log cadence) goes stale for this many seconds — a "
                        "hung child then feeds the normal fail-whole + "
                        "restart machinery. 0 disables. Size it well above "
                        "the training log interval")
    p.add_argument("--heartbeat-dir", default=None,
                   help="heartbeat file directory (default: a fresh temp "
                        "dir; local --num-processes jobs only)")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent compile cache shared by every child and "
                        "every restart attempt (docs/compile_cache.md); "
                        "default $DDL_COMPILE_CACHE or the repo-local "
                        ".cache/jax_compile; 'off' disables")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command, after `--`")
    args = p.parse_args(argv)

    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        p.error("no training command given (pass it after `--`)")

    # One compile cache for the whole job: resolve launcher flag > training
    # command's own --compile-cache-dir > env > default, then export it so
    # EVERY child of EVERY restart attempt lands on the same cache — a
    # restarted attempt then loads the previous attempt's executables
    # instead of recompiling (perf/compile_cache.py; jax-free here).
    from distributeddeeplearning_tpu.perf import compile_cache
    cache_flag = (args.compile_cache_dir
                  if args.compile_cache_dir is not None
                  else _flag_from_command(command, "--compile-cache-dir"))
    cache_dir = compile_cache.resolve_dir(cache_flag)
    if cache_dir is not None:
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            cache_dir = None
    compile_cache.export_env(cache_dir)

    if args.hostfile:
        if args.process_id is None:
            p.error("--hostfile requires --process-id")
        if args.child_fault_plan:
            p.error("--child-fault-plan only supports local "
                    "(--num-processes) jobs")
        if args.heartbeat_timeout:
            # The watchdog kills by local child index; a hostfile job's one
            # local child maps to a remote rank set this launcher cannot
            # attribute — keep the semantics local-only, like restarts.
            p.error("--heartbeat-timeout only supports local "
                    "(--num-processes) jobs")
        if args.max_restarts:
            # A per-host restart decision is wrong for a whole-job semantic:
            # hosts whose rank exited 0 would never relaunch, leaving the
            # restarted ranks hung in rendezvous. Multi-host restart needs a
            # whole-job resubmit (every host's launcher rerun), like the
            # reference's Batch-AI resubmission.
            p.error("--max-restarts only supports local (--num-processes) "
                    "jobs; for --hostfile, wrap the launcher in a "
                    "whole-job resubmit loop on every host")
        return run_from_hostfile(args.hostfile, args.process_id, command,
                                 port=args.port)
    n = args.num_processes or 1

    child_env: dict[int, dict[str, str]] = {}
    for item in args.child_fault_plan:
        idx_s, sep, plan = item.partition(":")
        if not sep or not idx_s.isdigit():
            p.error(f"--child-fault-plan expects IDX:PLAN, got {item!r}")
        faults.parse_plan(plan)  # fail fast on grammar errors
        child_env.setdefault(int(idx_s), {})[faults.ENV_PLAN] = plan

    progress_fn = None
    ckpt_dir = _checkpoint_dir_from_command(command)
    if ckpt_dir is not None:
        progress_fn = lambda: _latest_ckpt_step(ckpt_dir)  # noqa: E731

    heartbeat_dir = None
    if args.heartbeat_timeout > 0:
        import tempfile
        heartbeat_dir = args.heartbeat_dir or tempfile.mkdtemp(
            prefix="ddl_heartbeat_")

    # When the training command traces (--trace-dir), the launcher records
    # its restart/backoff/stale-heartbeat instants too and merges them into
    # process 0's trace AFTER the job ends — one Chrome-trace file then
    # shows the whole chaos story (step phases + faults + restarts).
    # Timestamps are CLOCK_MONOTONIC, shared across local processes.
    trace_dir = _flag_from_command(command, "--trace-dir")
    tele = None
    if trace_dir is not None:
        tele = telemetry.Telemetry(enabled=True, process_index=os.getpid(),
                                   process_name="launcher")

    rc = run_with_restarts(
        lambda: run_local(n, command, port=args.port, child_env=child_env,
                          heartbeat_dir=heartbeat_dir,
                          heartbeat_timeout_s=args.heartbeat_timeout,
                          tele=tele),
        args.max_restarts, backoff_s=args.backoff,
        backoff_cap_s=args.backoff_cap, progress_fn=progress_fn, tele=tele)
    if tele is not None:
        tele.export(telemetry.trace_path(trace_dir, 0))
    return rc


def _flag_from_command(command: Sequence[str], flag: str) -> Optional[str]:
    """The value of ``flag`` in the training command, if present."""
    for i, tok in enumerate(command):
        if tok == flag and i + 1 < len(command):
            return command[i + 1]
        if tok.startswith(flag + "="):
            return tok.split("=", 1)[1]
    return None


def _checkpoint_dir_from_command(command: Sequence[str]) -> Optional[str]:
    """The training command's --checkpoint-dir, if present — lets the
    restart budget observe progress (new checkpoint step => refill)."""
    return _flag_from_command(command, "--checkpoint-dir")


if __name__ == "__main__":
    sys.exit(main())
