"""Pod-slice launcher — the TPU-native replacement for mpirun / Batch-AI.

The reference launched N ranks with ``mpirun`` under a Batch-AI job and let
MPI handle rendezvous (SURVEY.md §2 #9-#10, §3.1). On TPU the moral
equivalents are:

- **rendezvous**: ``jax.distributed.initialize(coordinator, num_processes,
  process_id)`` — replaces ``MPI_Init``; XLA then sees the global device set.
- **process placement**: one Python process per TPU host. On Cloud TPU pod
  slices the TPU runtime supplies topology env vars and
  ``jax.distributed.initialize()`` needs no arguments; everywhere else (and
  for local multi-process development on CPU) this module wires the
  coordinator explicitly through ``DDL_*`` env vars.
- **failure detection** (SURVEY.md §5.3): the reference's mpirun died whole
  when any rank died. ``monitor`` reproduces that for the processes this
  launcher owns: first local child to exit nonzero triggers terminate-all
  and a nonzero launcher exit, so a wrapper can restart the job from the
  last checkpoint (fail-whole + checkpoint-resume semantics). Across hosts
  (``--hostfile``), each host's launcher only sees its own child; a *remote*
  rank's death reaches the survivors through jax.distributed's coordinator
  heartbeat, which tears down their processes — the local launcher then
  reports that nonzero exit. Cross-host detection latency is therefore the
  heartbeat timeout, not this monitor's poll interval.

Usage (local dev, 2 simulated hosts on CPU):
    python launch.py --num-processes 2 -- python train.py --backend cpu ...

Usage (TPU pod slice, run on every host, e.g. via gcloud ssh --worker=all):
    python launch.py -- python train.py --backend tpu ...
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Optional, Sequence

from distributeddeeplearning_tpu import hostmesh
from distributeddeeplearning_tpu.observability import flight as flightlib
from distributeddeeplearning_tpu.observability import health, telemetry
from distributeddeeplearning_tpu.observability import metrics as metricslib
from distributeddeeplearning_tpu.robustness import faults

ENV_COORDINATOR = "DDL_COORDINATOR"
ENV_NUM_PROCESSES = "DDL_NUM_PROCESSES"
ENV_PROCESS_ID = "DDL_PROCESS_ID"

# Exit codes that mean "the operator stopped the job", never "retry":
# 130 = SIGINT via shell, 143 = SIGTERM via shell (128+15), -15 = SIGTERM
# as reported by subprocess.Popen for a signal-killed child.
_OPERATOR_STOP_RCS = (130, 143, -15)


@dataclasses.dataclass(frozen=True)
class ProcessSpec:
    """One training process in the job (≈ one MPI rank, one TPU host)."""

    process_id: int
    num_processes: int
    coordinator: str  # "host:port"

    def env(self) -> dict[str, str]:
        return {
            ENV_COORDINATOR: self.coordinator,
            ENV_NUM_PROCESSES: str(self.num_processes),
            ENV_PROCESS_ID: str(self.process_id),
        }


def plan_local(num_processes: int, *, port: int = 9531,
               coordinator_host: str = "127.0.0.1") -> list[ProcessSpec]:
    """Specs for N processes on this machine (multi-host simulation)."""
    coord = f"{coordinator_host}:{port}"
    return [ProcessSpec(i, num_processes, coord) for i in range(num_processes)]


def plan_from_hostfile(path: str, *, port: int = 9531) -> list[ProcessSpec]:
    """Specs from a one-host-per-line file (first host is coordinator) —
    the launcher-side analogue of an MPI hostfile. Each host runs the
    launcher with ``--process-id`` matching its line number."""
    with open(path) as f:
        hosts = [ln.strip() for ln in f if ln.strip()
                 and not ln.lstrip().startswith("#")]
    if not hosts:
        raise ValueError(f"hostfile {path!r} lists no hosts")
    coord = f"{hosts[0]}:{port}"
    return [ProcessSpec(i, len(hosts), coord) for i in range(len(hosts))]


def maybe_initialize_distributed() -> Optional[int]:
    """Called by train.py at startup. Joins the job if one is configured.

    Returns the process id when distributed was initialized, else None.
    Resolution order:
    1. ``DDL_*`` env vars (set by this launcher) → explicit initialize;
    2. Cloud TPU pod-slice env (multi-host libtpu topology) → argless
       initialize, deferring to the TPU runtime's own metadata;
    3. otherwise single-process: do nothing.
    """
    import jax

    if os.environ.get(ENV_COORDINATOR):
        spec = ProcessSpec(
            process_id=int(os.environ[ENV_PROCESS_ID]),
            num_processes=int(os.environ[ENV_NUM_PROCESSES]),
            coordinator=os.environ[ENV_COORDINATOR])
        if spec.num_processes > 1:
            # Multi-process on the CPU backend (virtual hosts: tests, the
            # elastic soak, chaos bench) needs a real cross-process
            # collectives transport. jaxlib's CPU client defaults to
            # 'none' and then rejects ANY computation spanning processes
            # ("Multiprocess computations aren't implemented on the CPU
            # backend"); the option is config-only — jax never reads it
            # from the environment — so exporting a var in the launcher
            # cannot fix it. Gloo-over-TCP ships in jaxlib; turn it on
            # before the first backend use. No-op on TPU (the option only
            # affects CPU clients) and on jax builds without it.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except (AttributeError, ValueError):
                pass
        jax.distributed.initialize(
            coordinator_address=spec.coordinator,
            num_processes=spec.num_processes,
            process_id=spec.process_id)
        return spec.process_id
    # Cloud TPU pod slice: the runtime's own topology env lists >1 worker
    # host; defer entirely to it. (A 1-host listing — also what this dev
    # image sets — is single-process and needs no rendezvous.)
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len(workers.split(",")) > 1:
        jax.distributed.initialize()
        return jax.process_index()
    return None


# ---------------------------------------------------------------------------
# Child spawn + monitoring (fail-whole semantics)
# ---------------------------------------------------------------------------

def spawn(spec: ProcessSpec, command: Sequence[str], *,
          extra_env: Optional[dict[str, str]] = None) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(spec.env())
    env.update(extra_env or {})
    return subprocess.Popen(list(command), env=env)


def attribute_failure(heartbeat_dir: Optional[str], slot: int, *,
                      hung: bool = False, ever_beat: bool = False,
                      epoch: Optional[int] = None) -> str:
    """Classify one failed child from the heartbeat evidence.

    The hang watchdog and the elastic controller share ONE staleness clock
    (``--heartbeat-timeout`` over the same files), so the three verdicts
    partition cleanly:

    - ``hung``       — the watchdog killed it for heartbeat staleness while
      the process lived; the host is unusable either way, so elastic mode
      treats it as host loss.
    - ``host_lost``  — the child HAD a heartbeat and the file vanished with
      the process: a dead host takes its filesystem presence with it (the
      ``host_lost`` fault models exactly this). A transient crash leaves
      its last heartbeat behind.
    - ``crash``      — heartbeat intact (or never armed): the host is fine,
      the process died; the generic restart path applies.
    """
    if hung:
        return "hung"
    if (heartbeat_dir is not None and ever_beat and not os.path.exists(
            health.heartbeat_path(heartbeat_dir, slot, epoch))):
        return "host_lost"
    return "crash"


class ElasticController:
    """Membership controller for ``--elastic``: automatic re-formation at a
    new data-parallel degree on host loss or gain.

    The controller owns the live host set of a local simulated pod. When
    the monitor attributes a failure as host loss (or hang — same staleness
    clock), the lost host leaves the set and the next attempt re-plans at
    the surviving degree: fewer processes, the training command's ``--dp``
    rewritten to ``devices_per_host x live_hosts``, coordinator env
    re-exported by ``plan_local`` as usual. The global batch is left
    untouched, so a transformer trajectory continues bitwise through the
    re-formation (tests/test_elastic_resume.py). A returning host announces
    itself through the rejoin marker (observability/health.py); the monitor
    then stops the job gracefully (children save at the next step boundary
    via the loop's preemption handler) and the same machinery grows the
    plan back.

    Re-formations are PLANNED reconfigurations: ``run_with_restarts``
    relaunches without exponential backoff (the delay exists to
    de-synchronise shared-cause crash storms) and without burning the
    restart budget (which guards against crash loops — a re-formation IS
    the recovery). Pure stdlib, like the rest of the launcher.

    **Rendezvous membership** (this PR): the controller holds a membership
    ``epoch``, bumped per committed re-formation. A membership change
    (join/rejoin/drain marker, or a host-loss attribution) raises the
    reform barrier (``health.request_reform``) instead of tearing surviving
    children down: each child polls the barrier at its step boundary, saves
    collectively when every member is alive (``save=True``), and exits
    ``health.EXIT_DRAIN`` voluntarily. Heartbeats are namespaced per epoch
    so a previous epoch's frozen files never feed the new epoch's staleness
    clock. An optional **geometry table** (``--elastic-geometry``) maps
    live-host counts to full mesh shapes (dp/pp/optimizer-sharding),
    letting re-formation cross the ZeRO-stage and pipeline axes — the
    canonical checkpoint layout makes any pair restorable. When the table
    forces a smaller host count than survived, **topology-aware survivor
    selection** (hostmesh.select_survivors) keeps the ICI ring contiguous,
    logging chosen + rejected candidates to flight.
    """

    def __init__(self, num_hosts: int, heartbeat_dir: str, *, base_dp: int,
                 min_hosts: int = 1,
                 tele: Optional[telemetry.Telemetry] = None,
                 geometry: Optional[dict[int, dict]] = None):
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        if base_dp % num_hosts:
            raise ValueError(
                f"--dp {base_dp} does not divide evenly over {num_hosts} "
                f"host(s); elastic re-formation needs a whole number of "
                f"data shards per host")
        self.max_hosts = num_hosts
        self.devices_per_host = base_dp // num_hosts
        self.heartbeat_dir = heartbeat_dir
        self.min_hosts = max(int(min_hosts), 1)
        self.tele = tele
        self.geometry = dict(geometry or {})  # live hosts -> mesh shape
        self.epoch = 0                        # membership epoch (0 = first)
        self.live = list(range(num_hosts))   # original host ids, sorted
        self.events: list[dict] = []         # committed re-formations
        self._slots = list(self.live)        # slot -> host id, per attempt
        self._pending: Optional[dict] = None
        self._export: Optional[dict] = None

    @property
    def num_processes(self) -> int:
        return len(self.live)

    @property
    def degree(self) -> int:
        geo = self.geometry.get(len(self.live))
        if geo is not None:
            return int(geo["dp"])
        return self.devices_per_host * len(self.live)

    @property
    def has_pending(self) -> bool:
        """A membership change is planned but not yet committed — the
        monitor uses this to pick the drain barrier over fail-whole."""
        return self._pending is not None

    @property
    def pending_trigger(self) -> Optional[str]:
        return self._pending["trigger"] if self._pending else None

    def command(self, command: Sequence[str]) -> list[str]:
        """The training command at the current membership. Default: ``--dp``
        rewritten to ``devices_per_host x live`` (global batch untouched —
        trajectories stay bitwise). With a geometry entry for the live host
        count, the full mesh shape is rewritten: ``--dp``, ``--pp``, and
        ``--optimizer-sharding`` — cross-axis re-formation routed through
        the canonical checkpoint layout."""
        out = _with_flag_value(command, "--dp", str(self.degree))
        geo = self.geometry.get(len(self.live))
        if geo is not None:
            if "pp" in geo:
                out = _with_flag_value(out, "--pp", str(geo["pp"]))
            if "sharding" in geo:
                out = _with_flag_value(out, "--optimizer-sharding",
                                       str(geo["sharding"]))
        return out

    def child_env(self, base: dict[int, dict[str, str]]) -> dict:
        """Per-slot extra env for the next attempt. Fault plans follow the
        ORIGINAL host identity across re-formations (a plan injected into
        host 2 stays with host 2 whatever slot it lands on); every child
        learns its membership epoch (``DDL_ELASTIC_EPOCH`` — heartbeat
        namespace + barrier filter) and original host id
        (``DDL_ELASTIC_HOST`` — drain announcements); and every child of a
        re-formed attempt receives the membership event
        (``DDL_ELASTIC_EVENT``) so the loop can close the
        reconfiguration_time_s span on the shared monotonic clock."""
        self._slots = list(self.live)
        out: dict[int, dict[str, str]] = {}
        for slot, host in enumerate(self._slots):
            env = dict(base.get(host) or {})
            env[health.ENV_ELASTIC_EPOCH] = str(self.epoch)
            env[health.ENV_ELASTIC_HOST] = str(host)
            if self._export is not None:
                env[health.ENV_ELASTIC_EVENT] = json.dumps(self._export)
            out[slot] = env
        self._export = None  # the event tags exactly one attempt
        return out

    def note_failure(self, slot: int, rc: int, *, hung: bool = False,
                     ever_beat: bool = False) -> str:
        """Attribute one failed child; on host loss, shrink the membership
        and plan a re-formation. Returns the attribution string."""
        label = attribute_failure(self.heartbeat_dir, slot, hung=hung,
                                  ever_beat=ever_beat, epoch=self.epoch)
        if label in ("hung", "host_lost"):
            host = (self._slots[slot] if slot < len(self._slots) else None)
            if host is not None and host in self.live:
                before = self.degree
                self.live.remove(host)
                self._shrink_to_feasible()
                self._plan(label, before)
        return label

    def poll_rejoin(self) -> bool:
        """Consume a rejoin/join announcement. True when lost hosts
        returned and a grow re-formation is now planned — the monitor
        should then drain the job at the barrier. A marker with no one
        missing is consumed and ignored (the cluster is already whole)."""
        kind = health.consume_join(self.heartbeat_dir)
        if kind is None:
            return False
        if len(self.live) >= self.max_hosts:
            return False
        before = self.degree
        self.live = list(range(self.max_hosts))
        self._plan(kind, before)
        return True

    def poll_membership(self) -> Optional[str]:
        """Consume every pending membership announcement — join/rejoin
        markers (grow) and drain markers (planned leave) — and return the
        trigger of the newly planned re-formation, or None. The monitor
        calls this each poll; a returned trigger means it should raise the
        reform barrier."""
        trigger: Optional[str] = None
        if self.poll_rejoin():
            trigger = self._pending["trigger"]
        for host in health.consume_drains(self.heartbeat_dir):
            if host not in self.live:
                continue
            if len(self.live) <= max(self.min_hosts, 1):
                print(f"# launcher: drain of host {host} ignored — only "
                      f"{len(self.live)} host(s) live (min "
                      f"{self.min_hosts})", file=sys.stderr, flush=True)
                continue
            before = self.degree
            self.live.remove(host)
            self._shrink_to_feasible()
            self._plan("host_drain", before)
            trigger = "host_drain"
        return trigger

    def _shrink_to_feasible(self) -> None:
        """With a geometry table, only listed host counts (plus the full
        pod) have a mesh shape; after a shrink, land on the largest
        feasible count <= survivors using topology-aware survivor
        selection (ICI ring contiguity). Without a table every count is
        feasible (dp-only scaling) and this is a no-op."""
        if not self.geometry:
            return
        feasible = sorted(set(self.geometry) | {self.max_hosts})
        target = max((f for f in feasible if f <= len(self.live)),
                     default=None)
        if target is None or target >= len(self.live):
            return
        survivors, rejected = hostmesh.select_survivors(
            self.live, target, self.max_hosts)
        contiguous = hostmesh.is_contiguous_arc(survivors, self.max_hosts)
        flightlib.get().record(
            "survivor_selection", candidates=list(self.live),
            chosen=survivors, rejected=rejected,
            ring_size=self.max_hosts, contiguous=contiguous)
        print(f"# launcher: topology-aware shrink: hosts {self.live} -> "
              f"{survivors} (rejected {rejected}; ring "
              f"{'contiguous' if contiguous else 'BISECTED'})",
              file=sys.stderr, flush=True)
        self.live = survivors

    def note_drain_complete(self) -> None:
        """Stamp the moment the last member exited into the pending event —
        the detect->drain phase boundary of the reconfiguration breakdown."""
        if self._pending is not None:
            self._pending["drain_done_t"] = telemetry.now_s()

    def _plan(self, trigger: str, degree_before: int) -> None:
        now = telemetry.now_s()
        flightlib.get().record("membership", trigger=trigger,
                               degree_before=degree_before,
                               degree_after=self.degree,
                               live_hosts=list(self.live))
        if self._pending is None:
            self._pending = {"trigger": trigger,
                             "degree_before": degree_before,
                             "degree_after": self.degree,
                             # save-capable iff no member is dead: a
                             # collective save would wedge on a lost rank.
                             "save": trigger not in ("host_lost", "hung"),
                             "detect_t": now}
        else:
            # Several hosts lost in one poll: one re-formation, spanning
            # from the pre-batch degree to the final survivors.
            self._pending["degree_after"] = self.degree
            if trigger in ("host_lost", "hung"):
                self._pending["save"] = False

    def take_reconfiguration(self) -> Optional[dict]:
        """The planned membership change for the next attempt, or None.
        Consumes the plan, bumps the membership epoch, and arms the event
        export for the re-formed children. Returns None (give up -> generic
        failure path) when the surviving set is below ``min_hosts``."""
        event, self._pending = self._pending, None
        if event is None:
            return None
        if len(self.live) < self.min_hosts or not self.live:
            print(f"# launcher: elastic: only {len(self.live)} host(s) "
                  f"survive (min {self.min_hosts}) — cannot re-form, "
                  f"giving up", file=sys.stderr, flush=True)
            return None
        event["degree_after"] = self.degree
        self.epoch += 1
        event["epoch"] = self.epoch
        self.events.append(dict(event))
        self._export = dict(event)
        return event


def _await_drain(procs: Sequence[subprocess.Popen], heartbeat_dir: str,
                 elastic: "ElasticController", trigger: str, *, save: bool,
                 deadline_s: float, poll_interval_s: float = 0.2,
                 grace_s: float = 10.0) -> None:
    """Raise the reform barrier and wait for every child to exit on its
    own — the no-teardown half of rendezvous membership. Children poll the
    barrier at their step boundaries, save collectively when ``save`` (all
    members alive), and exit ``health.EXIT_DRAIN``. A child wedged past the
    deadline (e.g. a survivor stuck in a collective with a dead peer that
    gloo never errors out of) is escalated to the old terminate path."""
    health.request_reform(heartbeat_dir, epoch=elastic.epoch + 1,
                          trigger=trigger, save=save)
    flightlib.get().record("reform_barrier", trigger=trigger,
                           epoch=elastic.epoch + 1, save=save)
    deadline = time.monotonic() + deadline_s
    escalated = False
    while any(p.poll() is None for p in procs):
        if time.monotonic() > deadline:
            late = sum(1 for p in procs if p.poll() is None)
            print(f"# launcher: drain barrier deadline ({deadline_s:.0f}s) "
                  f"passed with {late} child(ren) still running — "
                  f"escalating to terminate", file=sys.stderr, flush=True)
            flightlib.get().record("drain_escalated", children=late,
                                   trigger=trigger)
            _terminate_all(procs, grace_s)
            escalated = True
            break
        time.sleep(poll_interval_s)
    elastic.note_drain_complete()
    health.clear_reform(heartbeat_dir)
    rcs = [p.poll() for p in procs]
    drained = sum(1 for rc in rcs if rc == health.EXIT_DRAIN)
    flightlib.get().record("drain_complete", trigger=trigger,
                           drained=drained, rcs=[int(rc) if rc is not None
                                                 else None for rc in rcs],
                           escalated=escalated)
    print(f"# launcher: drain complete — {drained}/{len(rcs)} child(ren) "
          f"exited at the barrier (rc={health.EXIT_DRAIN})",
          file=sys.stderr, flush=True)


def monitor(children: Sequence[subprocess.Popen], *,
            poll_interval_s: float = 0.2,
            grace_s: float = 10.0,
            heartbeat_dir: Optional[str] = None,
            heartbeat_timeout_s: float = 0.0,
            heartbeat_epoch: int = 0,
            tele: Optional[telemetry.Telemetry] = None,
            elastic: Optional["ElasticController"] = None) -> int:
    """Wait for all children; kill the survivors as soon as one fails.

    Returns 0 iff every child exited 0 — the contract a restart wrapper
    checks before deciding to relaunch from the last checkpoint.

    ``heartbeat_dir`` + ``heartbeat_timeout_s > 0`` arm the hang watchdog
    (observability/health.py): a child whose heartbeat file stops aging for
    longer than the timeout is presumed hung (deadlocked collective, wedged
    loader) and SIGKILLed — the next poll then attributes it and tears the
    job down fail-whole, exactly like a crash. A child that never beat is
    never judged, so startup/compile time needs no grace tuning.

    With an ``elastic`` controller, failures are attributed from the
    heartbeat evidence (crash vs host_lost vs hung) and host losses shrink
    the controller's membership for the next attempt; a join/rejoin/drain
    marker in the heartbeat dir raises the reform barrier — children save
    at their next step boundary and exit voluntarily (rendezvous
    membership: surviving children are never torn down for a planned
    change). ``heartbeat_epoch`` selects the heartbeat namespace this
    attempt's children beat into.
    """
    procs = list(children)
    hb_armed = heartbeat_dir is not None and heartbeat_timeout_s > 0
    track_beats = heartbeat_dir is not None and (hb_armed or
                                                 elastic is not None)
    ever_beat: set[int] = set()   # slots whose heartbeat file ever appeared
    hung: set[int] = set()        # slots the watchdog killed for staleness
    try:
        while True:
            if track_beats:
                for idx in range(len(procs)):
                    if idx not in ever_beat and os.path.exists(
                            health.heartbeat_path(heartbeat_dir, idx,
                                                  heartbeat_epoch)):
                        ever_beat.add(idx)
            if hb_armed:
                for idx, age in health.check_stale(
                        heartbeat_dir, len(procs), heartbeat_timeout_s,
                        epoch=heartbeat_epoch):
                    if idx < len(procs) and procs[idx].poll() is None:
                        print(f"# launcher: child {idx} heartbeat stale "
                              f"({age:.1f}s > {heartbeat_timeout_s:.1f}s) — "
                              f"presumed hung, killing (fail-whole)",
                              file=sys.stderr, flush=True)
                        if tele is not None:
                            tele.instant("launcher:heartbeat_stale",
                                         child=idx, age_s=round(age, 1))
                        flightlib.get().record("heartbeat_stale", child=idx,
                                               age_s=round(age, 1))
                        hung.add(idx)
                        procs[idx].kill()
            if elastic is not None:
                trigger = elastic.poll_membership()
                if trigger is not None:
                    # A membership change was announced while every member
                    # is alive: raise the reform barrier instead of tearing
                    # the job down. Children save collectively at their
                    # next step boundary and exit EXIT_DRAIN voluntarily —
                    # run_with_restarts then relaunches at the new
                    # membership without burning the budget.
                    if trigger in ("host_rejoin", "host_join"):
                        print(f"# launcher: host rejoin announced "
                              f"({trigger}) — draining at the reform "
                              f"barrier to re-form at the grown degree",
                              file=sys.stderr, flush=True)
                    else:
                        print(f"# launcher: host drain announced — "
                              f"draining at the reform barrier to re-form "
                              f"at the shrunk degree",
                              file=sys.stderr, flush=True)
                    if tele is not None:
                        tele.instant("launcher:membership_change",
                                     trigger=trigger)
                    if trigger in ("host_rejoin", "host_join"):
                        flightlib.get().record("host_rejoin",
                                               trigger=trigger)
                    else:
                        flightlib.get().record("host_drain", trigger=trigger)
                    _await_drain(procs, heartbeat_dir, elastic, trigger,
                                 save=True, deadline_s=max(grace_s, 30.0),
                                 poll_interval_s=poll_interval_s,
                                 grace_s=grace_s)
                    return 1
            codes = [p.poll() for p in procs]
            failed = [(i, c) for i, c in enumerate(codes)
                      if c not in (None, 0)]
            if failed:
                # Failure attribution BEFORE tearing the job down: once the
                # survivors are terminated every child is "dead", and the
                # operator can no longer tell the culprit from the victims.
                for idx, c in failed:
                    why = f" (killed by signal {-c})" if c < 0 else ""
                    attributed = ""
                    label = None
                    if heartbeat_dir is not None:
                        if elastic is not None:
                            label = elastic.note_failure(
                                idx, int(c), hung=idx in hung,
                                ever_beat=idx in ever_beat)
                        else:
                            label = attribute_failure(
                                heartbeat_dir, idx, hung=idx in hung,
                                ever_beat=idx in ever_beat)
                        attributed = f" [attributed: {label}]"
                        if tele is not None:
                            tele.instant("launcher:failure_attributed",
                                         child=idx, attribution=label)
                    flightlib.get().record("child_exit", child=idx,
                                           rc=int(c), attribution=label)
                    print(f"# launcher: child {idx} exited rc={c}{why}"
                          f"{attributed}", file=sys.stderr, flush=True)
                survivors = sum(1 for c in codes if c is None)
                if (elastic is not None and elastic.has_pending
                        and survivors):
                    # Host loss with a re-formation planned: survivors
                    # drain at the reform barrier instead of being torn
                    # down. save=False — the dead peer makes a collective
                    # save impossible (a gloo save would wedge on the
                    # missing rank); survivors exit at their next step
                    # boundary and the re-formed attempt resumes from the
                    # last committed checkpoint. A survivor that crashes
                    # first on its own collective error counts as exited.
                    print(f"# launcher: membership loss — draining "
                          f"{survivors} surviving child(ren) at the reform "
                          f"barrier (no teardown)",
                          file=sys.stderr, flush=True)
                    _await_drain(procs, heartbeat_dir, elastic,
                                 elastic.pending_trigger or "host_lost",
                                 save=False,
                                 deadline_s=max(grace_s, 10.0),
                                 poll_interval_s=poll_interval_s,
                                 grace_s=grace_s)
                    return int(failed[0][1]) or 1
                if survivors:
                    print(f"# launcher: terminating {survivors} surviving "
                          "child(ren) (fail-whole)",
                          file=sys.stderr, flush=True)
                _terminate_all(procs, grace_s)
                return int(failed[0][1]) or 1
            if all(c == 0 for c in codes):
                return 0
            time.sleep(poll_interval_s)
    except KeyboardInterrupt:
        _terminate_all(procs, grace_s)
        return 130


def _terminate_all(procs: Sequence[subprocess.Popen], grace_s: float) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()


def run_local(num_processes: int, command: Sequence[str], *,
              port: int = 9531,
              child_env: Optional[dict[int, dict[str, str]]] = None,
              heartbeat_dir: Optional[str] = None,
              heartbeat_timeout_s: float = 0.0,
              heartbeat_epoch: int = 0,
              tele: Optional[telemetry.Telemetry] = None,
              elastic: Optional["ElasticController"] = None) -> int:
    """Spawn + monitor N local processes (the `mpirun -np N` replacement).

    ``child_env`` maps process_id → extra env vars for that child only —
    how ``--child-fault-plan`` targets one rank of a simulated pod.
    With a ``heartbeat_dir``, children are told to beat there
    (``DDL_HEARTBEAT_DIR``; the train loop beats on log cadence) and the
    monitor watches for staleness. ``heartbeat_epoch`` names the membership
    epoch this attempt beats under (elastic rendezvous; 0 = the legacy
    un-namespaced files).
    """
    specs = plan_local(num_processes, port=port)
    if heartbeat_dir is not None:
        # A restarted attempt must not be judged by the previous attempt's
        # (now frozen) heartbeats: each attempt re-arms from nothing.
        for s in specs:
            try:
                os.remove(health.heartbeat_path(heartbeat_dir, s.process_id,
                                                heartbeat_epoch))
            except OSError:
                pass
    children = []
    for s in specs:
        extra = dict((child_env or {}).get(s.process_id) or {})
        if heartbeat_dir is not None:
            extra[health.ENV_HEARTBEAT_DIR] = heartbeat_dir
            extra.setdefault(health.ENV_ELASTIC_EPOCH, str(heartbeat_epoch))
        children.append(spawn(s, command, extra_env=extra))
    return monitor(children, heartbeat_dir=heartbeat_dir,
                   heartbeat_timeout_s=heartbeat_timeout_s,
                   heartbeat_epoch=heartbeat_epoch, tele=tele,
                   elastic=elastic)


def _backoff_delay(attempt: int, base_s: float, cap_s: float) -> float:
    """Exponential backoff with deterministic jitter.

    Jitter de-synchronises many launchers restarting after a shared-cause
    failure (coordinator blip) without randomness — a Knuth-hash fraction of
    the attempt number, so reruns of the same job back off identically.
    """
    delay = base_s * 2.0 ** max(attempt - 1, 0)
    frac = ((attempt * 2654435761) & 0xFFFFFFFF) / 2.0 ** 32
    return min(delay * (1.0 + 0.25 * frac), cap_s)


def _latest_ckpt_step(directory: str) -> Optional[int]:
    """Largest numeric subdirectory of an orbax root, stdlib-only (the
    launcher must not import jax/orbax — children own the accelerator)."""
    try:
        steps = [int(n) for n in os.listdir(directory) if n.isdigit()]
    except OSError:
        return None
    return max(steps, default=None)


def run_with_restarts(run_once, max_restarts: int, *,
                      backoff_s: float = 3.0,
                      backoff_cap_s: float = 60.0,
                      progress_fn: Optional[Callable[[], object]] = None,
                      sleep=None,
                      tele: Optional[telemetry.Telemetry] = None,
                      elastic: Optional["ElasticController"] = None) -> int:
    """Fail-whole + auto-relaunch: the in-launcher restart wrapper.

    The reference's failure story was "mpirun dies whole, Batch AI resubmits
    the job" (SURVEY.md §5.3); ``run_once`` is one whole-job attempt, and a
    nonzero exit relaunches it with exponential backoff (``backoff_s``
    doubling per consecutive failure, capped at ``backoff_cap_s``, with
    deterministic jitter). Paired with checkpoint-resume
    (train/checkpoint.py restores latest and the data stream repositions),
    each relaunch continues from the last saved step.

    ``max_restarts`` is a *restart budget between progress*, not a lifetime
    total: when ``progress_fn`` (e.g. latest checkpoint step) returns a new
    value after an attempt, the budget refills — a job that keeps advancing
    survives any number of transient faults, while a crash-loop that never
    reaches the next checkpoint exhausts the budget and stops. Without a
    ``progress_fn`` the budget is a plain lifetime cap (old behaviour).

    Operator stops (rc 130 = SIGINT, 143/-15 = SIGTERM) are never retried —
    a preempted child that saved and exited via its SIGTERM handler, or an
    operator ^C, must not resurrect the job.

    Each attempt exports its index via ``DDL_FAULT_PLAN``'s companion env
    (``DDL_RESTART_ATTEMPT``) so attempt-scoped fault injection
    (robustness/faults.py) fires only on the intended attempt.

    With an ``elastic`` controller, an attempt that ended in a PLANNED
    membership change (host lost -> shrink; host rejoined -> grow)
    relaunches immediately: no exponential backoff (the delay exists to
    de-synchronise shared-cause crash storms, not planned
    reconfigurations) and no restart-budget charge (the budget guards
    against crash loops; a re-formation IS the recovery). ^C (rc 130)
    still stops unconditionally.

    ``sleep`` is injectable for tests (defaults to ``time.sleep``).
    """
    do_sleep = sleep if sleep is not None else time.sleep
    total = 0          # attempts so far (exported to children)
    window_used = 0    # restarts consumed since the last observed progress
    last_progress = progress_fn() if progress_fn is not None else None
    prev_attempt = os.environ.get(faults.ENV_ATTEMPT)
    storm_detector = None  # lazy: only elastic jobs pay for it
    try:
        while True:
            os.environ[faults.ENV_ATTEMPT] = str(total)
            rc = run_once()
            total += 1
            if rc == 0:
                return rc
            if tele is not None:
                tele.instant("launcher:attempt_failed", rc=rc,
                             attempt=total - 1)
            flightlib.get().record("attempt_failed", rc=rc,
                                   attempt=total - 1)
            if rc == 130:
                # ^C is ALWAYS an operator stop, even mid-reconfiguration.
                print(f"# launcher: operator stop (rc={rc}); not retrying",
                      file=sys.stderr, flush=True)
                return rc
            if elastic is not None:
                event = elastic.take_reconfiguration()
                if event is not None:
                    print(f"# launcher: elastic re-formation "
                          f"({event['trigger']}): degree "
                          f"{event['degree_before']} -> "
                          f"{event['degree_after']} — relaunching "
                          f"immediately (planned reconfiguration: no "
                          f"backoff, budget untouched)",
                          file=sys.stderr, flush=True)
                    if tele is not None:
                        tele.instant("launcher:elastic_reconfigure",
                                     trigger=event["trigger"],
                                     degree_before=event["degree_before"],
                                     degree_after=event["degree_after"])
                    # The loop records "reconfiguration" when the re-formed
                    # attempt lands its first step; this is the plan side.
                    flightlib.get().record(
                        "reconfiguration_planned",
                        trigger=event["trigger"],
                        degree_before=event["degree_before"],
                        degree_after=event["degree_after"],
                        epoch=event.get("epoch"))
                    # Re-formation storm watch: a handful of planned
                    # re-formations is the feature working; a storm means
                    # membership is flapping faster than training can
                    # amortize (observability/anomaly.py discipline).
                    if storm_detector is None:
                        from distributeddeeplearning_tpu.observability \
                            import anomaly as anomalylib
                        storm_detector = anomalylib.AnomalyDetector()
                    flagged = storm_detector.update_elastic(
                        telemetry.now_s(), epoch=event.get("epoch"))
                    if flagged:
                        from distributeddeeplearning_tpu.observability \
                            import anomaly as anomalylib
                        anomalylib.report(flagged,
                                          flight_rec=flightlib.get(),
                                          tele=tele)
                    if progress_fn is not None:
                        # A re-formed attempt starts a fresh progress
                        # window — don't let the pre-shrink baseline
                        # double-count as progress later.
                        last_progress = progress_fn()
                    continue
            if rc in _OPERATOR_STOP_RCS:
                print(f"# launcher: operator stop (rc={rc}); not retrying",
                      file=sys.stderr, flush=True)
                return rc
            if progress_fn is not None:
                progress = progress_fn()
                if progress != last_progress and window_used:
                    print(f"# launcher: progress observed "
                          f"({last_progress!r} -> {progress!r}); restart "
                          "budget refilled",
                          file=sys.stderr, flush=True)
                    window_used = 0
                last_progress = progress
            if window_used >= max_restarts:
                if progress_fn is not None and max_restarts > 0:
                    print(f"# launcher: no progress across {window_used} "
                          f"consecutive restarts (budget={max_restarts}) — "
                          "crash loop, giving up",
                          file=sys.stderr, flush=True)
                flightlib.get().record("giving_up", rc=rc,
                                       restarts=window_used)
                return rc
            window_used += 1
            delay = _backoff_delay(window_used, backoff_s, backoff_cap_s)
            if tele is not None:
                tele.instant("launcher:restart", attempt=total,
                             restart=window_used, backoff_s=round(delay, 2))
            flightlib.get().record("restart", attempt=total,
                                   restart=window_used,
                                   backoff_s=round(delay, 2))
            print(f"# launcher: job failed (rc={rc}); restart "
                  f"{window_used}/{max_restarts} in {delay:.1f}s "
                  f"(resumes from the latest checkpoint)",
                  file=sys.stderr, flush=True)
            do_sleep(delay)
    finally:
        if prev_attempt is None:
            os.environ.pop(faults.ENV_ATTEMPT, None)
        else:
            os.environ[faults.ENV_ATTEMPT] = prev_attempt


def run_from_hostfile(path: str, process_id: int, command: Sequence[str], *,
                      port: int = 9531) -> int:
    """Run this host's single process of a hostfile-defined job."""
    specs = plan_from_hostfile(path, port=port)
    if not 0 <= process_id < len(specs):
        raise ValueError(
            f"process_id {process_id} out of range for {len(specs)} hosts")
    child = spawn(specs[process_id], command)
    return monitor([child])


# ---------------------------------------------------------------------------
# Serve mode: replica supervision with token-identical re-dispatch
# ---------------------------------------------------------------------------

def _spawn_replica(replica: int, num_replicas: int, workdir: str, *,
                   attempt: int, heartbeat_dir: Optional[str],
                   fault_plan: Optional[str],
                   trace_dir: Optional[str] = None) -> subprocess.Popen:
    """One serve replica process. Heartbeat/flight identity reuse the
    training child conventions (``DDL_PROCESS_ID`` names both files); no
    coordinator is exported — replicas are independent model copies, not
    ranks of one mesh. ``trace_dir`` arms per-request tracing in the
    child (``DDL_TRACE_DIR``) — set per spawn, never on the supervisor's
    own environ, so a traced serve run cannot leak tracing into later
    untraced children."""
    env = dict(os.environ)
    env[ENV_PROCESS_ID] = str(replica)
    env[ENV_NUM_PROCESSES] = str(num_replicas)
    env.pop(ENV_COORDINATOR, None)
    if trace_dir is not None:
        env[telemetry.ENV_TRACE_DIR] = trace_dir
    else:
        env.pop(telemetry.ENV_TRACE_DIR, None)
    # Serve replicas are outside the training membership: a stale elastic
    # epoch/identity inherited from a training launcher would namespace
    # their heartbeats away from the supervisor's staleness check.
    env.pop(health.ENV_ELASTIC_EPOCH, None)
    env.pop(health.ENV_ELASTIC_HOST, None)
    env.pop(health.ENV_ELASTIC_EVENT, None)
    env[faults.ENV_ATTEMPT] = str(attempt)
    if fault_plan:
        env[faults.ENV_PLAN] = fault_plan
    else:
        env.pop(faults.ENV_PLAN, None)
    if heartbeat_dir is not None:
        env[health.ENV_HEARTBEAT_DIR] = heartbeat_dir
        # A restarted replica must not inherit its predecessor's last
        # heartbeat: stale mtimes would mask a hang.
        try:
            os.remove(health.heartbeat_path(heartbeat_dir, replica))
        except OSError:
            pass
    command = [sys.executable, "-m",
               "distributeddeeplearning_tpu.serve.replica",
               "--workdir", workdir, "--replica", str(replica)]
    return subprocess.Popen(command, env=env)


def _dispatch_request(workdir: str, replica: int, attempt: int,
                      payload: dict) -> None:
    """Atomically drop one request file into a replica's inbox. The inbox
    is per (replica, attempt): a warm-restarted replica must not replay
    its predecessor's inbox — those victims were re-dispatched already."""
    inbox = os.path.join(workdir, "inbox", f"r{replica}.a{attempt}")
    os.makedirs(inbox, exist_ok=True)
    name = f"req-{payload['uid']:06d}-{payload.get('dispatch', 0)}.json"
    tmp = os.path.join(inbox, name + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, os.path.join(inbox, name))


class AutoscalePolicy:
    """Deterministic hysteresis over the supervisor's queue-depth gauge.

    The elastic controller's substrate applied to serving (ROADMAP 1d):
    instead of mesh re-formation, membership change means spawning or
    draining independent replicas. The policy is pure — ``decide`` sees
    only the gauge values the supervisor just observed into
    ``observability/metrics.py`` and its own streak counters — so unit
    tests can drive it with synthetic traffic and pin every transition.

    Scale-up: the backlog has exceeded ``up_backlog_per_replica`` open
    requests per live replica for ``up_sustain_polls`` consecutive polls
    (a burst shorter than the sustain window is absorbed, not scaled
    for). Scale-down: the queue has been empty for ``down_idle_polls``
    consecutive polls. Both directions respect [min_replicas,
    max_replicas]; a decision resets both streaks so scale events are
    spaced by at least one full sustain window.
    """

    def __init__(self, min_replicas: int, max_replicas: int, *,
                 up_backlog_per_replica: float = 2.0,
                 up_sustain_polls: int = 3,
                 down_idle_polls: int = 40):
        if min_replicas < 1:
            raise ValueError(f"min_replicas={min_replicas}: need >= 1")
        if max_replicas < min_replicas:
            raise ValueError(f"max_replicas={max_replicas} < "
                             f"min_replicas={min_replicas}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_backlog_per_replica = float(up_backlog_per_replica)
        self.up_sustain_polls = int(up_sustain_polls)
        self.down_idle_polls = int(down_idle_polls)
        self._up_streak = 0
        self._idle_streak = 0

    def decide(self, *, queue_depth: int, live_replicas: int) -> int:
        """+1 (scale up), -1 (scale down), or 0 — given the current open
        (dispatched or due, unclosed) request count and live replicas."""
        if queue_depth > self.up_backlog_per_replica * max(1, live_replicas):
            self._up_streak += 1
        else:
            self._up_streak = 0
        if queue_depth == 0:
            self._idle_streak += 1
        else:
            self._idle_streak = 0
        if (self._up_streak >= self.up_sustain_polls
                and live_replicas < self.max_replicas):
            self._up_streak = self._idle_streak = 0
            return 1
        if (self._idle_streak >= self.down_idle_polls
                and live_replicas > self.min_replicas):
            self._up_streak = self._idle_streak = 0
            return -1
        return 0


def run_serve(num_replicas: int, requests: Sequence[dict],
              serve_config: dict, *, workdir: str,
              heartbeat_dir: Optional[str] = None,
              heartbeat_timeout_s: float = 0.0,
              max_restarts: int = 1, max_request_retries: int = 3,
              child_fault_plans: Optional[dict] = None,
              flight_dir: Optional[str] = None,
              poll_interval_s: float = 0.05,
              timeout_s: float = 600.0,
              autoscale: Optional[AutoscalePolicy] = None,
              trace_dir: Optional[str] = None,
              clock: Callable[[], float] = time.monotonic) -> dict:
    """Supervise N serve-engine replicas over one request trace.

    The serving analogue of ``run_local`` + ``run_with_restarts``, with one
    structural difference: a training job fails whole (every rank computes
    the same update), but replicas are independent — one dying must NOT
    tear the others down. Instead its in-flight requests are re-dispatched
    to survivors with the token prefix the supervisor already received
    folded into the prompt, so the completed stream is token-identical to
    an uninterrupted run (greedy prefix-folding, the same path preemption
    resume uses). The dead replica is restarted warm (shared AOT
    executable cache via ``config.json``) under a per-replica restart
    budget, with ``DDL_RESTART_ATTEMPT`` bumped so attempt-scoped faults
    do not re-fire.

    ``requests``: dicts with ``prompt``/``max_new_tokens`` (+ optional
    ``tenant``/``arrival_s`` relative to the run start). Returns per-uid
    results plus the incident/restart accounting; the flight record gets
    the full chain (``serve_replica_lost`` -> ``serve_redispatch`` ->
    ``serve_replayed``) for ``tools/postmortem.py``.

    With ``autoscale`` (an :class:`AutoscalePolicy`), the supervisor
    observes its open-request backlog and shed count into
    ``observability/metrics.py`` gauges every poll and lets the policy
    drive the replica count: scale-up spawns a fresh replica that warms
    from the SHARED serve AOT executable cache (every replica reads the
    same ``config.json``, so the fingerprint matches and the new replica
    skips compilation); scale-down routes through the stop-sentinel drain
    gate, so a scaled-down replica still runs the shutdown leak check.

    With ``trace_dir``, every replica records per-request span trees
    (``serve/tracing.py``) into ``trace.p<rid>.json`` there, the
    supervisor records its dispatch/redispatch/replica-lost instants into
    its own per-process file, and after the drain everything is merged
    into ``trace_dir/trace.merged.json`` (``out["merged_trace"]``) — one
    Chrome trace where a re-dispatched request's spans are flow-linked
    across the replica processes it lived on.
    """
    if num_replicas < 1:
        raise ValueError(f"num_replicas={num_replicas}: need >= 1")
    if autoscale is not None:
        # Start inside the policy's band: the floor is the availability
        # promise, the ceiling the cost cap.
        num_replicas = min(max(num_replicas, autoscale.min_replicas),
                           autoscale.max_replicas)
    os.makedirs(workdir, exist_ok=True)
    if heartbeat_dir is not None:
        os.makedirs(heartbeat_dir, exist_ok=True)
    with open(os.path.join(workdir, "config.json"), "w",
              encoding="utf-8") as f:
        json.dump(dict(serve_config), f, indent=2, sort_keys=True)

    if flight_dir is not None:
        os.environ[flightlib.ENV_FLIGHT_DIR] = flight_dir
        os.environ.setdefault(flightlib.ENV_RUN_ID, flightlib.mint_run_id())
        flightlib.configure(flight_dir,
                            run_id=os.environ[flightlib.ENV_RUN_ID],
                            host="launcher")
    flight = flightlib.get()
    flight.record("serve_launch", num_replicas=num_replicas,
                  requests=len(requests), max_restarts=max_restarts)

    # Supervisor-side tracing: its OWN registry (never the module
    # singleton — a bench tracing an in-process engine in this same
    # process must not be clobbered), on a pid far above any replica id.
    sup_tele = None
    if trace_dir is not None:
        os.makedirs(trace_dir, exist_ok=True)
        sup_tele = telemetry.Telemetry(
            enabled=True, trace_dir=trace_dir, process_index=10_000,
            process_name="serve-supervisor")

    plans = dict(child_fault_plans or {})
    for plan in plans.values():
        faults.parse_plan(plan)  # fail fast on grammar errors

    reqs: dict[int, dict] = {}
    for i, d in enumerate(requests):
        uid = int(d.get("uid", i))
        reqs[uid] = {
            "tenant": d.get("tenant", "default"),
            "prompt": [int(t) for t in d["prompt"]],
            "max_new": int(d["max_new_tokens"]),
            "arrival_s": float(d.get("arrival_s", 0.0)),
            "tokens": [], "replica": None, "dispatched": False,
            "finished": False, "failed": None, "retries": 0,
            "dispatches": 0, "first_token_t": None,
        }

    reps: list[dict] = []
    for i in range(num_replicas):
        proc = _spawn_replica(i, num_replicas, workdir, attempt=0,
                              heartbeat_dir=heartbeat_dir,
                              fault_plan=plans.get(i),
                              trace_dir=trace_dir)
        reps.append({"proc": proc, "alive": True, "attempt": 0,
                     "restarts": 0, "ever_beat": False, "hung": False,
                     "last_step": 0, "offset": 0, "rc": None,
                     "drained": False, "draining": False})
        flight.record("spawn", child=i, pid=proc.pid, scope="serve")

    redispatched = 0
    total_restarts = 0
    scale_ups = 0
    scale_downs = 0
    gauges = metricslib.MetricsRegistry(
        run_id=os.environ.get(flightlib.ENV_RUN_ID, "")) \
        if autoscale is not None else None
    poll_n = 0
    stopping = False
    t0 = clock()

    def closed(st: dict) -> bool:
        return st["finished"] or st["failed"] is not None

    def drain_events(rid: int) -> None:
        rep = reps[rid]
        path = os.path.join(workdir, "events", f"r{rid}.jsonl")
        try:
            with open(path, "rb") as f:
                f.seek(rep["offset"])
                blob = f.read()
        except OSError:
            return
        cut = blob.rfind(b"\n")
        if cut < 0:
            return
        rep["offset"] += cut + 1
        for line in blob[:cut + 1].splitlines():
            try:
                e = json.loads(line)
            except ValueError:
                continue
            kind = e.get("ev")
            if "step" in e:
                rep["last_step"] = max(rep["last_step"], int(e["step"]))
            if kind == "token":
                st = reqs.get(int(e["uid"]))
                if st is not None and st["replica"] == rid \
                        and not closed(st):
                    if st["first_token_t"] is None:
                        st["first_token_t"] = clock()
                    st["tokens"].extend(int(t) for t in e["tokens"])
            elif kind == "finished":
                st = reqs.get(int(e["uid"]))
                if st is not None and st["replica"] == rid:
                    st["finished"] = True
                    if st["retries"]:
                        flight.record("serve_replayed",
                                      request=int(e["uid"]), replica=rid,
                                      tokens=len(st["tokens"]),
                                      retries=st["retries"],
                                      token_identical=True)
            elif kind == "failed":
                st = reqs.get(int(e["uid"]))
                if st is not None and st["replica"] == rid:
                    st["failed"] = e.get("reason", "unknown")
            elif kind == "drained":
                rep["drained"] = True

    def on_replica_death(rid: int, rc: int) -> None:
        nonlocal redispatched, total_restarts
        rep = reps[rid]
        rep["alive"], rep["rc"] = False, rc
        drain_events(rid)  # salvage everything the OS buffered
        if rc == 0 and rep["drained"]:
            return  # clean drain after the stop sentinel
        label = attribute_failure(heartbeat_dir, rid, hung=rep["hung"],
                                  ever_beat=rep["ever_beat"])
        victims = [uid for uid, st in reqs.items()
                   if st["replica"] == rid and st["dispatched"]
                   and not closed(st)]
        flight.record("child_exit", child=rid, rc=rc, attribution=label,
                      scope="serve")
        flight.record("serve_replica_lost", replica=rid, rc=rc,
                      step=rep["last_step"], attribution=label,
                      inflight=len(victims))
        if sup_tele is not None:
            sup_tele.instant("serve:replica_lost", replica=rid, rc=rc,
                             step=rep["last_step"], attribution=label,
                             inflight=len(victims))
        print(f"# launcher: serve replica {rid} lost at engine step "
              f"{rep['last_step']} (rc={rc}, {label}); "
              f"{len(victims)} in-flight request(s) to re-dispatch",
              file=sys.stderr, flush=True)
        for uid in victims:
            st = reqs[uid]
            st["replica"], st["dispatched"] = None, False
            if len(st["tokens"]) >= st["max_new"]:
                # Fully streamed; only the 'finished' line was lost.
                st["finished"] = True
                continue
            st["retries"] += 1
            if st["retries"] > max_request_retries:
                st["failed"] = "retries_exhausted"
                flight.record("serve_shed", request=uid,
                              reason="retries_exhausted", scope="serve")
            else:
                redispatched += 1
        if rep["restarts"] < max_restarts and not stopping:
            rep["restarts"] += 1
            rep["attempt"] += 1
            total_restarts += 1
            flight.record("restart", child=rid, attempt=rep["attempt"],
                          scope="serve")
            rep["proc"] = _spawn_replica(
                rid, num_replicas, workdir, attempt=rep["attempt"],
                heartbeat_dir=heartbeat_dir, fault_plan=plans.get(rid),
                trace_dir=trace_dir)
            rep["alive"], rep["hung"], rep["rc"] = True, False, None

    try:
        while True:
            now = clock()
            alive = [i for i, r in enumerate(reps)
                     if r["alive"] and not r["draining"]]
            # Dispatch due requests round-robin over live replicas; a
            # re-dispatched victim carries its received prefix.
            if alive:
                for uid in sorted(reqs):
                    st = reqs[uid]
                    if (st["dispatched"] or closed(st)
                            or now - t0 < st["arrival_s"]):
                        continue
                    rid = alive[st["dispatches"] % len(alive)]
                    rep = reps[rid]
                    payload = {"uid": uid, "tenant": st["tenant"],
                               "prompt": st["prompt"],
                               "max_new_tokens": st["max_new"],
                               "prefix": list(st["tokens"]),
                               "dispatch": st["dispatches"],
                               # Trace/flow id: the supervisor's GLOBAL
                               # uid, stable across re-dispatches, so
                               # every replica's spans for this request
                               # share one flow.
                               "trace": uid,
                               "redispatch": bool(st["retries"])}
                    _dispatch_request(workdir, rid, rep["attempt"], payload)
                    st["replica"], st["dispatched"] = rid, True
                    st["dispatches"] += 1
                    if st["retries"]:
                        flight.record("serve_redispatch", request=uid,
                                      to=rid, resumed_from=len(st["tokens"]),
                                      retries=st["retries"])
                        if sup_tele is not None:
                            sup_tele.instant("serve:redispatch",
                                             request=uid, to=rid,
                                             trace=uid,
                                             resumed_from=len(st["tokens"]),
                                             retries=st["retries"])
                    elif sup_tele is not None:
                        sup_tele.instant("serve:dispatch", request=uid,
                                         to=rid, trace=uid,
                                         dispatch=st["dispatches"] - 1)
            # Autoscaling: observe the gauges, then let the policy move
            # the replica count (elastic membership for independent
            # replicas — ROADMAP 1d).
            if autoscale is not None and not stopping:
                poll_n += 1
                backlog = sum(1 for st in reqs.values()
                              if not closed(st)
                              and now - t0 >= st["arrival_s"])
                shed = sum(1 for st in reqs.values()
                           if st["failed"] == "retries_exhausted")
                gauges.observe("serve_queue_depth", backlog, step=poll_n)
                gauges.observe("serve_shed_total", shed, step=poll_n)
                gauges.observe("serve_live_replicas", len(alive),
                               step=poll_n)
                move = autoscale.decide(queue_depth=backlog,
                                        live_replicas=len(alive)) \
                    if alive else 0
                if move > 0:
                    rid = len(reps)
                    proc = _spawn_replica(
                        rid, rid + 1, workdir, attempt=0,
                        heartbeat_dir=heartbeat_dir,
                        fault_plan=plans.get(rid),
                        trace_dir=trace_dir)
                    reps.append({"proc": proc, "alive": True,
                                 "attempt": 0, "restarts": 0,
                                 "ever_beat": False, "hung": False,
                                 "last_step": 0, "offset": 0, "rc": None,
                                 "drained": False, "draining": False})
                    scale_ups += 1
                    flight.record("spawn", child=rid, pid=proc.pid,
                                  scope="serve")
                    flight.record("serve_scale_up", replica=rid,
                                  queue_depth=backlog,
                                  live=len(alive) + 1, warm=True)
                    print(f"# launcher: serve autoscale up — replica "
                          f"{rid} spawned warm (queue depth {backlog} "
                          f"over {len(alive)} live)",
                          file=sys.stderr, flush=True)
                elif move < 0:
                    # Drain the newest idle replica (no open requests
                    # assigned) through the stop-sentinel gate.
                    idle = [i for i in reversed(alive)
                            if not any(st["replica"] == i
                                       and st["dispatched"]
                                       and not closed(st)
                                       for st in reqs.values())]
                    if idle:
                        rid = idle[0]
                        reps[rid]["draining"] = True
                        with open(os.path.join(workdir, f"stop.r{rid}"),
                                  "w", encoding="utf-8") as f:
                            f.write("drain\n")
                        scale_downs += 1
                        flight.record("serve_scale_down", replica=rid,
                                      live=len(alive) - 1)
                        print(f"# launcher: serve autoscale down — "
                              f"replica {rid} draining (idle "
                              f"{autoscale.down_idle_polls} polls)",
                              file=sys.stderr, flush=True)
            for rid in range(len(reps)):
                if reps[rid]["alive"]:
                    drain_events(rid)
            if heartbeat_dir is not None:
                for rid in range(len(reps)):
                    rep = reps[rid]
                    if rep["alive"] and not rep["ever_beat"]:
                        rep["ever_beat"] = os.path.exists(
                            health.heartbeat_path(heartbeat_dir, rid))
                if heartbeat_timeout_s > 0:
                    beat_set = {i for i, r in enumerate(reps)
                                if r["alive"] and r["ever_beat"]}
                    for pid, age in health.check_stale(
                            heartbeat_dir, len(reps),
                            heartbeat_timeout_s):
                        if pid in beat_set and not reps[pid]["hung"]:
                            reps[pid]["hung"] = True
                            flight.record("heartbeat_stale", child=pid,
                                          age_s=round(age, 3), scope="serve")
                            reps[pid]["proc"].kill()
            for rid in range(len(reps)):
                rep = reps[rid]
                if rep["alive"]:
                    rc = rep["proc"].poll()
                    if rc is not None:
                        on_replica_death(rid, rc)
            if all(closed(st) for st in reqs.values()):
                if not stopping:
                    stopping = True
                    for rid in range(len(reps)):
                        with open(os.path.join(workdir, f"stop.r{rid}"),
                                  "w", encoding="utf-8") as f:
                            f.write("drain\n")
                if not any(r["alive"] for r in reps):
                    break
            if now - t0 > timeout_s:
                raise RuntimeError(
                    f"serve supervision timed out after {timeout_s:.0f}s: "
                    f"{sum(1 for s in reqs.values() if not closed(s))} "
                    f"request(s) open, replicas alive="
                    f"{[i for i, r in enumerate(reps) if r['alive']]}")
            time.sleep(poll_interval_s)
    finally:
        for rep in reps:
            if rep["alive"]:
                rep["proc"].kill()
                rep["proc"].wait()

    # The drain gate: a replica that reaches its stop sentinel runs the
    # engine's shutdown leak check and exits 0 only if page accounting
    # balanced — so "every replica drained AND exited 0" IS the leak
    # check. A replica that died in shutdown (leak found) has rc != 0 and
    # no drained event; both must fail this.
    leak_check_ok = bool(reps) and all(
        r["rc"] == 0 and r["drained"] for r in reps)
    window_s = clock() - t0
    flight.record("serve_drained", window_s=round(window_s, 3),
                  redispatched=redispatched, restarts=total_restarts,
                  leak_check_ok=leak_check_ok, scale_ups=scale_ups,
                  scale_downs=scale_downs)
    results = {}
    for uid, st in reqs.items():
        ttft = None
        if st["first_token_t"] is not None:
            ttft = max(0.0, st["first_token_t"] - (t0 + st["arrival_s"]))
        results[uid] = {"tokens": list(st["tokens"]),
                        "finished": st["finished"],
                        "failed": st["failed"],
                        "retries": st["retries"], "ttft_s": ttft}
    out = {"results": results, "redispatched": redispatched,
           "restarts": total_restarts, "window_s": window_s,
           "leak_check_ok": leak_check_ok,
           "replica_rcs": {i: r["rc"] for i, r in enumerate(reps)}}
    if trace_dir is not None:
        if sup_tele is not None:
            sup_tele.export()
        merged, merge_errors = telemetry.merge_trace_dir(trace_dir)
        out["trace_dir"] = trace_dir
        out["merged_trace"] = merged
        if merge_errors:
            # Typically the SIGKILL'd replica's last file — report what
            # was salvaged rather than pretending the merge was whole.
            out["trace_merge_errors"] = merge_errors
    if autoscale is not None:
        out["autoscale"] = {"scale_ups": scale_ups,
                            "scale_downs": scale_downs,
                            "peak_replicas": len(reps),
                            "min_replicas": autoscale.min_replicas,
                            "max_replicas": autoscale.max_replicas,
                            "gauges": gauges.aggregate()["metrics"]}
    return out


def _main_serve(args, p) -> int:
    """CLI shim for serve mode: files in, run_serve, summary out."""
    import tempfile

    with open(args.serve, encoding="utf-8") as f:
        requests = json.load(f)
    if not isinstance(requests, list) or not requests:
        p.error(f"--serve {args.serve}: expected a non-empty JSON list")
    with open(args.serve_config, encoding="utf-8") as f:
        serve_config = json.load(f)

    plans: dict[int, str] = {}
    for item in args.child_fault_plan:
        idx_s, sep, plan = item.partition(":")
        if not sep or not idx_s.isdigit():
            p.error(f"--child-fault-plan expects IDX:PLAN, got {item!r}")
        plans[int(idx_s)] = plan

    workdir = args.serve_dir or tempfile.mkdtemp(prefix="ddl-serve-")
    # Heartbeats are always on in serve mode: attribution (hung vs crash
    # vs host_lost) needs ever_beat even when the staleness watchdog is
    # disabled.
    heartbeat_dir = args.heartbeat_dir or tempfile.mkdtemp(
        prefix="ddl-serve-hb-")

    autoscale = None
    if args.serve_autoscale:
        lo_s, sep, hi_s = args.serve_autoscale.partition(":")
        if not sep or not lo_s.isdigit() or not hi_s.isdigit():
            p.error(f"--serve-autoscale expects MIN:MAX, got "
                    f"{args.serve_autoscale!r}")
        try:
            autoscale = AutoscalePolicy(int(lo_s), int(hi_s))
        except ValueError as e:
            p.error(f"--serve-autoscale: {e}")

    out = run_serve(args.num_processes or 1, requests, serve_config,
                    workdir=workdir, heartbeat_dir=heartbeat_dir,
                    heartbeat_timeout_s=args.heartbeat_timeout,
                    max_restarts=args.max_restarts,
                    child_fault_plans=plans, flight_dir=args.flight_dir,
                    autoscale=autoscale,
                    trace_dir=args.serve_trace_dir)
    if args.serve_out:
        with open(args.serve_out, "w", encoding="utf-8") as f:
            json.dump(out, f, indent=2, sort_keys=True, default=str)
    done = sum(1 for r in out["results"].values() if r["finished"])
    print(f"# launcher: serve drained — {done}/{len(out['results'])} "
          f"finished, {out['redispatched']} re-dispatched, "
          f"{out['restarts']} restart(s), leak check "
          f"{'ok' if out['leak_check_ok'] else 'FAILED'} "
          f"({out['window_s']:.1f}s)", flush=True)
    ok = out["leak_check_ok"] and all(
        r["finished"] for r in out["results"].values())
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--num-processes", type=int, default=None,
                   help="spawn N local processes (multi-host simulation / "
                        "single-host multi-process)")
    p.add_argument("--hostfile", default=None,
                   help="one host per line; first is coordinator")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's line number in --hostfile")
    p.add_argument("--port", type=int, default=9531,
                   help="coordinator port")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="relaunch the whole job up to N times after a "
                        "failure (resumes from the latest checkpoint); when "
                        "the command names a --checkpoint-dir, N is a budget "
                        "*between checkpoints* — progress refills it, a "
                        "crash loop exhausts it")
    p.add_argument("--backoff", type=float, default=3.0,
                   help="base restart delay in seconds (doubles per "
                        "consecutive failure, deterministic jitter)")
    p.add_argument("--backoff-cap", type=float, default=60.0,
                   help="maximum restart delay in seconds")
    p.add_argument("--child-fault-plan", action="append", default=[],
                   metavar="IDX:PLAN",
                   help="inject a fault plan (robustness/faults.py grammar) "
                        "into one local child, e.g. 0:sigkill@20 "
                        "(repeatable; local --num-processes jobs only)")
    p.add_argument("--heartbeat-timeout", type=float, default=0.0,
                   help="kill a child whose heartbeat file "
                        "(observability/health.py; children beat on their "
                        "log cadence) goes stale for this many seconds — a "
                        "hung child then feeds the normal fail-whole + "
                        "restart machinery. 0 disables. Size it well above "
                        "the training log interval")
    p.add_argument("--heartbeat-dir", default=None,
                   help="heartbeat file directory (default: a fresh temp "
                        "dir; local --num-processes jobs only)")
    p.add_argument("--elastic", action="store_true",
                   help="automatic mesh re-formation on host loss/gain: a "
                        "child attributed as a lost host (its heartbeat "
                        "vanished with it, or the hang watchdog killed it) "
                        "shrinks the plan and the job relaunches at the "
                        "surviving --dp degree from the latest checkpoint, "
                        "without sleeping the backoff or burning the "
                        "restart budget; a rejoin marker in the heartbeat "
                        "dir grows it back. Requires a local "
                        "--num-processes job whose command names --dp and "
                        "--checkpoint-dir; the global batch is unchanged, "
                        "so trajectories stay bitwise "
                        "(docs/fault_tolerance.md)")
    p.add_argument("--min-hosts", type=int, default=1,
                   help="with --elastic, give up (generic failure path) "
                        "instead of re-forming below this many hosts")
    p.add_argument("--elastic-geometry", action="append", default=[],
                   metavar="HOSTS:dp=D[,pp=P][,sharding=S]",
                   help="with --elastic, the full mesh shape to re-form at "
                        "when HOSTS hosts are live (repeatable), e.g. "
                        "1:dp=1,pp=4,sharding=none — re-formation then "
                        "crosses the pipeline-degree and ZeRO-stage axes "
                        "through the canonical checkpoint layout. dp*pp "
                        "must equal HOSTS x devices-per-host. Host counts "
                        "not listed (other than the full pod) shrink to "
                        "the largest listed count via topology-aware "
                        "survivor selection (docs/fault_tolerance.md)")
    p.add_argument("--serve-autoscale", default=None, metavar="MIN:MAX",
                   help="with --serve, autoscale the replica count between "
                        "MIN and MAX from the supervisor's queue-depth "
                        "gauge: sustained backlog per live replica scales "
                        "up (warm via the shared serve AOT fingerprint), "
                        "sustained idleness scales down (docs/serving.md)")
    p.add_argument("--flight-dir", default=None,
                   help="flight recorder directory (observability/"
                        "flight.py): the launcher mints one run id for the "
                        "whole job, exports it to every child of every "
                        "restart attempt, and appends its own spawn/"
                        "attribution/restart events — the crash-surviving "
                        "record tools/postmortem.py reads. Default: the "
                        "training command's own --flight-dir, else off")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent compile cache shared by every child and "
                        "every restart attempt (docs/compile_cache.md); "
                        "default $DDL_COMPILE_CACHE or the repo-local "
                        ".cache/jax_compile; 'off' disables")
    p.add_argument("--serve", default=None, metavar="REQUESTS.json",
                   help="serve mode: supervise --num-processes engine "
                        "replicas over this request trace (list of "
                        "{prompt, max_new_tokens[, tenant, arrival_s]}) "
                        "instead of launching a training command. Replicas "
                        "lost mid-decode have their in-flight requests "
                        "re-dispatched to survivors token-identically; "
                        "--max-restarts / --heartbeat-timeout / "
                        "--child-fault-plan / --flight-dir apply per "
                        "replica (docs/serving.md)")
    p.add_argument("--serve-config", default=None, metavar="CONFIG.json",
                   help="ServeConfig fields for serve mode (required with "
                        "--serve)")
    p.add_argument("--serve-dir", default=None,
                   help="serve-mode work directory for the inbox/event "
                        "files (default: a fresh temp dir)")
    p.add_argument("--serve-out", default=None,
                   help="write the serve-mode result summary (per-request "
                        "tokens, re-dispatch/restart accounting, leak "
                        "check) to this JSON file")
    p.add_argument("--serve-trace-dir", default=None,
                   help="with --serve, record per-request span trees in "
                        "every replica (serve/tracing.py) and merge the "
                        "per-replica files into "
                        "TRACE_DIR/trace.merged.json after the drain — "
                        "one Chrome trace, flow-linked across replicas")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command, after `--`")
    args = p.parse_args(argv)

    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if args.serve is not None:
        if command:
            p.error("--serve replaces the training command; drop the "
                    "trailing command")
        if args.hostfile or args.elastic:
            p.error("--serve only supports local (--num-processes) jobs")
        if args.serve_config is None:
            p.error("--serve requires --serve-config")
        return _main_serve(args, p)
    if args.serve_autoscale:
        p.error("--serve-autoscale requires --serve")
    if args.serve_trace_dir:
        p.error("--serve-trace-dir requires --serve")
    if not command:
        p.error("no training command given (pass it after `--`)")

    # One compile cache for the whole job: resolve launcher flag > training
    # command's own --compile-cache-dir > env > default, then export it so
    # EVERY child of EVERY restart attempt lands on the same cache — a
    # restarted attempt then loads the previous attempt's executables
    # instead of recompiling (perf/compile_cache.py; jax-free here).
    from distributeddeeplearning_tpu.perf import compile_cache
    cache_flag = (args.compile_cache_dir
                  if args.compile_cache_dir is not None
                  else _flag_from_command(command, "--compile-cache-dir"))
    cache_dir = compile_cache.resolve_dir(cache_flag)
    if cache_dir is not None:
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            cache_dir = None
    compile_cache.export_env(cache_dir)

    if args.hostfile:
        if args.process_id is None:
            p.error("--hostfile requires --process-id")
        if args.child_fault_plan:
            p.error("--child-fault-plan only supports local "
                    "(--num-processes) jobs")
        if args.heartbeat_timeout:
            # The watchdog kills by local child index; a hostfile job's one
            # local child maps to a remote rank set this launcher cannot
            # attribute — keep the semantics local-only, like restarts.
            p.error("--heartbeat-timeout only supports local "
                    "(--num-processes) jobs")
        if args.max_restarts:
            # A per-host restart decision is wrong for a whole-job semantic:
            # hosts whose rank exited 0 would never relaunch, leaving the
            # restarted ranks hung in rendezvous. Multi-host restart needs a
            # whole-job resubmit (every host's launcher rerun), like the
            # reference's Batch-AI resubmission.
            p.error("--max-restarts only supports local (--num-processes) "
                    "jobs; for --hostfile, wrap the launcher in a "
                    "whole-job resubmit loop on every host")
        if args.elastic:
            # Elastic re-formation re-plans the LOCAL process set; a
            # hostfile job's membership lives across machines where this
            # launcher only owns one child.
            p.error("--elastic only supports local (--num-processes) jobs")
        return run_from_hostfile(args.hostfile, args.process_id, command,
                                 port=args.port)
    n = args.num_processes or 1

    child_env: dict[int, dict[str, str]] = {}
    for item in args.child_fault_plan:
        idx_s, sep, plan = item.partition(":")
        if not sep or not idx_s.isdigit():
            p.error(f"--child-fault-plan expects IDX:PLAN, got {item!r}")
        faults.parse_plan(plan)  # fail fast on grammar errors
        child_env.setdefault(int(idx_s), {})[faults.ENV_PLAN] = plan

    progress_fn = None
    ckpt_dir = _checkpoint_dir_from_command(command)
    if ckpt_dir is not None:
        progress_fn = lambda: _latest_ckpt_step(ckpt_dir)  # noqa: E731

    heartbeat_dir = None
    if args.heartbeat_timeout > 0 or args.elastic:
        import tempfile
        heartbeat_dir = args.heartbeat_dir or tempfile.mkdtemp(
            prefix="ddl_heartbeat_")
        os.makedirs(heartbeat_dir, exist_ok=True)

    # Flight recorder (observability/flight.py): ONE run id for the whole
    # job, minted here and exported so every child of every restart attempt
    # appends to the same run's record under the shared identity scheme.
    # The launcher writes its own file (child exits, attribution verdicts,
    # restarts, re-formations) — the events that survive even when a child
    # died too fast to record anything.
    flight_dir = (args.flight_dir if args.flight_dir is not None
                  else _flag_from_command(command, "--flight-dir"))
    if flight_dir is not None:
        os.environ[flightlib.ENV_FLIGHT_DIR] = flight_dir
        os.environ.setdefault(flightlib.ENV_RUN_ID, flightlib.mint_run_id())
        flight = flightlib.configure(
            flight_dir, run_id=os.environ[flightlib.ENV_RUN_ID],
            host="launcher")
        flight.record("launch", num_processes=n,
                      max_restarts=args.max_restarts,
                      elastic=bool(args.elastic),
                      command=" ".join(command))
    else:
        flight = flightlib.get()

    # When the training command traces (--trace-dir), the launcher records
    # its restart/backoff/stale-heartbeat instants too and merges them into
    # process 0's trace AFTER the job ends — one Chrome-trace file then
    # shows the whole chaos story (step phases + faults + restarts).
    # Timestamps are CLOCK_MONOTONIC, shared across local processes.
    trace_dir = _flag_from_command(command, "--trace-dir")
    tele = None
    if trace_dir is not None:
        tele = telemetry.Telemetry(enabled=True, process_index=os.getpid(),
                                   process_name="launcher")

    elastic_ctl = None
    if args.elastic:
        dp_s = _flag_from_command(command, "--dp")
        if dp_s is None or not dp_s.isdigit():
            p.error("--elastic requires the training command to name an "
                    "explicit integer --dp (the degree the controller "
                    "re-plans)")
        if ckpt_dir is None:
            p.error("--elastic requires the training command to name "
                    "--checkpoint-dir (re-formation resumes from the "
                    "latest checkpoint)")
        fsdp_s = _flag_from_command(command, "--fsdp")
        if fsdp_s not in (None, "1"):
            # Shrinking fsdp re-shards parameters mid-plan; the converter
            # handles the CHECKPOINT side bitwise, but the per-host device
            # arithmetic here only re-plans the data axis.
            p.error("--elastic re-plans the --dp axis only; run with "
                    "--fsdp 1 (or drop --fsdp)")
        base_dp = int(dp_s)
        if base_dp % n:
            p.error(f"--elastic: --dp {base_dp} must divide evenly over "
                    f"--num-processes {n}")
        geometry = _parse_elastic_geometry(
            args.elastic_geometry, p, num_hosts=n, base_dp=base_dp,
            base_pp=_flag_from_command(command, "--pp"))
        # A stale rejoin/drain marker or reform barrier from a previous job
        # must not trigger a phantom re-formation on the first failure of
        # this one.
        health.consume_rejoin(heartbeat_dir)
        health.consume_drains(heartbeat_dir)
        health.clear_reform(heartbeat_dir)
        elastic_ctl = ElasticController(n, heartbeat_dir, base_dp=base_dp,
                                        min_hosts=args.min_hosts, tele=tele,
                                        geometry=geometry)
    elif args.elastic_geometry:
        p.error("--elastic-geometry requires --elastic")

    if elastic_ctl is not None:
        run_once = lambda: run_local(  # noqa: E731
            elastic_ctl.num_processes, elastic_ctl.command(command),
            port=args.port, child_env=elastic_ctl.child_env(child_env),
            heartbeat_dir=heartbeat_dir,
            heartbeat_timeout_s=args.heartbeat_timeout,
            heartbeat_epoch=elastic_ctl.epoch,
            tele=tele, elastic=elastic_ctl)
    else:
        run_once = lambda: run_local(  # noqa: E731
            n, command, port=args.port, child_env=child_env,
            heartbeat_dir=heartbeat_dir,
            heartbeat_timeout_s=args.heartbeat_timeout, tele=tele)

    rc = run_with_restarts(
        run_once, args.max_restarts, backoff_s=args.backoff,
        backoff_cap_s=args.backoff_cap, progress_fn=progress_fn, tele=tele,
        elastic=elastic_ctl)
    if elastic_ctl is not None and elastic_ctl.events:
        for ev in elastic_ctl.events:
            print(f"# launcher: elastic event: {ev['trigger']} degree "
                  f"{ev['degree_before']} -> {ev['degree_after']}",
                  file=sys.stderr, flush=True)
        print(f"# launcher: elastic: {len(elastic_ctl.events)} "
              f"re-formation(s), final degree {elastic_ctl.degree} "
              f"({elastic_ctl.num_processes}/{elastic_ctl.max_hosts} hosts)",
              file=sys.stderr, flush=True)
    if tele is not None:
        tele.export(telemetry.trace_path(trace_dir, 0))
    flight.record("job_end", rc=rc)
    flight.close()
    return rc


def _parse_elastic_geometry(items: Sequence[str], p, *, num_hosts: int,
                            base_dp: int, base_pp: Optional[str]
                            ) -> dict[int, dict]:
    """Parse repeated ``--elastic-geometry HOSTS:dp=D[,pp=P][,sharding=S]``
    entries into the controller's geometry table, validating each shape
    against the pod's device budget (dp*pp == hosts x devices-per-host)."""
    pp = int(base_pp) if base_pp and base_pp.isdigit() else 1
    if (base_dp * pp) % num_hosts:
        p.error(f"--elastic-geometry: base mesh dp={base_dp} pp={pp} does "
                f"not fill {num_hosts} host(s) evenly")
    devices_per_host = (base_dp * pp) // num_hosts
    geometry: dict[int, dict] = {}
    for item in items:
        hosts_s, sep, spec = item.partition(":")
        if not sep or not hosts_s.isdigit() or int(hosts_s) < 1:
            p.error(f"--elastic-geometry expects HOSTS:dp=D[,pp=P]"
                    f"[,sharding=S], got {item!r}")
        hosts = int(hosts_s)
        if hosts > num_hosts:
            p.error(f"--elastic-geometry {item!r}: {hosts} hosts exceeds "
                    f"--num-processes {num_hosts}")
        entry: dict = {}
        for kv in spec.split(","):
            key, sep2, value = kv.partition("=")
            if key == "dp" and sep2 and value.isdigit():
                entry["dp"] = int(value)
            elif key == "pp" and sep2 and value.isdigit():
                entry["pp"] = int(value)
            elif key == "sharding" and sep2 and value in (
                    "none", "zero1", "zero2", "zero3"):
                entry["sharding"] = value
            else:
                p.error(f"--elastic-geometry: bad field {kv!r} in {item!r}")
        if "dp" not in entry:
            p.error(f"--elastic-geometry {item!r}: dp= is required")
        shape = entry["dp"] * entry.get("pp", 1)
        if shape != devices_per_host * hosts:
            p.error(f"--elastic-geometry {item!r}: dp x pp = {shape} does "
                    f"not fill {hosts} host(s) x {devices_per_host} "
                    f"device(s)")
        geometry[hosts] = entry
    return geometry


def _flag_from_command(command: Sequence[str], flag: str) -> Optional[str]:
    """The value of ``flag`` in the training command, if present."""
    for i, tok in enumerate(command):
        if tok == flag and i + 1 < len(command):
            return command[i + 1]
        if tok.startswith(flag + "="):
            return tok.split("=", 1)[1]
    return None


def _with_flag_value(command: Sequence[str], flag: str,
                     value: str) -> list[str]:
    """The command with ``flag`` set to ``value`` (rewritten in place for
    both ``--flag V`` and ``--flag=V`` spellings; appended if absent) —
    how the elastic controller re-plans ``--dp`` at the surviving degree."""
    out = list(command)
    for i, tok in enumerate(out):
        if tok == flag and i + 1 < len(out):
            out[i + 1] = value
            return out
        if tok.startswith(flag + "="):
            out[i] = f"{flag}={value}"
            return out
    out.extend([flag, value])
    return out


def _checkpoint_dir_from_command(command: Sequence[str]) -> Optional[str]:
    """The training command's --checkpoint-dir, if present — lets the
    restart budget observe progress (new checkpoint step => refill)."""
    return _flag_from_command(command, "--checkpoint-dir")


if __name__ == "__main__":
    sys.exit(main())
