"""Pod-slice launcher — the TPU-native replacement for mpirun / Batch-AI.

The reference launched N ranks with ``mpirun`` under a Batch-AI job and let
MPI handle rendezvous (SURVEY.md §2 #9-#10, §3.1). On TPU the moral
equivalents are:

- **rendezvous**: ``jax.distributed.initialize(coordinator, num_processes,
  process_id)`` — replaces ``MPI_Init``; XLA then sees the global device set.
- **process placement**: one Python process per TPU host. On Cloud TPU pod
  slices the TPU runtime supplies topology env vars and
  ``jax.distributed.initialize()`` needs no arguments; everywhere else (and
  for local multi-process development on CPU) this module wires the
  coordinator explicitly through ``DDL_*`` env vars.
- **failure detection** (SURVEY.md §5.3): the reference's mpirun died whole
  when any rank died. ``monitor`` reproduces that for the processes this
  launcher owns: first local child to exit nonzero triggers terminate-all
  and a nonzero launcher exit, so a wrapper can restart the job from the
  last checkpoint (fail-whole + checkpoint-resume semantics). Across hosts
  (``--hostfile``), each host's launcher only sees its own child; a *remote*
  rank's death reaches the survivors through jax.distributed's coordinator
  heartbeat, which tears down their processes — the local launcher then
  reports that nonzero exit. Cross-host detection latency is therefore the
  heartbeat timeout, not this monitor's poll interval.

Usage (local dev, 2 simulated hosts on CPU):
    python launch.py --num-processes 2 -- python train.py --backend cpu ...

Usage (TPU pod slice, run on every host, e.g. via gcloud ssh --worker=all):
    python launch.py -- python train.py --backend tpu ...
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Optional, Sequence

ENV_COORDINATOR = "DDL_COORDINATOR"
ENV_NUM_PROCESSES = "DDL_NUM_PROCESSES"
ENV_PROCESS_ID = "DDL_PROCESS_ID"


@dataclasses.dataclass(frozen=True)
class ProcessSpec:
    """One training process in the job (≈ one MPI rank, one TPU host)."""

    process_id: int
    num_processes: int
    coordinator: str  # "host:port"

    def env(self) -> dict[str, str]:
        return {
            ENV_COORDINATOR: self.coordinator,
            ENV_NUM_PROCESSES: str(self.num_processes),
            ENV_PROCESS_ID: str(self.process_id),
        }


def plan_local(num_processes: int, *, port: int = 9531,
               coordinator_host: str = "127.0.0.1") -> list[ProcessSpec]:
    """Specs for N processes on this machine (multi-host simulation)."""
    coord = f"{coordinator_host}:{port}"
    return [ProcessSpec(i, num_processes, coord) for i in range(num_processes)]


def plan_from_hostfile(path: str, *, port: int = 9531) -> list[ProcessSpec]:
    """Specs from a one-host-per-line file (first host is coordinator) —
    the launcher-side analogue of an MPI hostfile. Each host runs the
    launcher with ``--process-id`` matching its line number."""
    with open(path) as f:
        hosts = [ln.strip() for ln in f if ln.strip()
                 and not ln.lstrip().startswith("#")]
    if not hosts:
        raise ValueError(f"hostfile {path!r} lists no hosts")
    coord = f"{hosts[0]}:{port}"
    return [ProcessSpec(i, len(hosts), coord) for i in range(len(hosts))]


def maybe_initialize_distributed() -> Optional[int]:
    """Called by train.py at startup. Joins the job if one is configured.

    Returns the process id when distributed was initialized, else None.
    Resolution order:
    1. ``DDL_*`` env vars (set by this launcher) → explicit initialize;
    2. Cloud TPU pod-slice env (multi-host libtpu topology) → argless
       initialize, deferring to the TPU runtime's own metadata;
    3. otherwise single-process: do nothing.
    """
    import jax

    if os.environ.get(ENV_COORDINATOR):
        spec = ProcessSpec(
            process_id=int(os.environ[ENV_PROCESS_ID]),
            num_processes=int(os.environ[ENV_NUM_PROCESSES]),
            coordinator=os.environ[ENV_COORDINATOR])
        jax.distributed.initialize(
            coordinator_address=spec.coordinator,
            num_processes=spec.num_processes,
            process_id=spec.process_id)
        return spec.process_id
    # Cloud TPU pod slice: the runtime's own topology env lists >1 worker
    # host; defer entirely to it. (A 1-host listing — also what this dev
    # image sets — is single-process and needs no rendezvous.)
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len(workers.split(",")) > 1:
        jax.distributed.initialize()
        return jax.process_index()
    return None


# ---------------------------------------------------------------------------
# Child spawn + monitoring (fail-whole semantics)
# ---------------------------------------------------------------------------

def spawn(spec: ProcessSpec, command: Sequence[str], *,
          extra_env: Optional[dict[str, str]] = None) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(spec.env())
    env.update(extra_env or {})
    return subprocess.Popen(list(command), env=env)


def monitor(children: Sequence[subprocess.Popen], *,
            poll_interval_s: float = 0.2,
            grace_s: float = 10.0) -> int:
    """Wait for all children; kill the survivors as soon as one fails.

    Returns 0 iff every child exited 0 — the contract a restart wrapper
    checks before deciding to relaunch from the last checkpoint.
    """
    procs = list(children)
    try:
        while True:
            codes = [p.poll() for p in procs]
            failed = [c for c in codes if c not in (None, 0)]
            if failed:
                _terminate_all(procs, grace_s)
                return int(failed[0]) or 1
            if all(c == 0 for c in codes):
                return 0
            time.sleep(poll_interval_s)
    except KeyboardInterrupt:
        _terminate_all(procs, grace_s)
        return 130


def _terminate_all(procs: Sequence[subprocess.Popen], grace_s: float) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()


def run_local(num_processes: int, command: Sequence[str], *,
              port: int = 9531) -> int:
    """Spawn + monitor N local processes (the `mpirun -np N` replacement)."""
    specs = plan_local(num_processes, port=port)
    children = [spawn(s, command) for s in specs]
    return monitor(children)


def run_with_restarts(run_once, max_restarts: int, *,
                      backoff_s: float = 3.0) -> int:
    """Fail-whole + auto-relaunch: the in-launcher restart wrapper.

    The reference's failure story was "mpirun dies whole, Batch AI resubmits
    the job" (SURVEY.md §5.3); ``run_once`` is one whole-job attempt, and a
    nonzero exit relaunches it up to ``max_restarts`` times. Paired with
    checkpoint-resume (train/checkpoint.py restores latest and the data
    stream repositions), each relaunch continues from the last saved step.
    Interrupts (rc 130) are the operator stopping the job — never retried.
    """
    attempt = 0
    while True:
        rc = run_once()
        if rc == 0 or rc == 130 or attempt >= max_restarts:
            return rc
        attempt += 1
        print(f"# launcher: job failed (rc={rc}); restart "
              f"{attempt}/{max_restarts} in {backoff_s:.0f}s "
              f"(resumes from the latest checkpoint)",
              file=sys.stderr, flush=True)
        time.sleep(backoff_s)


def run_from_hostfile(path: str, process_id: int, command: Sequence[str], *,
                      port: int = 9531) -> int:
    """Run this host's single process of a hostfile-defined job."""
    specs = plan_from_hostfile(path, port=port)
    if not 0 <= process_id < len(specs):
        raise ValueError(
            f"process_id {process_id} out of range for {len(specs)} hosts")
    child = spawn(specs[process_id], command)
    return monitor([child])


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--num-processes", type=int, default=None,
                   help="spawn N local processes (multi-host simulation / "
                        "single-host multi-process)")
    p.add_argument("--hostfile", default=None,
                   help="one host per line; first is coordinator")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's line number in --hostfile")
    p.add_argument("--port", type=int, default=9531,
                   help="coordinator port")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="relaunch the whole job up to N times after a "
                        "failure (resumes from the latest checkpoint)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command, after `--`")
    args = p.parse_args(argv)

    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        p.error("no training command given (pass it after `--`)")

    if args.hostfile:
        if args.process_id is None:
            p.error("--hostfile requires --process-id")
        if args.max_restarts:
            # A per-host restart decision is wrong for a whole-job semantic:
            # hosts whose rank exited 0 would never relaunch, leaving the
            # restarted ranks hung in rendezvous. Multi-host restart needs a
            # whole-job resubmit (every host's launcher rerun), like the
            # reference's Batch-AI resubmission.
            p.error("--max-restarts only supports local (--num-processes) "
                    "jobs; for --hostfile, wrap the launcher in a "
                    "whole-job resubmit loop on every host")
        return run_from_hostfile(args.hostfile, args.process_id, command,
                                 port=args.port)
    n = args.num_processes or 1
    return run_with_restarts(
        lambda: run_local(n, command, port=args.port), args.max_restarts)


if __name__ == "__main__":
    sys.exit(main())
