"""Always-on phase telemetry: spans/counters/gauges -> Chrome-trace JSON.

Horovod answered "where does a step's time go?" with HOROVOD_TIMELINE — a
Chrome-trace file of per-tensor collective phases (PAPERS.md: Horovod,
arXiv:1802.05799). This module is that layer for the whole framework: the
train loop records per-step *phase spans* (data_wait / dispatch /
fetch_barrier / checkpoint_save / eval), the collective layers record
per-bucket spans, and the fault/restart machinery records instant events,
all into one bounded ring buffer of monotonic-clock events that exports as
Chrome-trace JSON (``chrome://tracing``, Perfetto, or TensorBoard's trace
viewer load it directly).

Design constraints, in order:

1. **Cheap enough to leave on.** Events are (name, int-microseconds, small
   dict) tuples appended to a ``collections.deque(maxlen=...)`` under a
   lock — no device fetches, no I/O until :meth:`Telemetry.export`. The
   *disabled* path is a true no-op: ``span()`` returns a shared do-nothing
   context manager (no allocation) and every record method returns after
   one attribute check, so an uninstrumented run pays a few nanoseconds
   per call site (bounded by a tier-1 test and the gated chip_window A/B).
2. **Importable everywhere.** Pure stdlib: the launcher (which must never
   import jax — children own the accelerator) and robustness/faults.py
   record through the same API as the train loop.
3. **Mergeable.** ``export`` folds its events into any trace file already
   at the destination path, so the attempts of a restart-recovered chaos
   run and the launcher's own restart/backoff instants accumulate into ONE
   valid Chrome-trace JSON. All timestamps are CLOCK_MONOTONIC (shared
   across processes on one host), so merged events stay ordered.

The module-level singleton (:func:`get` / :func:`configure`) is what the
instrumentation sites use; tests construct :class:`Telemetry` directly.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from collections import deque
from typing import Any, Optional

try:  # POSIX advisory locking for multi-process export merges
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX fallback
    fcntl = None  # type: ignore[assignment]

DEFAULT_MAX_EVENTS = 200_000

#: Child processes (serve replicas) inherit their trace destination from
#: the supervisor through this env var — the serve counterpart of the
#: launcher's coordinator env plumbing.
ENV_TRACE_DIR = "DDL_TRACE_DIR"


def now_s() -> float:
    """Monotonic seconds — the clock every span endpoint must come from."""
    return time.monotonic()


def trace_path(trace_dir: str, process_index: int) -> str:
    """Canonical per-process trace file: one file per training process;
    the launcher merges its own events into process 0's file."""
    return os.path.join(trace_dir, f"trace.p{process_index}.json")


class _NullSpan:
    """Shared no-op span: the entire disabled/off-window code path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tele", "_name", "_args", "_t0")

    def __init__(self, tele: "Telemetry", name: str, args: dict):
        self._tele, self._name, self._args = tele, name, args

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        self._tele._emit({
            "name": self._name, "ph": "X", "ts": self._t0 // 1000,
            "dur": max((time.monotonic_ns() - self._t0) // 1000, 0),
            "pid": self._tele.process_index,
            "tid": threading.get_ident() & 0xFFFF,
            "args": self._args})
        return False


class Telemetry:
    """Thread-safe span/counter/gauge registry over a bounded ring buffer.

    ``trace_steps=(lo, hi)`` restricts *step-tagged* events to the
    half-open window [lo, hi); events with no step (bucket trace spans,
    fault/restart instants) are always kept. ``max_events`` bounds memory:
    the deque drops the oldest events, so a long run's export holds the
    most recent window — the part a post-mortem wants.
    """

    def __init__(self, enabled: bool = False,
                 trace_dir: Optional[str] = None,
                 trace_steps: Optional[tuple[int, int]] = None,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 process_index: int = 0,
                 process_name: str = "ddl"):
        self.enabled = bool(enabled)
        self.trace_dir = trace_dir
        self.trace_steps = tuple(trace_steps) if trace_steps else None
        self.process_index = int(process_index)
        self.process_name = process_name
        self._events: deque = deque(maxlen=max(int(max_events), 1))
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}

    # -- recording ----------------------------------------------------------

    def _in_window(self, step: Optional[int]) -> bool:
        if self.trace_steps is None or step is None:
            return True
        lo, hi = self.trace_steps
        return lo <= step < hi

    def _emit(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)

    def span(self, name: str, *, step: Optional[int] = None, **args: Any):
        """Context manager timing a phase; ``with tele.span("data_wait",
        step=i): ...``. Returns the shared no-op span when disabled or when
        ``step`` falls outside the trace window."""
        if not self.enabled or not self._in_window(step):
            return _NULL_SPAN
        if step is not None:
            args["step"] = step
        return _Span(self, name, args)

    def record_span(self, name: str, start_s: float, end_s: float, *,
                    step: Optional[int] = None, tid: Optional[int] = None,
                    **args: Any) -> None:
        """Record an already-measured span from two :func:`now_s` readings
        — for call sites that time unconditionally (the hot loop shares one
        clock read between telemetry and the straggler monitor) or that
        only decide to record after the fact (checkpoint_save records only
        when a save actually launched). ``tid`` overrides the thread-id
        lane: the serve engine renders per-slot decode ticks on one stable
        track per slot instead of interleaving every slot onto the host
        thread's row."""
        if not self.enabled or not self._in_window(step):
            return
        if step is not None:
            args["step"] = step
        self._emit({
            "name": name, "ph": "X", "ts": int(start_s * 1e6),
            "dur": max(int((end_s - start_s) * 1e6), 0),
            "pid": self.process_index,
            "tid": (threading.get_ident() & 0xFFFF if tid is None
                    else int(tid)),
            "args": args})

    def flow(self, name: str, flow_id: int, phase: str, *,
             ts_s: Optional[float] = None, cat: str = "serve",
             **args: Any) -> None:
        """A flow event: ``phase`` is ``"s"`` (start), ``"t"`` (step), or
        ``"f"`` (finish). Events sharing ``cat`` + ``flow_id`` draw one
        arrow chain in the trace viewer ACROSS processes — how a request
        re-dispatched after a replica death stays one visual thread. Flow
        events bind to the enclosing slice on their pid/tid/ts, so stamp
        ``ts_s`` inside the span the arrow should anchor to."""
        if not self.enabled:
            return
        event = {
            "name": name, "cat": cat, "ph": phase, "id": int(flow_id),
            "ts": (time.monotonic_ns() // 1000 if ts_s is None
                   else int(ts_s * 1e6)),
            "pid": self.process_index,
            "tid": threading.get_ident() & 0xFFFF, "args": args}
        if phase == "f":
            event["bp"] = "e"  # bind the finish to the enclosing slice
        self._emit(event)

    def async_begin(self, name: str, async_id: int, *,
                    ts_s: Optional[float] = None, cat: str = "serve",
                    **args: Any) -> None:
        """Open an async ("b") span — a wall-clock track whose begin/end
        can be in different steps (a request's whole life from arrival to
        retirement). Matched to :meth:`async_end` by ``cat`` + id +
        name."""
        if not self.enabled:
            return
        self._emit({
            "name": name, "cat": cat, "ph": "b", "id": int(async_id),
            "ts": (time.monotonic_ns() // 1000 if ts_s is None
                   else int(ts_s * 1e6)),
            "pid": self.process_index, "tid": 0, "args": args})

    def async_end(self, name: str, async_id: int, *,
                  ts_s: Optional[float] = None, cat: str = "serve",
                  **args: Any) -> None:
        """Close an async span opened by :meth:`async_begin`."""
        if not self.enabled:
            return
        self._emit({
            "name": name, "cat": cat, "ph": "e", "id": int(async_id),
            "ts": (time.monotonic_ns() // 1000 if ts_s is None
                   else int(ts_s * 1e6)),
            "pid": self.process_index, "tid": 0, "args": args})

    def instant(self, name: str, *, step: Optional[int] = None,
                **args: Any) -> None:
        """A zero-duration marker (fault fired, restart scheduled, ...)."""
        if not self.enabled:
            return
        if step is not None:
            args["step"] = step
        self._emit({
            "name": name, "ph": "i", "s": "p",
            "ts": time.monotonic_ns() // 1000, "pid": self.process_index,
            "tid": threading.get_ident() & 0xFFFF, "args": args})

    def gauge(self, name: str, value, *, step: Optional[int] = None) -> None:
        """A sampled value (HBM bytes, queue depth) -> Chrome counter
        track."""
        if not self.enabled or not self._in_window(step):
            return
        self._emit({
            "name": name, "ph": "C", "ts": time.monotonic_ns() // 1000,
            "pid": self.process_index, "tid": 0,
            "args": {"value": float(value)}})

    def counter(self, name: str, inc: float = 1.0, *,
                step: Optional[int] = None) -> None:
        """A monotonically accumulating count (faults fired, bad steps);
        each increment emits the running total as a counter event."""
        if not self.enabled:
            return
        with self._lock:
            total = self._counters.get(name, 0.0) + float(inc)
            self._counters[name] = total
        if not self._in_window(step):
            return
        self._emit({
            "name": name, "ph": "C", "ts": time.monotonic_ns() // 1000,
            "pid": self.process_index, "tid": 0,
            "args": {"value": total}})

    # -- inspection / export ------------------------------------------------

    def snapshot(self) -> list[dict]:
        """The buffered events, oldest first, without draining them."""
        with self._lock:
            return list(self._events)

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write (and DRAIN) the buffered events as Chrome-trace JSON.

        Merges into an existing file at ``path`` — a restarted attempt or
        the launcher folds its events into the same trace. Returns the
        path written, or None when there is nowhere/nothing to write.
        """
        if path is None:
            if self.trace_dir is None:
                return None
            path = trace_path(self.trace_dir, self.process_index)
        with self._lock:
            events = list(self._events)
            self._events.clear()
        if not events:
            return None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # The merge below is read-modify-write; two processes (or threads
        # of one process through separate registries) exporting to the
        # same path would otherwise race and lose whichever write landed
        # first. Serialize through an advisory lock on a sidecar file —
        # the trace itself is still replaced atomically, so readers never
        # need the lock.
        lock_fh = None
        if fcntl is not None:
            lock_fh = open(f"{path}.lock", "a")
            fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
        try:
            existing: list = []
            try:
                with open(path) as fh:
                    prior = json.load(fh)
                existing = (prior.get("traceEvents", [])
                            if isinstance(prior, dict) else list(prior))
            except (OSError, ValueError):
                pass  # first write, or an unreadable prior file
            meta = []
            if not any(e.get("ph") == "M"
                       and e.get("pid") == self.process_index
                       for e in existing):
                meta.append({
                    "name": "process_name", "ph": "M", "ts": 0,
                    "pid": self.process_index,
                    "args": {"name":
                             f"{self.process_name} p{self.process_index}"}})
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "w") as fh:
                json.dump({"traceEvents": existing + meta + events,
                           "displayTimeUnit": "ms"}, fh)
            os.replace(tmp, path)
        finally:
            if lock_fh is not None:
                fcntl.flock(lock_fh.fileno(), fcntl.LOCK_UN)
                lock_fh.close()
        return path


# ---------------------------------------------------------------------------
# Module singleton — what the instrumentation sites record through.
# ---------------------------------------------------------------------------

_active = Telemetry()


def get() -> Telemetry:
    return _active


def configure(enabled: Optional[bool] = None,
              trace_dir: Optional[str] = None,
              trace_steps: Optional[tuple[int, int]] = None,
              max_events: int = DEFAULT_MAX_EVENTS,
              process_index: int = 0,
              process_name: str = "ddl") -> Telemetry:
    """Install a fresh module-level registry (one per run). ``enabled``
    defaults to "a trace destination was given"."""
    global _active
    if enabled is None:
        enabled = trace_dir is not None or trace_steps is not None
    _active = Telemetry(enabled=enabled, trace_dir=trace_dir,
                        trace_steps=trace_steps, max_events=max_events,
                        process_index=process_index,
                        process_name=process_name)
    return _active


def configure_from_env(process_index: int = 0,
                       process_name: str = "ddl") -> Optional[Telemetry]:
    """Child-process side of the serve trace plumbing: install a registry
    pointed at :data:`ENV_TRACE_DIR` when the supervisor set it, else
    leave the (disabled) singleton alone and return None. Replicas call
    this before building their engine so the engine's tracer resolves."""
    trace_dir = os.environ.get(ENV_TRACE_DIR)
    if not trace_dir:
        return None
    return configure(enabled=True, trace_dir=trace_dir,
                     process_index=process_index,
                     process_name=process_name)


def reset() -> None:
    """Back to the disabled singleton (tests)."""
    global _active
    _active = Telemetry()


# ---------------------------------------------------------------------------
# Trace analysis helpers — shared by tools/summarize_trace.py and bench.py's
# phase-breakdown record section.
# ---------------------------------------------------------------------------

def load_events(path: str) -> list[dict]:
    """Events from a Chrome-trace JSON file (object or bare-array form)."""
    with open(path) as fh:
        obj = json.load(fh)
    return obj.get("traceEvents", []) if isinstance(obj, dict) else list(obj)


def load_events_tolerant(path: str) -> tuple[list[dict], Optional[str]]:
    """Like :func:`load_events`, but salvages a truncated file.

    A crashed process can leave a trace cut mid-write (the export itself
    is atomic, but ctrl-C'd copies and half-synced artifact pulls are
    not). Returns ``(events, error)``: on clean parse ``error`` is None;
    on damage, every complete event object that precedes the cut is
    recovered one ``raw_decode`` at a time and ``error`` says what was
    lost — the caller decides how loudly to say it (an analysis that
    silently drops the tail would misreport phase totals as complete).
    """
    try:
        return load_events(path), None
    except OSError as e:
        return [], f"{path}: {e}"
    except ValueError:
        pass
    try:
        with open(path, errors="replace") as fh:
            text = fh.read()
    except OSError as e:
        return [], f"{path}: {e}"
    # Find the events array (object form) or the array start (bare form),
    # then decode complete {...} entries until the truncation point.
    start = text.find('"traceEvents"')
    start = text.find("[", start if start >= 0 else 0)
    if start < 0:
        return [], f"{path}: unparseable trace (no event array found)"
    dec = json.JSONDecoder()
    events: list[dict] = []
    i = start + 1
    n = len(text)
    while i < n:
        while i < n and text[i] in " \t\r\n,":
            i += 1
        if i >= n or text[i] == "]":
            break
        try:
            obj, end = dec.raw_decode(text, i)
        except ValueError:
            break  # the truncated tail — everything before it is saved
        if isinstance(obj, dict):
            events.append(obj)
        i = end
    return events, (f"{path}: truncated trace; recovered "
                    f"{len(events)} complete event(s)")


def merge_traces(paths, out_path: str) -> tuple[Optional[str], list[str]]:
    """Fold several per-process trace files into ONE Chrome-trace JSON.

    Every timestamp is CLOCK_MONOTONIC on the one host the serve fleet
    runs on, so a plain concatenation is already time-coherent; events
    are sorted metadata-first then by timestamp so viewers name the
    process tracks before drawing them. Damaged inputs (a SIGKILL'd
    replica's final file) go through the tolerant loader — whatever was
    recovered is merged and the loss is reported, not hidden. Returns
    ``(out_path or None-if-no-events, errors)``.
    """
    events: list[dict] = []
    errors: list[str] = []
    for p in paths:
        evs, err = load_events_tolerant(p)
        events.extend(evs)
        if err:
            errors.append(err)
    if not events:
        return None, errors
    events.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0)))
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    os.replace(tmp, out_path)
    return out_path, errors


def merge_trace_dir(trace_dir: str, out_name: str = "trace.merged.json"
                    ) -> tuple[Optional[str], list[str]]:
    """Merge every ``trace.p*.json`` in ``trace_dir`` (the per-replica
    layout :func:`trace_path` writes) into ``trace_dir/out_name``. The
    merged name deliberately does not match the per-process glob, so
    directory-mode tools never double-count it."""
    paths = sorted(glob.glob(os.path.join(trace_dir, "trace.p*.json")))
    if not paths:
        return None, [f"{trace_dir}: no trace.p*.json files to merge"]
    return merge_traces(paths, os.path.join(trace_dir, out_name))


def phase_totals(events) -> dict[str, dict[str, float]]:
    """Per-phase aggregate over the complete ("X") spans: count, total and
    mean duration in milliseconds, keyed by span name, largest total
    first."""
    acc: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") == "X":
            acc.setdefault(e["name"], []).append(float(e.get("dur", 0)))
    out = {}
    for name, durs in sorted(acc.items(),
                             key=lambda kv: -sum(kv[1])):
        total_us = sum(durs)
        out[name] = {"count": len(durs),
                     "total_ms": total_us / 1000.0,
                     "mean_ms": total_us / len(durs) / 1000.0}
    return out


def overlap_fraction(events) -> float:
    """Fraction of gradient reduce-scatter spans issued INSIDE backward.

    The ZeRO bucket scatters (parallel/zero.py) emit trace-time spans named
    ``collective:<stage>/reduce_scatter/bucketNN``; the custom_vjp backward
    rules of the overlapped zero2/zero3 schedules mark theirs with
    ``args.overlapped`` while the serialized post-backward pass (zero1,
    overlap off) does not. The ratio is therefore the structural
    backward/collective-overlap fraction of the traced program: 1.0 when
    every bucket's scatter can run concurrently with remaining backward
    compute, 0.0 for a fully serialized schedule — or when no scatter spans
    exist at all (no sharding, or an AOT cache hit that skipped tracing).
    """
    total = overlapped = 0
    for e in events:
        if e.get("ph") != "X" or "/reduce_scatter/" not in e.get("name", ""):
            continue
        total += 1
        if (e.get("args") or {}).get("overlapped"):
            overlapped += 1
    return overlapped / total if total else 0.0


def pipeline_bubble_fraction(events):
    """Measured pipeline bubble: idle stage-ticks / total stage-ticks over
    the ``pipeline_tick`` instants the schedule loop emits per executed tick
    (models/pipeline.py). Like :func:`overlap_fraction`, the instants fire
    at trace time — a multi-step run traced once contributes one full
    schedule's worth of ticks (re-traces add whole schedules, leaving the
    ratio unchanged), and an AOT cache hit that skipped tracing leaves no
    events at all, reported honestly as ``None`` rather than a fake zero
    (a zero bubble is a real, excellent measurement)."""
    idle = total = 0
    for e in events:
        if e.get("ph") != "i" or e.get("name") != "pipeline_tick":
            continue
        args = e.get("args") or {}
        idle += int(args.get("idle", 0))
        total += int(args.get("stages", 0))
    return idle / total if total else None
