"""Online anomaly detection over the log-cadence metric stream.

The bad-step guard (`train/loop.py`) catches the sharpest failure —
non-finite loss/grad inside a single compiled step — but a run can rot
in ways no single step exposes: loss quietly diverging, grad norms
drifting orders of magnitude, throughput collapsing because one loader
started thrashing, a straggler trending worse every log interval. This
detector watches exactly the signals the chief already has in hand on
the log cadence and flags four families:

    loss_spike / loss_nonfinite          optimization diverging
    grad_norm_drift / grad_norm_nonfinite  update scale off the rails
    throughput_collapse / loader_stall   examples/sec cratered
                                         (loader_stall when data-wait
                                         dominates the interval)
    straggler_trending                   one host slow for N intervals
    bad_step                             the compiled guard tripped

Launcher-side signal (fed by ``launch.run_with_restarts`` on each planned
elastic re-formation via ``update_elastic()``):

    elastic_reconfig                     re-formation storm — membership
                                         churning faster than training can
                                         amortize (flapping host, bad
                                         autoscaler); a handful of planned
                                         re-formations is normal and stays
                                         quiet

Serve-side signals (fed by the replica / bench on the same cadence via
``update_serve()``):

    queue_blowup                         wait queue far above its rolling
                                         median — admission has stalled
    shed_storm                           brownout shedding in bulk
    deadline_miss_rate                   a large fraction of completions
                                         are deadline misses
    spec_acceptance_collapse             speculative-decode acceptance
                                         cratered vs its rolling median
                                         (drafter drift / distribution
                                         shift — every rejected token is
                                         wasted verify work)

Attribution-fed serve signals (require the serve tracer —
``serve/tracing.py`` — whose per-request TTFT decomposition supplies the
interval means; quiet when tracing is off):

    queue_wait_regression                mean pre-admission wait
                                         (queue + interference) far above
                                         its rolling median
    allocation_stall                     completions spending real time
                                         blocked on pages
                                         (admission_stall component)
    decode_stall                         the decode dispatch itself got
                                         slower vs its rolling median
                                         (contention, pool thrash)

Design constraints, in order:

1. **Zero false positives on a clean run.** Baselines are rolling
   *medians* with MAD-scaled margins plus generous absolute floors, and
   nothing fires until ``min_samples`` observations exist — compile
   warm-up, checkpoint pauses and ordinary loss noise stay quiet.
2. **Fast on real faults.** An injected loss spike or NaN flags on the
   first or second cadence after it appears (acceptance bound: five).
3. **Cheap.** Pure host-side Python over deques; no device fetches
   beyond what `MetricLogger.log` already paid.

``update()`` returns plain anomaly dicts; ``report()`` is the one place
that turns them into operator-visible artifacts — flight-recorder
events, telemetry instants, stderr warnings, and (for non-finite
signals) bad-step-guard feedback — so the loop and tests share one
reporting path.

Pure stdlib on purpose.
"""
from __future__ import annotations

import math
import sys
from collections import deque
from statistics import median
from typing import Any, Optional

# Kinds report() feeds to the bad-step tracker. "bad_step" itself is
# excluded: the tracker already counted the compiled flag via push() —
# feeding it back would double-count every skip.
FEEDS_GUARD = ("loss_nonfinite", "grad_norm_nonfinite")


def _finite(value: Any) -> Optional[float]:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


class AnomalyDetector:
    """Rolling-median detector; one instance per run, fed on log steps."""

    def __init__(self, *, window: int = 16, min_samples: int = 4,
                 loss_margin: float = 0.5, loss_mad_k: float = 8.0,
                 grad_drift_factor: float = 10.0,
                 throughput_collapse_frac: float = 0.35,
                 data_wait_dominance: float = 0.6,
                 straggler_ratio: float = 1.5,
                 straggler_patience: int = 3,
                 queue_blowup_factor: float = 4.0,
                 queue_floor: int = 4,
                 shed_storm_min: int = 3,
                 deadline_miss_threshold: float = 0.25,
                 spec_collapse_frac: float = 0.25,
                 spec_median_floor: float = 0.2,
                 spec_min_proposed: int = 4,
                 queue_wait_factor: float = 4.0,
                 queue_wait_floor_s: float = 0.05,
                 alloc_stall_factor: float = 4.0,
                 alloc_stall_floor_s: float = 0.02,
                 decode_stall_factor: float = 3.0,
                 decode_stall_floor_s: float = 0.005,
                 elastic_storm_min: int = 4,
                 elastic_storm_window_s: float = 600.0):
        self.min_samples = int(min_samples)
        self.loss_margin = float(loss_margin)
        self.loss_mad_k = float(loss_mad_k)
        self.grad_drift_factor = float(grad_drift_factor)
        self.throughput_collapse_frac = float(throughput_collapse_frac)
        self.data_wait_dominance = float(data_wait_dominance)
        self.straggler_ratio = float(straggler_ratio)
        self.straggler_patience = int(straggler_patience)
        self.queue_blowup_factor = float(queue_blowup_factor)
        self.queue_floor = int(queue_floor)
        self.shed_storm_min = int(shed_storm_min)
        self.deadline_miss_threshold = float(deadline_miss_threshold)
        self.spec_collapse_frac = float(spec_collapse_frac)
        self.spec_median_floor = float(spec_median_floor)
        self.spec_min_proposed = int(spec_min_proposed)
        self.queue_wait_factor = float(queue_wait_factor)
        self.queue_wait_floor_s = float(queue_wait_floor_s)
        self.alloc_stall_factor = float(alloc_stall_factor)
        self.alloc_stall_floor_s = float(alloc_stall_floor_s)
        self.decode_stall_factor = float(decode_stall_factor)
        self.decode_stall_floor_s = float(decode_stall_floor_s)
        self.elastic_storm_min = int(elastic_storm_min)
        self.elastic_storm_window_s = float(elastic_storm_window_s)
        self._loss: deque = deque(maxlen=window)
        self._grad: deque = deque(maxlen=window)
        self._eps: deque = deque(maxlen=window)
        self._queue: deque = deque(maxlen=window)
        self._accept: deque = deque(maxlen=window)
        self._qwait: deque = deque(maxlen=window)
        self._astall: deque = deque(maxlen=window)
        self._dtick: deque = deque(maxlen=window)
        self._reforms: deque = deque(maxlen=max(window, 32))
        self._straggler_streak = 0

    def update(self, step: int, *, loss: Any = None, grad_norm: Any = None,
               examples_per_sec: Any = None, data_wait_frac: Any = None,
               straggler_ratio: Any = None,
               bad_step: Any = None) -> list[dict]:
        """Feed one log-cadence observation; returns flagged anomalies
        (empty list on a healthy interval). Missing signals are skipped."""
        out: list[dict] = []

        def flag(kind: str, value: Any, baseline: Any, detail: str) -> None:
            out.append({"kind": kind, "step": int(step),
                        "value": value, "baseline": baseline,
                        "detail": detail})

        if loss is not None:
            v = _finite(loss)
            if v is None:
                flag("loss_nonfinite", float("nan"), None,
                     f"loss={loss!r}")
            else:
                if len(self._loss) >= self.min_samples:
                    med = median(self._loss)
                    mad = median(abs(x - med) for x in self._loss)
                    limit = med + max(self.loss_margin,
                                      self.loss_mad_k * mad)
                    if v > limit:
                        flag("loss_spike", v, med,
                             f"loss {v:.4g} > {limit:.4g} "
                             f"(median {med:.4g})")
                self._loss.append(v)

        if grad_norm is not None:
            v = _finite(grad_norm)
            if v is None:
                flag("grad_norm_nonfinite", float("nan"), None,
                     f"grad_norm={grad_norm!r}")
            else:
                if len(self._grad) >= self.min_samples:
                    med = median(self._grad)
                    if med > 1e-12:
                        ratio = v / med
                        if (ratio > self.grad_drift_factor
                                or ratio < 1.0 / self.grad_drift_factor):
                            flag("grad_norm_drift", v, med,
                                 f"grad norm {v:.4g} is {ratio:.3g}x the "
                                 f"rolling median {med:.4g}")
                self._grad.append(v)

        eps = _finite(examples_per_sec) if examples_per_sec is not None \
            else None
        wait = _finite(data_wait_frac) if data_wait_frac is not None \
            else None
        if eps is not None and eps > 0:
            if len(self._eps) >= self.min_samples:
                med = median(self._eps)
                if med > 0 and eps < self.throughput_collapse_frac * med:
                    if wait is not None and wait >= self.data_wait_dominance:
                        flag("loader_stall", eps, med,
                             f"throughput {eps:.4g} ex/s vs median "
                             f"{med:.4g} with {wait:.0%} of the interval "
                             "spent waiting on data")
                    else:
                        flag("throughput_collapse", eps, med,
                             f"throughput {eps:.4g} ex/s < "
                             f"{self.throughput_collapse_frac:.0%} of "
                             f"median {med:.4g}")
            self._eps.append(eps)

        if straggler_ratio is not None:
            r = _finite(straggler_ratio)
            if r is not None and r >= self.straggler_ratio:
                self._straggler_streak += 1
                if self._straggler_streak >= self.straggler_patience:
                    flag("straggler_trending", r, self.straggler_ratio,
                         f"host step-time skew {r:.3g}x mean for "
                         f"{self._straggler_streak} consecutive log "
                         "intervals")
                    self._straggler_streak = 0
            elif r is not None:
                self._straggler_streak = 0

        if bad_step is not None:
            b = _finite(bad_step)
            if b is not None and b > 0:
                flag("bad_step", b, 0.0,
                     "compiled bad-step guard skipped a non-finite update")

        return out

    def update_serve(self, step: int, *, queue_depth: Any = None,
                     sheds: Any = None, deadline_misses: Any = None,
                     finished: Any = None, spec_proposed: Any = None,
                     spec_accepted: Any = None,
                     queue_wait_s: Any = None,
                     alloc_stall_s: Any = None,
                     decode_tick_s: Any = None) -> list[dict]:
        """Feed one serve-cadence observation; returns flagged anomalies.

        ``queue_depth`` is the instantaneous wait-queue length;
        ``sheds``/``deadline_misses``/``finished`` and
        ``spec_proposed``/``spec_accepted`` are counts *for this
        interval* (the caller diffs the engine's cumulative counters).
        ``queue_wait_s``/``alloc_stall_s``/``decode_tick_s`` are the
        serve tracer's interval means (``ServeTracer.interval_signals``):
        mean pre-admission wait and admission stall per completion, mean
        decode dispatch duration per step — None (the default, and what
        an untraced engine supplies) keeps those detectors silent.
        Same zero-false-positive discipline as ``update()``: queue depth
        and spec acceptance judge against their own rolling medians
        behind absolute floors and ``min_samples``; the storm/rate kinds
        need real volume before they can fire, so a healthy engine never
        trips them. Spec acceptance additionally requires the rolling
        median itself to clear ``spec_median_floor`` — a drafter that
        was never any good is a configuration problem, not an anomaly —
        and ``spec_min_proposed`` proposals this interval, so a single
        unlucky round stays quiet."""
        out: list[dict] = []

        def flag(kind: str, value: Any, baseline: Any, detail: str) -> None:
            out.append({"kind": kind, "step": int(step),
                        "value": value, "baseline": baseline,
                        "detail": detail})

        if queue_depth is not None:
            q = _finite(queue_depth)
            if q is not None:
                if len(self._queue) >= self.min_samples:
                    med = median(self._queue)
                    limit = max(float(self.queue_floor),
                                self.queue_blowup_factor * max(med, 1.0))
                    if q > limit:
                        flag("queue_blowup", q, med,
                             f"wait queue {q:.0f} deep vs rolling median "
                             f"{med:.0f} (limit {limit:.0f}) — admission "
                             "has stalled or arrivals outrun decode")
                self._queue.append(q)

        if sheds is not None:
            s = _finite(sheds)
            if s is not None and s >= self.shed_storm_min:
                flag("shed_storm", s, float(self.shed_storm_min),
                     f"brownout shed {s:.0f} request(s) in one interval — "
                     "the pool or queue is pressured enough to drop work "
                     "in bulk")

        if deadline_misses is not None:
            m = _finite(deadline_misses)
            done = _finite(finished) if finished is not None else None
            total = (m or 0.0) + (done or 0.0)
            if (m is not None and m > 0 and total >= self.min_samples
                    and m / total >= self.deadline_miss_threshold):
                flag("deadline_miss_rate", m / total,
                     self.deadline_miss_threshold,
                     f"{m:.0f} of {total:.0f} completions this interval "
                     f"missed their deadline "
                     f"({m / total:.0%} >= "
                     f"{self.deadline_miss_threshold:.0%})")

        if spec_proposed is not None:
            p = _finite(spec_proposed)
            a = _finite(spec_accepted) if spec_accepted is not None else None
            if p is not None and p >= self.spec_min_proposed:
                rate = max(a or 0.0, 0.0) / p
                if len(self._accept) >= self.min_samples:
                    med = median(self._accept)
                    if (med >= self.spec_median_floor
                            and rate < self.spec_collapse_frac * med):
                        flag("spec_acceptance_collapse", rate, med,
                             f"spec acceptance {rate:.0%} this interval "
                             f"({(a or 0):.0f}/{p:.0f}) vs rolling median "
                             f"{med:.0%} — drafter has drifted from the "
                             "target distribution; verify work is being "
                             "wasted")
                self._accept.append(rate)

        if queue_wait_s is not None:
            w = _finite(queue_wait_s)
            if w is not None:
                if len(self._qwait) >= self.min_samples:
                    med = median(self._qwait)
                    limit = max(self.queue_wait_floor_s,
                                self.queue_wait_factor * med)
                    if w > limit:
                        flag("queue_wait_regression", w, med,
                             f"mean pre-admission wait {w * 1e3:.1f}ms "
                             f"per completion vs rolling median "
                             f"{med * 1e3:.1f}ms (limit "
                             f"{limit * 1e3:.1f}ms) — requests are aging "
                             "in the queue before any resource stall")
                self._qwait.append(w)

        if alloc_stall_s is not None:
            s2 = _finite(alloc_stall_s)
            if s2 is not None:
                if len(self._astall) >= self.min_samples:
                    med = median(self._astall)
                    limit = max(self.alloc_stall_floor_s,
                                self.alloc_stall_factor * med)
                    if s2 > limit:
                        flag("allocation_stall", s2, med,
                             f"mean admission stall {s2 * 1e3:.1f}ms per "
                             f"completion vs rolling median "
                             f"{med * 1e3:.1f}ms (limit "
                             f"{limit * 1e3:.1f}ms) — the page pool is "
                             "the bottleneck, not scheduling policy")
                self._astall.append(s2)

        if decode_tick_s is not None:
            dt = _finite(decode_tick_s)
            if dt is not None:
                if len(self._dtick) >= self.min_samples:
                    med = median(self._dtick)
                    limit = max(self.decode_stall_floor_s,
                                self.decode_stall_factor * med)
                    if dt > limit:
                        flag("decode_stall", dt, med,
                             f"mean decode dispatch {dt * 1e3:.1f}ms vs "
                             f"rolling median {med * 1e3:.1f}ms (limit "
                             f"{limit * 1e3:.1f}ms) — the decode program "
                             "itself slowed down (host contention, pool "
                             "thrash), not admission")
                self._dtick.append(dt)

        return out

    def update_elastic(self, t_s: Any, *, epoch: Any = None) -> list[dict]:
        """Feed one planned elastic re-formation (``t_s``: monotonic
        seconds at commit). Flags ``elastic_reconfig`` only when
        ``elastic_storm_min`` or more re-formations land inside one
        ``elastic_storm_window_s`` window — membership is churning faster
        than training can re-amortize its compile/restore cost (a flapping
        host, an autoscaler oscillating). The acceptance soaks' two-or-
        three planned re-formations stay far below the floor, keeping the
        zero-false-positive discipline."""
        out: list[dict] = []
        t = _finite(t_s)
        if t is None:
            return out
        self._reforms.append(t)
        recent = [x for x in self._reforms
                  if t - x <= self.elastic_storm_window_s]
        if len(recent) >= self.elastic_storm_min:
            out.append({
                "kind": "elastic_reconfig",
                "step": int(epoch) if epoch is not None else 0,
                "value": float(len(recent)),
                "baseline": float(self.elastic_storm_min),
                "detail": (f"{len(recent)} elastic re-formations inside "
                           f"{self.elastic_storm_window_s:.0f}s (epoch "
                           f"{epoch}) — membership is flapping; training "
                           "cannot amortize reconfiguration cost"),
            })
        return out


def report(anomalies: list[dict], *, flight_rec: Any = None,
           tele: Any = None, bad_tracker: Any = None,
           stream: Any = None) -> None:
    """Fan one ``update()`` result out to every consumer: flight record,
    trace instants, stderr, and the bad-step guard (non-finite kinds
    count toward its consecutive-abort limit, so a run pinned at NaN
    aborts even when the compiled flag is not being fetched)."""
    stream = sys.stderr if stream is None else stream
    for a in anomalies:
        kind, step = a["kind"], a["step"]
        print(f"# anomaly: {kind} at step {step} — {a['detail']}",
              file=stream, flush=True)
        if flight_rec is not None:
            flight_rec.record("anomaly", kind=kind, step=step,
                              value=a.get("value"),
                              baseline=a.get("baseline"),
                              detail=a["detail"])
        if tele is not None:
            tele.instant(f"anomaly:{kind}", step=step,
                         detail=a["detail"])
        if bad_tracker is not None and kind in FEEDS_GUARD:
            bad_tracker.note_anomaly()
