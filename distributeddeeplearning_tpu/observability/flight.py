"""Flight recorder: a crash-surviving, append-only structured event log.

The telemetry ring buffer (`telemetry.py`) is deliberately lazy — events
live in memory and hit disk only when `export()` drains them in the
loop's `finally`. That is the right trade for dense phase spans, and the
wrong one for the handful of events a post-mortem actually hinges on: a
`sigkill` (or a real preemption) destroys the unexported tail by design.

The flight recorder is the other half of the trade: a *sparse* JSONL
event log where every line is flushed and fsync'd at record time, so the
record survives any way the process can die. Each writer (one per host,
plus the launcher) appends to its own file in a shared directory:

    <flight_dir>/flight.p0.jsonl        host 0
    <flight_dir>/flight.p1.jsonl        host 1
    <flight_dir>/flight.launcher.jsonl  the launcher

Every event carries one shared identity scheme so records from any
number of hosts and restart attempts merge into one run timeline:

    run      run id, minted once by the launcher (DDL_RUN_ID) or by an
             unlaunched train.py; constant across restart attempts
    attempt  restart attempt (DDL_RESTART_ATTEMPT, 0 when unlaunched)
    host     process index (DDL_PROCESS_ID) or "launcher"
    seq      per-writer monotonic sequence number (tie-break + torn-tail
             detection)
    t        wall-clock seconds (cross-host ordering, human timestamps)
    mono     CLOCK_MONOTONIC seconds — same clock as telemetry.now_s(),
             shared by all processes on one host, so flight events and
             trace instants interleave exactly

Files are size-bounded ring buffers: past ``max_bytes`` the segment
rotates to ``<name>.1`` (one previous segment kept), so a pathological
writer is bounded at ~2x``max_bytes`` per host while the *most recent*
window — the part a post-mortem wants — is always intact.

Pure stdlib on purpose: `launch.py` and `robustness/faults.py` record
flight events and must never import jax. Recording never raises — a
full disk must not kill training.
"""
from __future__ import annotations

import glob
import json
import os
import re
import threading
import time
from typing import Any, Optional

ENV_FLIGHT_DIR = "DDL_FLIGHT_DIR"
ENV_RUN_ID = "DDL_RUN_ID"
# Shared with health.py / faults.py (redeclared to stay import-light).
_ENV_PROCESS_ID = "DDL_PROCESS_ID"
_ENV_ATTEMPT = "DDL_RESTART_ATTEMPT"

DEFAULT_MAX_BYTES = 4 * 1024 * 1024

# Event kinds a post-mortem treats as "something went wrong".
INCIDENT_EVENTS = ("fault", "anomaly", "child_exit", "heartbeat_stale",
                   "preempted", "abort", "giving_up",
                   "serve_replica_lost", "serve_shed",
                   "serve_deadline_miss")


def mint_run_id(now: Optional[float] = None) -> str:
    """A sortable, collision-safe run id: wall time + random suffix."""
    now = time.time() if now is None else now
    stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
    return f"run-{stamp}-{os.urandom(3).hex()}"


def flight_path(directory: str, host: Any) -> str:
    """``flight.p{N}.jsonl`` for rank N; ``flight.{label}.jsonl`` else."""
    label = f"p{host}" if isinstance(host, int) else str(host)
    return os.path.join(directory, f"flight.{label}.jsonl")


class FlightRecorder:
    """Append-only fsync'd JSONL writer for one host.

    ``directory=None`` builds a disabled recorder: ``record()`` is a
    cheap no-op, so call sites never branch.
    """

    def __init__(self, directory: Optional[str], *,
                 run_id: Optional[str] = None,
                 host: Any = 0,
                 attempt: int = 0,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 fsync: bool = True):
        self.enabled = directory is not None
        self.directory = directory
        self.run_id = run_id or mint_run_id()
        self.host = host
        self.attempt = int(attempt)
        self.max_bytes = int(max_bytes)
        self._fsync = bool(fsync)
        self._seq = 0
        self._lock = threading.Lock()
        self._fh = None
        if self.enabled:
            try:
                os.makedirs(directory, exist_ok=True)
                self.path = flight_path(directory, host)
            except OSError:
                self.enabled = False
                self.path = None
        else:
            self.path = None

    @classmethod
    def from_env(cls, *, host: Any = None,
                 directory: Optional[str] = None) -> "FlightRecorder":
        """Build from the launcher-exported environment.

        ``directory`` (e.g. from ``--flight-dir``) overrides
        ``$DDL_FLIGHT_DIR``; with neither set the recorder is disabled.
        The run id comes from ``$DDL_RUN_ID`` when a launcher minted one.
        """
        directory = directory or os.environ.get(ENV_FLIGHT_DIR)
        if host is None:
            try:
                host = int(os.environ.get(_ENV_PROCESS_ID, "0"))
            except ValueError:
                host = 0
        try:
            attempt = int(os.environ.get(_ENV_ATTEMPT, "0"))
        except ValueError:
            attempt = 0
        return cls(directory, run_id=os.environ.get(ENV_RUN_ID),
                   host=host, attempt=attempt)

    # -- writing ---------------------------------------------------------

    def record(self, ev: str, **fields: Any) -> None:
        """Append one event and force it to disk. Never raises."""
        if not self.enabled:
            return
        try:
            with self._lock:
                self._seq += 1
                entry = {"ev": ev, "t": time.time(),
                         "mono": time.monotonic(),
                         "run": self.run_id, "attempt": self.attempt,
                         "host": self.host, "seq": self._seq}
                entry.update(fields)
                line = json.dumps(entry, sort_keys=True,
                                  default=_json_fallback) + "\n"
                fh = self._open_locked()
                fh.write(line)
                fh.flush()
                if self._fsync:
                    os.fsync(fh.fileno())
                if fh.tell() >= self.max_bytes:
                    self._rotate_locked()
        except Exception:  # noqa: BLE001 — recording must never kill a run
            pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def _open_locked(self):
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def _rotate_locked(self) -> None:
        """Ring semantics: keep one previous segment, start a fresh one."""
        try:
            self._fh.close()
        except OSError:
            pass
        self._fh = None
        os.replace(self.path, self.path + ".1")


def _json_fallback(obj: Any) -> Any:
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)


# -- module singleton (telemetry-style) ----------------------------------

_active = FlightRecorder(None)


def get() -> FlightRecorder:
    return _active


def configure(directory: Optional[str], **kw: Any) -> FlightRecorder:
    global _active
    _active.close()
    _active = FlightRecorder(directory, **kw)
    return _active


def configure_from_env(*, host: Any = None,
                       directory: Optional[str] = None) -> FlightRecorder:
    global _active
    _active.close()
    _active = FlightRecorder.from_env(host=host, directory=directory)
    return _active


def reset() -> None:
    configure(None)


# -- reading -------------------------------------------------------------

def read_file(path: str) -> tuple[list[dict], Optional[str]]:
    """Parse one flight file tolerantly.

    A writer killed mid-line (the whole point of the recorder is that
    writers get killed) leaves at most one torn tail line; it is skipped
    and reported, everything before it is salvaged. Returns
    ``(events, error)`` with ``error=None`` when the file parsed whole.
    """
    events: list[dict] = []
    error = None
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            for n, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    error = f"{os.path.basename(path)}:{n}: unparseable line"
                    continue
                if isinstance(obj, dict):
                    obj["_file"] = os.path.basename(path)
                    events.append(obj)
    except OSError as exc:
        return [], f"{path}: {exc}"
    return events, error


def read_all(directory: str) -> tuple[list[dict], list[str]]:
    """All events from every flight file (rotated segments included),
    sorted into one timeline by ``(t, seq)``."""
    events: list[dict] = []
    errors: list[str] = []
    paths = sorted(glob.glob(os.path.join(directory, "flight.*.jsonl.1"))) + \
        sorted(glob.glob(os.path.join(directory, "flight.*.jsonl")))
    for path in paths:
        evs, err = read_file(path)
        events.extend(evs)
        if err:
            errors.append(err)
    events.sort(key=lambda e: (e.get("t", 0.0), e.get("seq", 0)))
    return events, errors


def runs(events: list[dict]) -> list[str]:
    """Distinct run ids, oldest first (by first appearance in time)."""
    seen: dict[str, float] = {}
    for e in events:
        run = e.get("run")
        if run and run not in seen:
            seen[run] = e.get("t", 0.0)
    return sorted(seen, key=seen.get)


def last_run_events(directory: str) -> tuple[list[dict], list[str]]:
    """Events of the most recent run only (latest run id by first-seen
    time), plus any file-level parse errors."""
    events, errors = read_all(directory)
    ids = runs(events)
    if not ids:
        return [], errors
    last = ids[-1]
    return [e for e in events if e.get("run") == last], errors


def last_incident(directory: str) -> Optional[dict]:
    """The most recent incident-class event of the most recent run, or
    ``None``. Used by ``tools/doctor.py`` for a one-line health report."""
    events, _ = last_run_events(directory)
    incidents = [e for e in events if e.get("ev") in INCIDENT_EVENTS]
    return incidents[-1] if incidents else None


def default_dir() -> str:
    """Repo-local fallback (``<repo>/.cache/flight``) for tools that
    inspect the last local run without an explicit ``--flight-dir``."""
    env = os.environ.get(ENV_FLIGHT_DIR)
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, ".cache", "flight")


def describe(event: dict) -> str:
    """One human line for an event — shared by postmortem and doctor."""
    ev = event.get("ev", "?")
    bits = []
    for key in ("kind", "label", "attribution", "trigger", "child", "rc",
                "step", "signum", "detail"):
        if key in event and event[key] is not None:
            bits.append(f"{key}={event[key]}")
    stamp = time.strftime("%H:%M:%S", time.localtime(event.get("t", 0.0)))
    host = event.get("host", "?")
    attempt = event.get("attempt", 0)
    suffix = f" ({', '.join(bits)})" if bits else ""
    return f"{stamp} [a{attempt} h{host}] {ev}{suffix}"


_RE_FLIGHT_FILE = re.compile(r"^flight\..+\.jsonl(\.1)?$")


def is_flight_file(name: str) -> bool:
    return bool(_RE_FLIGHT_FILE.match(name))
