"""CPU-proxy perf regression gate: perf bugs fail tier-1, not chip time.

The chip tunnel is scarce and flaky (BENCH_r01-r05: 2 of 5 rounds never
reached a backend), so a perf regression that waits for chip time to be
noticed waits for days. This gate catches the host-visible class of
regression — slower compiled step on a fixed workload, a phase whose share
of the step exploded (data pipeline stall, accidental sync, pathological
retrace) — on CPU, deterministically, inside the tier-1 test budget.

How it works:

- :class:`ProxyRunner` builds ONE tiny fixed-shape training program
  (``resnet18_thin``, 32 px, batch 8, seed 0, float32, single device —
  deliberately the chaos benchmark's workload) through the real
  ``train/loop.build`` path, then measures per-step wall time and the
  telemetry phase breakdown (``data_wait`` / ``dispatch`` /
  ``fetch_barrier`` — the same phase names the production loop records).
- Wall time is normalized by :func:`calibrate` — a fixed numpy matmul
  workload timed in the same process — so the checked-in baseline
  (``perf_baselines.json``) transfers across machine speeds: the gate
  compares ``step_wall / calib_unit`` ratios, not absolute seconds.
- :func:`compare` fails the build when the normalized step time exceeds
  ``baseline x step_hi`` or any phase's share of the step grew by more
  than ``share_abs`` — both tolerances live IN the baseline file, so
  recalibration and tolerance changes are one reviewed diff.
- ``inject_sleep_s`` plants a sleep inside the traced ``data_wait`` phase;
  the self-test in tests/test_perf_gate.py proves the gate flips on it
  (a gate that cannot fail is decoration, not a gate).

``tools/perf_gate.py`` is the CLI (check / --recalibrate); the tier-1
test (``@pytest.mark.perf_gate``, audited by tools/marker_audit.py) is
the enforcement point. Results land in ``.cache/perf_gate_last.json`` so
``tools/doctor.py`` can report gate status without rerunning pytest.
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Any, Optional

from distributeddeeplearning_tpu.observability import (perf_report,
                                                       sidecars, telemetry)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(_REPO_ROOT, "perf_baselines.json")
LAST_RESULT_PATH = sidecars.path_for("perf_gate_last")

SCHEMA_VERSION = 1

# The fixed proxy workload. Any change here invalidates perf_baselines.json
# — bump via ``python tools/perf_gate.py --recalibrate`` in the same PR.
WORKLOAD = {
    "model": "resnet18_thin",
    "image_size": 32,
    "batch": 8,
    "dtype": "float32",
    "seed": 0,
    "steps": 10,
    "warmup": 3,
}
# Named gate workloads. "default" is the headline proxy above (top level
# of perf_baselines.json); the rest live under the file's "extras" key and
# gate specific schedules. ``zero2_overlap`` drives the overlapped ZeRO-2
# path on a dp=2 CPU mesh — the custom_vjp bucket boundaries, per-bucket
# reduce-scatter, and chunked update all sit inside its timed step, so a
# retrace or added sync in the sharded schedule fails tier-1 here instead
# of waiting for chip time. Fewer steps than the default: the sharded
# step is slower per step and the gate needs a median, not a mean.
# The serve-engine decode proxy (kind="serve_decode" routes construction
# to :class:`ServeProxyRunner`): a tiny Engine with every slot held live,
# so each timed step is one compiled decode advance plus the engine's
# host bookkeeping — the per-token serving cost continuous batching pays.
# A regression here (retrace in the decode program, accidental pool copy,
# host loop bloat) fails tier-1 instead of waiting for chip time.
SERVE_WORKLOAD = {
    "kind": "serve_decode",
    "model": "gpt_tiny",
    "vocab_size": 256,
    "dtype": "float32",
    "max_slots": 4,
    "page_size": 4,
    "num_pages": 32,
    "max_pages_per_slot": 8,
    "prefill_buckets": [8],
    "seed": 0,
    "steps": 10,
    "warmup": 3,
}
# The prefix-hit admission proxy (kind="serve_prefix_prefill"): a tiny
# Engine with the radix prefix cache ON, its tree primed with one shared
# head; each timed step is one admission whose prompt hits that prefix —
# radix walk, shared-page mapping, suffix block prefill, retire. The
# serve fast path's headline win lives in this path, so a regression
# here (retrace in the block-prefill program, host-side tree bloat, a
# COW copy that stopped being in-place) fails tier-1 on CPU.
SERVE_PREFIX_WORKLOAD = {
    "kind": "serve_prefix_prefill",
    "model": "gpt_tiny",
    "vocab_size": 256,
    "dtype": "float32",
    "max_slots": 4,
    "page_size": 4,
    "num_pages": 64,
    "max_pages_per_slot": 8,
    "prefill_buckets": [8, 32],
    "shared_prefix_len": 16,
    "tail_len": 2,
    "prefix_cache": True,
    "seed": 0,
    "steps": 10,
    "warmup": 3,
}
# The interleaved-pipeline proxy: bert_tiny_pp4 (4 layers, 2 stages,
# layers_per_stage=2) under the 1F1B schedule with V=2 virtual chunks on
# a pipeline=2 CPU sub-mesh — every steady-state 1F1B tick, both
# activation-shift forms (inject + circular wrap), the per-tick chunk
# selection, and the canonical->interleaved param re-layout all sit
# inside its timed step. A retrace in the tick loop, a chunk gather that
# stopped being a static slice, or an accidental sync between ticks
# fails tier-1 here instead of waiting for chip time.
PIPELINE_WORKLOAD = {
    "model": "bert_tiny_pp4",
    "seq_len": 16,
    "vocab_size": 256,
    "batch": 8,
    "dtype": "float32",
    "seed": 0,
    "steps": 6,
    "warmup": 2,
    "pp": 2,
    "pipeline_schedule": "1f1b",
    "pipeline_virtual_stages": 2,
}
# The large-batch mixed-precision proxy (ISSUE 20): the default model at
# 2x the default batch under the explicit mixed policy (bf16 compute +
# fp32 master weights + dynamic loss scaling) with LARS — the large-batch
# recipe's compiled step, including every op the policy adds (loss
# scale/unscale, the overflow reduction, the skip-select on params and
# opt state, the scale automaton). A retrace, added sync, or host stall
# in the mixed path fails tier-1 here instead of waiting for chip time.
LARGEBATCH_WORKLOAD = dict(WORKLOAD, batch=16, steps=6, dtype="bfloat16",
                           precision="mixed", optimizer="lars")
WORKLOADS = {
    "default": WORKLOAD,
    "zero2_overlap": dict(WORKLOAD, steps=6, dp=2,
                          optimizer_sharding="zero2"),
    "largebatch_bf16": LARGEBATCH_WORKLOAD,
    "pipeline_1f1b": PIPELINE_WORKLOAD,
    "serve_decode": SERVE_WORKLOAD,
    "serve_prefix_prefill": SERVE_PREFIX_WORKLOAD,
}
# LR-schedule horizon compiled into the step program; fixed so every
# measure() pass (and the AOT cache) shares one executable.
_TOTAL_STEPS = 64

DEFAULT_TOLERANCE = {
    # Normalized step time may grow to this multiple of baseline before
    # the gate fails. Generous: machine-speed variance is mostly divided
    # out by the calibration unit, but XLA-version jitter on a tiny
    # program is real; an injected regression worth catching (extra sync,
    # pipeline stall) shows up as 5-100x on a ~10 ms step.
    "step_hi": 3.0,
    # A phase's share of summed span time may grow this much (absolute)
    # before the gate fails — catches mix shifts (data_wait ballooning)
    # even when total step time hides inside step_hi.
    "share_abs": 0.25,
}


def calibrate(reps: int = 24, size: int = 192, best_of: int = 3) -> float:
    """Machine-speed unit: seconds for a fixed numpy matmul workload,
    best-of-``best_of`` (load spikes inflate single samples). The SAME
    unit divides both the baseline and the current measurement, so the
    checked-in ratio transfers across boxes of different speeds."""
    import numpy as np

    a = np.arange(size * size, dtype=np.float32).reshape(size, size) / size
    best = float("inf")
    for _ in range(best_of):
        t0 = time.perf_counter()
        b = a
        for _ in range(reps):
            b = b @ a
            b *= 1.0 / max(float(b[0, 0]), 1.0)
        best = min(best, time.perf_counter() - t0)
    return best


class ProxyRunner:
    """Builds the fixed proxy program once; each :meth:`measure` pass
    reuses the compiled step, so the self-test's injected-slowdown
    remeasure costs steps, not a recompile."""

    def __init__(self, workload: Optional[dict] = None):
        self.workload = dict(WORKLOAD, **(workload or {}))
        from distributeddeeplearning_tpu import data as datalib
        from distributeddeeplearning_tpu.config import (
            DataConfig, OptimizerConfig, ParallelConfig, PrecisionPolicy,
            TrainConfig)
        from distributeddeeplearning_tpu.models import model_spec
        from distributeddeeplearning_tpu.train import loop

        w = self.workload
        spec = model_spec(w["model"])
        # Optional workload keys: ``dp``/``pp`` widen the CPU mesh (need
        # --xla_force_host_platform_device_count >= dp*pp, as
        # tests/conftest.py and tools/perf_gate.py both force),
        # ``optimizer_sharding`` selects a ZeRO stage (the zero2_overlap
        # workload), ``pipeline_schedule``/``pipeline_virtual_stages``
        # pick the pipeline schedule (the pipeline_1f1b workload). Token
        # models get a synthetic token stream sized by ``seq_len``/
        # ``vocab_size`` instead of the image pipeline.
        if spec.input_kind == "tokens":
            data = DataConfig(
                synthetic=True, seq_len=w.get("seq_len", 16),
                vocab_size=w.get("vocab_size", 256))
        else:
            data = DataConfig(synthetic=True, image_size=w["image_size"],
                              num_classes=10)
        # Optional policy/optimizer keys (the largebatch_bf16 workload):
        # "precision" arms an explicit PrecisionPolicy, "optimizer" swaps
        # the update rule (LARS for the large-batch recipe).
        extra_kw: dict = {}
        if w.get("precision") == "mixed":
            extra_kw["precision"] = PrecisionPolicy.mixed()
        elif w.get("precision") == "fp32":
            extra_kw["precision"] = PrecisionPolicy.fp32()
        if w.get("optimizer"):
            extra_kw["optimizer"] = OptimizerConfig(
                name=w["optimizer"], schedule="constant")
        self.config = TrainConfig(
            model=w["model"], backend="cpu",
            global_batch_size=w["batch"], dtype=w["dtype"],
            seed=w["seed"], log_every=10**9, **extra_kw,
            optimizer_sharding=w.get("optimizer_sharding", "none"),
            pipeline_schedule=w.get("pipeline_schedule", "gpipe"),
            pipeline_virtual_stages=w.get("pipeline_virtual_stages", 1),
            data=data,
            parallel=ParallelConfig(data=w.get("dp", 1),
                                    pipeline=w.get("pp", 1)))
        (self.mesh, self.model, batch_shd, self.state, self.train_step,
         _sched, self.rng) = loop.build(self.config, _TOTAL_STEPS)
        self.source = datalib.make_source(self.config, spec.input_kind,
                                          batch_shd,
                                          objective=spec.objective)
        self._jax = __import__("jax")

    def measure(self, *, steps: Optional[int] = None,
                warmup: Optional[int] = None,
                inject_sleep_s: float = 0.0) -> dict:
        """One measurement pass: per-step wall times (median over the
        timed steps) + phase breakdown, normalized by a fresh calibration
        unit. ``inject_sleep_s`` sleeps inside the traced ``data_wait``
        phase each timed step — the deliberate slowdown the gate's
        self-test must catch."""
        jax = self._jax
        steps = self.workload["steps"] if steps is None else steps
        warmup = self.workload["warmup"] if warmup is None else warmup
        state, rng = self.state, self.rng
        metrics = None
        i = 0
        for _ in range(warmup):  # compile + cache warmup, never timed
            state, metrics = self.train_step(state, self.source.batch(i),
                                             rng)
            i += 1
        if metrics is not None:
            jax.device_get(metrics)
        # Fresh telemetry per pass: warmup (compile) spans must not
        # pollute the phase mix the gate compares.
        tele = telemetry.Telemetry(enabled=True)
        per_step: list[float] = []
        for _ in range(steps):
            t0 = telemetry.now_s()
            with tele.span("data_wait", step=i):
                batch = self.source.batch(i)
                if inject_sleep_s > 0:
                    time.sleep(inject_sleep_s)
            t1 = telemetry.now_s()
            state, metrics = self.train_step(state, batch, rng)
            t2 = telemetry.now_s()
            tele.record_span("dispatch", t1, t2, step=i)
            # Per-step fetch: a true execution barrier, so each wall
            # sample covers exactly one step's device work (the
            # production loop pipelines; the gate wants determinism).
            with tele.span("fetch_barrier", step=i):
                jax.device_get(metrics)
            per_step.append(telemetry.now_s() - t0)
            i += 1
        self.state = state  # reuse across passes; shapes never change
        phases = telemetry.phase_totals(tele.snapshot())
        span_total = sum(p["total_ms"] for p in phases.values()) or 1.0
        calib = calibrate()
        step_s = statistics.median(per_step)
        return {
            "schema_version": SCHEMA_VERSION,
            "workload": dict(self.workload,
                             **({"steps": steps, "warmup": warmup})),
            "step_time_ms": round(step_s * 1e3, 3),
            "calib_unit_ms": round(calib * 1e3, 3),
            "normalized_step": round(step_s / calib, 4),
            "phase_share": {name: round(p["total_ms"] / span_total, 4)
                            for name, p in phases.items()},
            "phases": phases,
            "injected_sleep_s": inject_sleep_s,
        }


class ServeProxyRunner:
    """Serve-engine proxies for serve/engine.py. Builds ONE tiny Engine
    (compile-cache off — the gate times the build in front of it, never a
    deserialized one); what a timed step is depends on the workload kind:

    - ``serve_decode``: every slot held live by a long request — each
      timed ``Engine.step()`` is one static-shape decode advance, the
      per-token serving cost continuous batching pays.
    - ``serve_prefix_prefill``: the radix tree primed with a shared head
      — each timed step is one admission that HITS the prefix cache
      (tree walk + shared-page mapping + suffix block prefill + retire),
      the admission cost the fast path is supposed to have shrunk.

    Same result schema as :class:`ProxyRunner`, so :func:`compare` and the
    baseline file work unchanged."""

    def __init__(self, workload: Optional[dict] = None):
        self.workload = dict(SERVE_WORKLOAD, **(workload or {}))
        from distributeddeeplearning_tpu.serve.engine import (Engine,
                                                              ServeConfig)

        w = self.workload
        self.config = ServeConfig(
            model=w["model"], vocab_size=w["vocab_size"], dtype=w["dtype"],
            max_slots=w["max_slots"], page_size=w["page_size"],
            num_pages=w["num_pages"],
            max_pages_per_slot=w["max_pages_per_slot"],
            prefill_buckets=tuple(w["prefill_buckets"]), seed=w["seed"],
            prefix_cache=bool(w.get("prefix_cache", False)),
            compile_cache_dir="off")
        self.engine = Engine(self.config)
        self.engine.warmup()

    def _timed_steps(self, steps, tele, inject_sleep_s):
        """Time ``steps`` decode advances; the caller has filled every
        slot so each one is a pure static-shape decode step."""
        eng = self.engine
        per_step: list[float] = []
        for k in range(steps):
            t0 = telemetry.now_s()
            with tele.span("host_stall", step=k):
                if inject_sleep_s > 0:
                    time.sleep(inject_sleep_s)
            with tele.span("decode_step", step=k):
                eng.step()  # np.asarray on the emitted tokens is the sync
            per_step.append(telemetry.now_s() - t0)
        return per_step

    def measure(self, *, steps: Optional[int] = None,
                warmup: Optional[int] = None,
                inject_sleep_s: float = 0.0) -> dict:
        w = self.workload
        steps = w["steps"] if steps is None else steps
        warmup = w["warmup"] if warmup is None else warmup
        eng = self.engine
        if not eng.idle:  # leftovers from a previous pass
            eng.run_until_idle()
        if w.get("kind") == "serve_prefix_prefill":
            head_len = int(w["shared_prefix_len"])
            tail_len = int(w["tail_len"])
            head = [1 + (i % (w["vocab_size"] - 2))
                    for i in range(head_len)]
            # Prime the radix tree (one full prefill), then queue one
            # max_new=1 request per step: each admits, hits the shared
            # head, block-prefills only the tail, and retires in-step.
            eng.submit(head + [2] * tail_len, max_new_tokens=1)
            eng.run_until_idle()
            hits_before = eng.prefix_hits

            def one_admit(k: int) -> None:
                # Submit-then-step so each step admits exactly ONE
                # prefix-hit request (and retires it: max_new=1).
                tail = [2 + ((k + j) % (w["vocab_size"] - 3))
                        for j in range(tail_len)]
                eng.submit(head + tail, max_new_tokens=1)
                eng.step()

            for k in range(warmup):
                one_admit(k)
            tele = telemetry.Telemetry(enabled=True)
            per_step = []
            for k in range(steps):
                tail = [2 + ((warmup + k + j) % (w["vocab_size"] - 3))
                        for j in range(tail_len)]
                eng.submit(head + tail, max_new_tokens=1)
                t0 = telemetry.now_s()
                with tele.span("host_stall", step=k):
                    if inject_sleep_s > 0:
                        time.sleep(inject_sleep_s)
                with tele.span("prefix_admit", step=k):
                    eng.step()
                per_step.append(telemetry.now_s() - t0)
            eng.run_until_idle()
            if eng.prefix_hits - hits_before < warmup + steps:
                raise RuntimeError(
                    f"serve_prefix_prefill proxy mis-primed: only "
                    f"{eng.prefix_hits - hits_before} prefix hits for "
                    f"{warmup + steps} admissions — the gate would be "
                    f"timing cold prefills, not the fast path")
        else:
            # One request per slot, sized to outlive warmup + timed steps
            # (admission prefill emits token 1; each step emits one more).
            prompt_len = min(4, max(self.config.prefill_buckets))
            max_new = warmup + steps + 1
            if prompt_len + max_new > self.config.slot_capacity:
                raise ValueError(
                    f"serve_decode workload needs {prompt_len + max_new} "
                    f"tokens/slot but slot capacity is "
                    f"{self.config.slot_capacity}; shrink steps or grow "
                    f"pages")
            for s in range(self.config.max_slots):
                eng.submit([1 + s] * prompt_len, max_new_tokens=max_new)
            for _ in range(warmup):
                eng.step()
            assert eng.num_live == self.config.max_slots
            tele = telemetry.Telemetry(enabled=True)
            per_step = self._timed_steps(steps, tele, inject_sleep_s)
            eng.run_until_idle()
        phases = telemetry.phase_totals(tele.snapshot())
        span_total = sum(p["total_ms"] for p in phases.values()) or 1.0
        calib = calibrate()
        step_s = statistics.median(per_step)
        return {
            "schema_version": SCHEMA_VERSION,
            "workload": dict(self.workload,
                             **({"steps": steps, "warmup": warmup})),
            "step_time_ms": round(step_s * 1e3, 3),
            "calib_unit_ms": round(calib * 1e3, 3),
            "normalized_step": round(step_s / calib, 4),
            "phase_share": {name: round(p["total_ms"] / span_total, 4)
                            for name, p in phases.items()},
            "phases": phases,
            "injected_sleep_s": inject_sleep_s,
        }


def runner_for(workload: str = "default"):
    """The right proxy runner for a named gate workload: training loop by
    default, the serve engine for kind="serve_decode" /
    "serve_prefix_prefill" entries."""
    if workload == "default":
        return ProxyRunner()
    w = WORKLOADS[workload]
    if w.get("kind") in ("serve_decode", "serve_prefix_prefill"):
        return ServeProxyRunner(w)
    return ProxyRunner(w)


def measure(runner: Optional[ProxyRunner] = None, **kw) -> dict:
    return (runner or ProxyRunner()).measure(**kw)


def load_baseline(path: Optional[str] = None,
                  name: str = "default") -> Optional[dict]:
    """Baseline for a named gate workload: the file's top level for
    "default", the matching ``extras`` entry otherwise (None = not yet
    recalibrated for that workload)."""
    try:
        with open(path or BASELINE_PATH) as fh:
            obj = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(obj, dict):
        return None
    if name == "default":
        return obj
    extra = (obj.get("extras") or {}).get(name)
    return extra if isinstance(extra, dict) else None


def compare(baseline: Optional[dict], current: dict,
            tolerance: Optional[dict] = None) -> list[str]:
    """Violations of ``current`` against ``baseline`` (empty = gate
    passes). Tolerances come from the baseline file unless overridden —
    loosening the gate is a reviewed diff, not a test-local constant."""
    if not baseline:
        return ["no baseline: run `python tools/perf_gate.py "
                "--recalibrate [--workload NAME]` and commit "
                "perf_baselines.json"]
    tol = dict(DEFAULT_TOLERANCE, **(baseline.get("tolerance") or {}),
               **(tolerance or {}))
    out = []
    base_norm = float(baseline.get("normalized_step") or 0.0)
    cur_norm = float(current.get("normalized_step") or 0.0)
    base_ms = float(baseline.get("step_time_ms") or 0.0)
    cur_ms = float(current.get("step_time_ms") or 0.0)
    # Fail only when BOTH views regress past the band: the normalized
    # ratio forgives a slower machine (calibration divides speed out) and
    # the raw ratio forgives a loaded one (contention inflates the
    # calibration unit too) — a real regression (injected sleep, added
    # sync) inflates both by the same large factor.
    if base_norm > 0 and base_ms > 0:
        ratio = min(cur_norm / base_norm, cur_ms / base_ms)
        if ratio > float(tol["step_hi"]):
            out.append(
                f"step-time regression: {ratio:.1f}x baseline > "
                f"{tol['step_hi']:g}x tolerance (normalized "
                f"{cur_norm:.2f} vs {base_norm:.2f}; raw {cur_ms:g} ms "
                f"vs {base_ms:g} ms)")
    base_share = baseline.get("phase_share") or {}
    for phase, share in (current.get("phase_share") or {}).items():
        grew = float(share) - float(base_share.get(phase, 0.0))
        if grew > float(tol["share_abs"]):
            out.append(
                f"phase-mix regression: {phase!r} share "
                f"{float(share):.0%} grew {grew:+.0%} over baseline "
                f"{float(base_share.get(phase, 0.0)):.0%} "
                f"(> {float(tol['share_abs']):.0%} tolerance)")
    return out


def _write_sidecar(result: dict) -> None:
    # Atomic + enveloped via sidecars.write (never raises): the sidecar
    # is for doctor.py; losing it costs no gate run.
    sidecars.write(LAST_RESULT_PATH, result)


def check(baseline_path: Optional[str] = None,
          runner: Optional[ProxyRunner] = None,
          inject_sleep_s: float = 0.0,
          write_sidecar: bool = True,
          workload: str = "default") -> dict:
    """Measure the named proxy workload and gate it against its checked-in
    baseline. Returns ``{ok, violations, current, baseline}``; the default
    workload also drops the result into ``.cache/perf_gate_last.json`` for
    tools/doctor.py (extras never overwrite the headline sidecar)."""
    baseline = load_baseline(baseline_path, name=workload)
    if runner is None:
        runner = runner_for(workload)
    current = measure(runner, inject_sleep_s=inject_sleep_s)
    violations = compare(baseline, current)
    result: dict[str, Any] = {
        "ok": not violations,
        "violations": violations,
        "workload_name": workload,
        "current": current,
        "baseline_normalized_step": (baseline or {}).get("normalized_step"),
        "baseline_recorded": (baseline or {}).get("recorded"),
        "checked_at": time.strftime("%Y-%m-%d %H:%M:%S"),
    }
    rev = perf_report.git_rev()
    if rev:
        result["git_rev"] = rev
    if write_sidecar and inject_sleep_s == 0 and workload == "default":
        # Never persist a deliberately-slowed self-test pass as "the
        # last gate result" — doctor would report a phantom regression.
        _write_sidecar(result)
    return result


def recalibrate(path: Optional[str] = None,
                runner: Optional[ProxyRunner] = None,
                passes: int = 3,
                workload: str = "default") -> dict:
    """Measure ``passes`` times, keep the fastest pass (baseline = the
    machine's honest capability, not its worst moment), and write the
    baseline file. Recalibrating "default" rewrites the top level but
    PRESERVES any ``extras`` entries; recalibrating a named extra rewrites
    only its entry under ``extras``. Returns the baseline entry written."""
    r = runner or runner_for(workload)
    best = None
    for _ in range(max(passes, 1)):
        cur = r.measure()
        if best is None or cur["normalized_step"] < best["normalized_step"]:
            best = cur
    entry = {
        "schema_version": SCHEMA_VERSION,
        "workload": best["workload"],
        "step_time_ms": best["step_time_ms"],
        "calib_unit_ms": best["calib_unit_ms"],
        "normalized_step": best["normalized_step"],
        "phase_share": best["phase_share"],
        "tolerance": dict(DEFAULT_TOLERANCE),
        "recorded": {
            "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S"),
            "git_rev": perf_report.git_rev(),
            "backend": perf_report.backend_identity(),
        },
    }
    out = path or BASELINE_PATH
    existing = None
    try:
        with open(out) as fh:
            existing = json.load(fh)
    except (OSError, ValueError):
        pass
    if not isinstance(existing, dict):
        existing = None
    if workload == "default":
        baseline = dict(entry)
        if existing and isinstance(existing.get("extras"), dict):
            baseline["extras"] = existing["extras"]
    else:
        if existing is None:
            raise ValueError(
                f"cannot recalibrate extra workload {workload!r} into a "
                f"missing/invalid baseline file {out!r}: recalibrate the "
                f"default workload first")
        baseline = existing
        baseline.setdefault("extras", {})[workload] = entry
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(baseline, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, out)
    return entry


def status(baseline_path: Optional[str] = None) -> dict:
    """Gate status WITHOUT running the proxy — what doctor.py prints:
    baseline presence/age + the last recorded check result."""
    baseline = load_baseline(baseline_path)
    out: dict[str, Any] = {"baseline_present": baseline is not None}
    if baseline:
        out["baseline_normalized_step"] = baseline.get("normalized_step")
        out["baseline_recorded"] = baseline.get("recorded", {})
        out["tolerance"] = baseline.get("tolerance", {})
        out["extra_baselines"] = sorted((baseline.get("extras") or {}))
    last = sidecars.read(LAST_RESULT_PATH)
    out["last_check"] = ({
        k: last.get(k) for k in ("ok", "violations", "checked_at",
                                 "git_rev")}
        if last is not None else None)
    return out
