"""One self-describing record schema for every perf number this repo emits.

BENCH_r01-r05 showed where the perf story breaks: 2 of 5 driver rounds
errored on backend unavailability, and the surviving "current" number was a
cached measurement re-reported for days (``stale_age_s`` 92824 in r05) with
nothing in the record saying so loudly. The fix is not better luck with the
tunnel — it is records that carry their own evidence. Every measurement
surface (``bench.py`` metric lines, ``train/loop.py`` run summaries,
``tools/summarize_trace.py`` analyses) emits into the schema defined here:

- ``provenance`` — exactly one of :data:`PROVENANCE_STATES`:

  * ``fresh``   — measured on a live backend by THIS invocation;
  * ``stale``   — a cached prior measurement re-surfaced within
    :data:`DEFAULT_MAX_STALE_AGE_S` (age attached);
  * ``expired`` — a cached measurement older than the cap: context only,
    never comparable, excluded from ``vs_baseline``;
  * ``error``   — no measurement; the record explains why.

- ``backend`` — platform/device_kind/device+process counts the number was
  measured on (a v5e-8 row and a CPU smoke row must never be conflated);
- ``attempts`` — the retry history that produced (or failed to produce)
  the number, so "one clean attempt" and "landed on attempt 3 of a flaky
  tunnel" read differently;
- ``git_rev`` + ``config_fingerprint`` (perf/aot.py) — which build and
  which compiled-program-shaping config the number belongs to;
- roofline accounting via ``models/flops.py`` — ``pct_of_peak`` makes
  numbers comparable across meshes the way the large-batch ResNet
  literature reports them (PAPERS.md: arXiv:1711.04325): analytic
  train FLOPs/example x rate / bf16 peak.

Everything here is annotation, never measurement: every helper is
no-raise (a missing git dir or an unimportable jax must not cost a
throughput number) and pure-stdlib unless a guarded import succeeds.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

SCHEMA_VERSION = 1

PROVENANCE_STATES = ("fresh", "stale", "expired", "error")

# Past this age a cached number stops being "the current number reported
# late" and becomes history: demoted to ``expired``, excluded from
# vs_baseline comparisons (ISSUE 6 satellite: r05 re-reported a 92824 s
# old cache as current).
DEFAULT_MAX_STALE_AGE_S = 24 * 3600.0

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def git_rev(repo_root: Optional[str] = None) -> Optional[str]:
    """Short commit hash of HEAD, read straight from ``.git`` (no
    subprocess — this runs inside bench children where every fork counts).
    None when the tree is not a git checkout or HEAD is unreadable."""
    root = repo_root or _REPO_ROOT
    git = os.path.join(root, ".git")
    try:
        with open(os.path.join(git, "HEAD")) as fh:
            head = fh.read().strip()
        if not head.startswith("ref:"):
            return head[:12] or None  # detached HEAD: the hash itself
        ref = head.split(None, 1)[1]
        ref_path = os.path.join(git, *ref.split("/"))
        if os.path.exists(ref_path):
            with open(ref_path) as fh:
                return fh.read().strip()[:12] or None
        with open(os.path.join(git, "packed-refs")) as fh:
            for line in fh:
                parts = line.split()
                if len(parts) == 2 and parts[1] == ref:
                    return parts[0][:12]
    except OSError:
        pass
    return None


def backend_identity() -> Optional[dict]:
    """Which hardware answered: platform, device_kind, device/process
    counts. Guarded — returns None wherever jax (or the backend) is
    unavailable, because identity annotation must never initialize or
    crash a backend on its own."""
    try:
        import jax
        dev = jax.devices()[0]
        return {
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", "?"),
            "device_count": jax.device_count(),
            "process_count": jax.process_count(),
        }
    except Exception:
        return None


def roofline(value: Optional[float], model: str, *,
             seq_len: Optional[int] = None, mlm_positions: int = 0,
             device_kind: Optional[str] = None,
             compute_dtype: str = "bfloat16") -> dict:
    """Roofline fields for a rate of ``value`` examples/sec/chip:
    ``tflops_per_sec`` (analytic model FLOPs actually sustained) and
    ``pct_of_peak`` (vs the chip's spec peak AT ``compute_dtype`` — the
    %-of-peak axis the large-batch ResNet papers compare on; an fp32 arm
    scores against the fp32 roof, a mixed arm against bf16, so the two
    arms measure distance from their own speed of light). Unknown model
    or chip omits the respective field; never raises."""
    out: dict = {}
    if value is None:
        return out
    try:
        from distributeddeeplearning_tpu.models import flops as flopslib
        per_ex = flopslib.train_flops_per_example(
            model, seq_len=seq_len, mlm_positions=mlm_positions)
        if per_ex is None:
            return out
        out["tflops_per_sec"] = round(value * per_ex / 1e12, 2)
        if device_kind:
            peak = flopslib.peak_flops(device_kind, compute_dtype)
            if peak:
                out["pct_of_peak"] = round(100.0 * value * per_ex / peak, 1)
                out["peak_tflops"] = round(peak / 1e12, 0)
                out["peak_dtype"] = compute_dtype
                if compute_dtype == "bfloat16":
                    # Back-compat alias: pre-policy records carried the
                    # bf16 roof under this name.
                    out["bf16_peak_tflops"] = out["peak_tflops"]
    except Exception:
        return {}
    return out


def classify_age(age_s: Optional[float],
                 max_stale_age_s: float = DEFAULT_MAX_STALE_AGE_S) -> str:
    """``stale`` while a cached number is young enough to still be worth
    reporting next to an error, ``expired`` past the cap. A cached record
    is NEVER ``fresh`` — freshness belongs only to this invocation's own
    measurements, whatever the age says."""
    if age_s is None:
        # Unknown age is indistinguishable from arbitrarily old: the
        # honest label is the conservative one.
        return "expired"
    return "stale" if float(age_s) <= float(max_stale_age_s) else "expired"


def stale_record(prior: dict, age_s: Optional[float],
                 max_stale_age_s: float = DEFAULT_MAX_STALE_AGE_S) -> dict:
    """Label a cached last-good record for embedding into an error record:
    provenance stale/expired by age, and an expired record loses its
    ``vs_baseline`` (a week-old number must not keep scoring against the
    target as if it were current)."""
    rec = dict(prior)
    rec["provenance"] = classify_age(age_s, max_stale_age_s)
    if age_s is not None:
        rec["stale_age_s"] = int(age_s)
    if rec["provenance"] == "expired":
        rec.pop("vs_baseline", None)
    return rec


def measurement_age_s(measured_at: Optional[str],
                      now: Optional[float] = None) -> Optional[float]:
    """Seconds since a ``measured_at`` stamp in the last-good table's
    '%Y-%m-%d %H:%M:%S' format; None when absent/unparseable."""
    if not measured_at:
        return None
    try:
        measured = time.mktime(time.strptime(measured_at,
                                             "%Y-%m-%d %H:%M:%S"))
    except (ValueError, TypeError, OverflowError):
        return None
    return max(0.0, (time.time() if now is None else now) - measured)


def annotate(rec: dict, *, provenance: str,
             config: Any = None, total_steps: Optional[int] = None,
             attempts: Optional[list] = None,
             stale_age_s: Optional[float] = None,
             with_backend: bool = True) -> dict:
    """Stamp a record with the schema's provenance block (in place, and
    returned). ``config`` (a TrainConfig) adds the perf/aot.py
    config_fingerprint so the number is tied to the compiled program it
    measured. ``with_backend=False`` for pure-host analyses (trace
    summaries) that must not touch jax."""
    if provenance not in PROVENANCE_STATES:
        raise ValueError(f"provenance {provenance!r} not in "
                         f"{PROVENANCE_STATES}")
    rec["schema_version"] = SCHEMA_VERSION
    rec["provenance"] = provenance
    rev = git_rev()
    if rev:
        rec["git_rev"] = rev
    if with_backend:
        backend = backend_identity()
        if backend:
            rec["backend"] = backend
    if attempts is not None:
        rec["attempts"] = list(attempts)
    if stale_age_s is not None:
        rec["stale_age_s"] = int(stale_age_s)
    if config is not None:
        try:
            from distributeddeeplearning_tpu.perf import aot as aotlib
            rec["config_fingerprint"] = aotlib.config_fingerprint(
                config, total_steps=total_steps)
        except Exception:
            pass  # fingerprint is annotation; its absence is visible anyway
        try:
            # Precision-policy + batch-ramp provenance: every config-tied
            # perf record names the policy and ramp it ran under, so an
            # fp32 and a mixed arm (or a ramped and an unramped run) can
            # never be conflated — and never share a last-good baseline
            # entry, since both fields feed the fingerprint above.
            from distributeddeeplearning_tpu.config import resolve_precision
            from distributeddeeplearning_tpu.train import optim as optimlib
            rec.setdefault("precision",
                           resolve_precision(config).describe())
            rec.setdefault("batch_ramp", optimlib.ramp_describe(config))
        except Exception:
            pass  # annotation only, like the fingerprint
    if provenance != "error":
        schedules = lint_schedules()
        if schedules:
            rec.setdefault("collective_schedules", schedules)
    return rec


# Schedule fingerprints older than this describe some other build, not
# the one being measured; the chip window runs ddl_lint minutes before
# bench, so a day is generous without re-surfacing ancient runs.
LINT_SCHEDULES_MAX_AGE_S = 24 * 3600.0


def lint_schedules() -> Optional[dict]:
    """Collective-schedule fingerprints from the last ddl_lint run
    (tools/ddl_lint.py's ``last_ddl_lint`` sidecar) — attached to perf
    records so a throughput number names the collective schedule it was
    measured under. ``None`` when absent, stale, or unreadable (pure
    annotation, never a failure)."""
    try:
        from distributeddeeplearning_tpu.observability import sidecars
        side = sidecars.read("last_ddl_lint")
        age = sidecars.age_s(side)
        schedules = (side or {}).get("collective_schedules")
        if (isinstance(schedules, dict) and schedules
                and age is not None and age < LINT_SCHEDULES_MAX_AGE_S):
            return dict(schedules)
    except Exception:  # noqa: BLE001 — annotation only
        pass
    return None


def validate(rec: dict) -> list[str]:
    """Schema problems in a record (empty list = conforming). The rules
    tests pin so no surface can quietly drift:

    - provenance present and one of :data:`PROVENANCE_STATES`;
    - ``fresh`` requires a real value and forbids ``stale_age_s`` — a
      number served from any cache is by definition not fresh;
    - ``error`` requires a null value (an error that reports a value is a
      mislabeled measurement) and an ``error`` message;
    - ``stale``/``expired`` require the age that justifies the label, and
      ``expired`` must not carry ``vs_baseline``.
    """
    problems = []
    prov = rec.get("provenance")
    if prov not in PROVENANCE_STATES:
        problems.append(f"provenance {prov!r} not in {PROVENANCE_STATES}")
        return problems
    if prov == "fresh":
        # Bench records carry an explicit ``value`` (null on failure);
        # run summaries measure through other keys and omit it entirely.
        if "value" in rec and rec["value"] is None:
            problems.append("fresh record with null value")
        if rec.get("stale_age_s") is not None:
            problems.append("fresh record carrying stale_age_s — a cached "
                            "number must be labeled stale/expired")
    elif prov == "error":
        if rec.get("value") is not None:
            problems.append("error record carrying a value")
        if not rec.get("error"):
            problems.append("error record without an error message")
    else:  # stale / expired
        if rec.get("stale_age_s") is None:
            problems.append(f"{prov} record without stale_age_s")
        if prov == "expired" and rec.get("vs_baseline") is not None:
            problems.append("expired record still scoring vs_baseline")
    return problems


def dumps(rec: dict) -> str:
    return json.dumps(rec)
