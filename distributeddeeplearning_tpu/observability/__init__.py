"""Unified observability: telemetry, flight record, metrics, health.

Modules, split by import weight:

- :mod:`.telemetry` — thread-safe span/counter/gauge registry over a
  bounded ring buffer, exportable as Chrome-trace JSON. Pure stdlib, so
  the jax-free launcher and the data/robustness layers import it freely.
- :mod:`.flight` — crash-surviving fsync'd JSONL event log (the flight
  recorder) with a shared run/attempt/host identity scheme. Pure stdlib.
- :mod:`.metrics` — cross-host gauge registry with Prometheus-text and
  JSON-snapshot export. Pure stdlib.
- :mod:`.anomaly` — online detector (loss spikes, grad-norm drift,
  throughput collapse, straggler trending) over the log-cadence metric
  stream. Pure stdlib.
- :mod:`.sidecars` — the one read/write helper behind every
  ``.cache/*.json`` run sidecar. Pure stdlib.
- :mod:`.health` — heartbeat files (child-side writer, launcher-side
  staleness check). Pure stdlib.
- :mod:`.straggler` — cross-host step-time/data-wait aggregation on log
  cadence (imports jax; the train loop is its only consumer).
"""

from distributeddeeplearning_tpu.observability import (
    anomaly, flight, health, metrics, sidecars, telemetry)

__all__ = ["anomaly", "flight", "health", "metrics", "sidecars",
           "telemetry"]
