"""Unified observability: always-on phase telemetry, heartbeat health.

Three modules, split by import weight:

- :mod:`.telemetry` — thread-safe span/counter/gauge registry over a
  bounded ring buffer, exportable as Chrome-trace JSON. Pure stdlib, so
  the jax-free launcher and the data/robustness layers import it freely.
- :mod:`.health` — heartbeat files (child-side writer, launcher-side
  staleness check). Pure stdlib for the same reason.
- :mod:`.straggler` — cross-host step-time/data-wait aggregation on log
  cadence (imports jax; the train loop is its only consumer).
"""

from distributeddeeplearning_tpu.observability import health, telemetry

__all__ = ["health", "telemetry"]
