"""Cross-host straggler detection on log cadence.

A multi-host data-parallel step runs at the speed of its slowest host, but
per-host symptoms (slow NIC, contended input volume, thermal throttling)
are invisible in chief-only metrics — the MPI characterization work
(PAPERS.md: arXiv:1810.11112) shows imbalance surfacing exactly as
collective wait time. On every log-cadence step each host contributes its
(step_time, data_wait) means since the last log via one small
``process_allgather``; the chief logs min/max/mean skew and names the
slowest host when it exceeds ``threshold`` x the mean.

The allgather doubles as a cross-host sync point, so its cost is bounded
by the skew it measures; single-process jobs build no monitor at all.
"""

from __future__ import annotations

import sys
from typing import Optional

from distributeddeeplearning_tpu.observability import telemetry


class StragglerMonitor:
    """Built once per run; ``collect`` must be called by EVERY process at
    the same steps (it is a collective)."""

    def __init__(self, threshold: float, num_processes: int):
        self.threshold = float(threshold)
        self.num_processes = num_processes

    def collect(self, step: int, step_time_s: float,
                data_wait_s: float) -> dict:
        """Allgather this host's phase times; returns the skew fields to
        fold into the chief's log record (identical on every process)."""
        import jax
        import numpy as np
        from jax.experimental import multihost_utils

        arr = multihost_utils.process_allgather(
            np.asarray([step_time_s, data_wait_s], np.float64))
        arr = np.asarray(arr).reshape(self.num_processes, 2)
        st, dw = arr[:, 0], arr[:, 1]
        mean = float(st.mean())
        slowest = int(st.argmax())
        record = {
            "host_count": self.num_processes,
            "host_step_time_min": round(float(st.min()), 6),
            "host_step_time_max": round(float(st.max()), 6),
            "host_step_time_mean": round(mean, 6),
            "host_data_wait_max": round(float(dw.max()), 6),
        }
        if mean > 0 and float(st.max()) > self.threshold * mean:
            record["straggler_host"] = slowest
            telemetry.get().instant(
                "straggler", step=step, host=slowest,
                step_time_s=round(float(st.max()), 6),
                mean_s=round(mean, 6))
            if jax.process_index() == 0:
                print(f"# straggler: host {slowest} step_time "
                      f"{st.max():.4f}s > {self.threshold:.2f}x mean "
                      f"{mean:.4f}s at step {step} "
                      f"(data_wait {dw[slowest]:.4f}s)",
                      file=sys.stderr, flush=True)
        return record


def make_monitor(config) -> Optional[StragglerMonitor]:
    """A monitor when the job is multi-process and the threshold is
    positive (``straggler_threshold=0`` opts out), else None — the loop
    then runs zero cross-host code."""
    import jax

    nproc = jax.process_count()
    if nproc <= 1:
        return None
    threshold = float(getattr(config, "straggler_threshold", 1.5) or 0.0)
    if threshold <= 0:
        return None
    return StragglerMonitor(threshold, nproc)
