"""Cross-host straggler detection on log cadence.

A multi-host data-parallel step runs at the speed of its slowest host, but
per-host symptoms (slow NIC, contended input volume, thermal throttling)
are invisible in chief-only metrics — the MPI characterization work
(PAPERS.md: arXiv:1810.11112) shows imbalance surfacing exactly as
collective wait time. On every log-cadence step each host contributes its
(step_time, data_wait) means since the last log via one small
``process_allgather``; the chief logs min/max/mean skew and names the
slowest host when it exceeds ``threshold`` x the mean.

The allgather doubles as a cross-host sync point, so its cost is bounded
by the skew it measures; single-process jobs build no monitor at all.
"""

from __future__ import annotations

import sys
from typing import Optional

from distributeddeeplearning_tpu.observability import metrics, telemetry


class StragglerMonitor:
    """Built once per run; ``collect`` must be called by EVERY process at
    the same steps (it is a collective)."""

    def __init__(self, threshold: float, num_processes: int):
        self.threshold = float(threshold)
        self.num_processes = num_processes

    def _warn(self, name: str, *, step: int, host: int,
              chief: bool, message: str, **args) -> None:
        """One path for every straggler verdict: a telemetry instant (so
        the warning lands on the merged timeline next to the step spans
        it explains) plus a chief-only stderr line. The skew *ratios*
        are observed into the metrics registry unconditionally in
        ``collect`` — trend tooling sees skew building before it crosses
        the threshold's step function; this path fires only past it."""
        telemetry.get().instant(name, step=step, host=host, **args)
        if chief:
            print(f"# {message}", file=sys.stderr, flush=True)

    def collect(self, step: int, step_time_s: float,
                data_wait_s: float,
                compile_s: Optional[float] = None) -> dict:
        """Allgather this host's phase times; returns the skew fields to
        fold into the chief's log record (identical on every process).

        ``compile_s`` is passed exactly once per run — on the first log
        boundary after the step program is built (train/loop.py) — and
        widens the payload on EVERY host at that step (the compile happens
        at the same step everywhere, so the collective shapes agree). It
        surfaces hosts that straggle in *compile* (cold cache on one host,
        slow persistent-cache volume) the same way step-time skew is
        surfaced.
        """
        import jax
        import numpy as np
        from jax.experimental import multihost_utils

        payload = [step_time_s, data_wait_s]
        if compile_s is not None:
            payload.append(compile_s)
        width = len(payload)
        arr = multihost_utils.process_allgather(
            np.asarray(payload, np.float64))
        arr = np.asarray(arr).reshape(self.num_processes, width)
        st, dw = arr[:, 0], arr[:, 1]
        mean = float(st.mean())
        slowest = int(st.argmax())
        record = {
            "host_count": self.num_processes,
            "host_step_time_min": round(float(st.min()), 6),
            "host_step_time_max": round(float(st.max()), 6),
            "host_step_time_mean": round(mean, 6),
            "host_data_wait_max": round(float(dw.max()), 6),
        }
        chief = jax.process_index() == 0
        if compile_s is not None:
            cp = arr[:, 2]
            cmean = float(cp.mean())
            record["host_compile_min"] = round(float(cp.min()), 6)
            record["host_compile_max"] = round(float(cp.max()), 6)
            record["host_compile_mean"] = round(cmean, 6)
            slow_cp = int(cp.argmax())
            cratio = float(cp.max()) / cmean if cmean > 0 else 1.0
            metrics.get().observe("straggler_compile_ratio", cratio,
                                  step=step, host=slow_cp)
            # Compile skew matters above noise level only: sub-second
            # "compiles" are warm AOT loads everywhere.
            if cmean > 0.5 and cratio > self.threshold:
                record["compile_straggler_host"] = slow_cp
                self._warn(
                    "compile_straggler", step=step, host=slow_cp,
                    chief=chief,
                    compile_s=round(float(cp.max()), 6),
                    mean_s=round(cmean, 6),
                    message=(f"compile straggler: host {slow_cp} compiled "
                             f"in {cp.max():.1f}s > {self.threshold:.2f}x "
                             f"mean {cmean:.1f}s (cold cache on one "
                             f"host?)"))
        ratio = float(st.max()) / mean if mean > 0 else 1.0
        metrics.get().observe("straggler_step_time_ratio", ratio,
                              step=step, host=slowest)
        if mean > 0 and ratio > self.threshold:
            record["straggler_host"] = slowest
            self._warn(
                "straggler", step=step, host=slowest, chief=chief,
                step_time_s=round(float(st.max()), 6),
                mean_s=round(mean, 6),
                data_wait_s=round(float(dw[slowest]), 6),
                message=(f"straggler: host {slowest} step_time "
                         f"{st.max():.4f}s > {self.threshold:.2f}x mean "
                         f"{mean:.4f}s at step {step} "
                         f"(data_wait {dw[slowest]:.4f}s)"))
        return record


def make_monitor(config) -> Optional[StragglerMonitor]:
    """A monitor when the job is multi-process and the threshold is
    positive (``straggler_threshold=0`` opts out), else None — the loop
    then runs zero cross-host code."""
    import jax

    nproc = jax.process_count()
    if nproc <= 1:
        return None
    threshold = float(getattr(config, "straggler_threshold", 1.5) or 0.0)
    if threshold <= 0:
        return None
    return StragglerMonitor(threshold, nproc)
