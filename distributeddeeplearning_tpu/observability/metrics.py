"""Cross-host metrics registry: per-host gauges → run-level series.

`utils/logging.py` already computes the interesting gauges on the log
cadence (step time, loss, throughput) and `observability/straggler.py`
already allgathers the per-host view onto the chief. What was missing is
a place where those observations *accumulate across the run* and an
export format an external scraper understands. This module is that
place:

- ``observe(name, value, host=...)`` keeps the latest value per
  ``(metric, host)`` and a bounded per-metric series of
  ``(step, value)`` samples (run-level = host-aggregated view);
- ``prometheus_text()`` renders the current state in Prometheus text
  exposition format (``# TYPE`` + ``ddl_<metric>{run=...,host=...}``
  gauge lines) — point a node-exporter textfile collector or a sidecar
  scraper at the file ``write_prometheus()`` refreshes;
- ``write_snapshot()`` publishes a periodic JSON aggregate (min / max /
  mean / last per metric, plus the recent series) for tools that want
  history without a Prometheus stack — ``tools/postmortem.py`` folds it
  into incident reports.

The registry is plain host-side Python — no device fetches, no
collectives of its own. Cross-host values arrive through whatever the
caller already gathered (the straggler monitor's per-host step times,
for example); single-host runs simply populate host 0.

Pure stdlib so jax-free readers can import it.
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from collections import deque
from typing import Any, Optional

DEFAULT_MAX_SAMPLES = 512

#: Quantiles every observed series is summarized at — p99 is the SLO the
#: serve stack is run against, p50/p90 give the shape of the body.
PERCENTILES = (50, 90, 99)

_RE_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def percentile(values, pct: float) -> Optional[float]:
    """The ``pct``-th percentile of ``values`` by linear interpolation
    between closest ranks (numpy's default method, stdlib-only so
    jax-free readers can use it). None on an empty input."""
    vals = sorted(v for v in values if _finite(v) is not None)
    if not vals:
        return None
    if len(vals) == 1:
        return float(vals[0])
    rank = (len(vals) - 1) * (float(pct) / 100.0)
    lo = int(math.floor(rank))
    hi = min(lo + 1, len(vals) - 1)
    frac = rank - lo
    return float(vals[lo] + (vals[hi] - vals[lo]) * frac)


def _sane(name: str) -> str:
    out = _RE_SANITIZE.sub("_", name)
    return out if not out[:1].isdigit() else "_" + out


def _finite(value: Any) -> Optional[float]:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


class MetricsRegistry:
    """Run-level gauge store with Prometheus + JSON snapshot export."""

    def __init__(self, *, run_id: str = "", max_samples: int =
                 DEFAULT_MAX_SAMPLES):
        self.run_id = run_id
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        # (name, host) -> latest value; name -> deque[(step, value)]
        self._last: dict[tuple[str, Any], float] = {}
        self._series: dict[str, deque] = {}

    # -- ingest ----------------------------------------------------------

    def observe(self, name: str, value: Any, *, step: Optional[int] = None,
                host: Any = 0) -> None:
        """Record one gauge sample. Non-numeric / non-finite values are
        dropped (NaN loss is the anomaly detector's job, not the
        scraper's)."""
        v = _finite(value)
        if v is None:
            return
        with self._lock:
            self._last[(name, host)] = v
            series = self._series.get(name)
            if series is None:
                series = self._series[name] = deque(maxlen=self.max_samples)
            series.append((int(step) if step is not None else None, v))

    def observe_many(self, record: dict[str, Any], *,
                     step: Optional[int] = None, host: Any = 0) -> None:
        """Ingest every numeric field of a metrics record (the dict
        ``MetricLogger.log`` returns) in one call."""
        if step is None:
            step = record.get("step")
        for name, value in record.items():
            if name == "step":
                continue
            self.observe(name, value, step=step, host=host)

    # -- views -----------------------------------------------------------

    def hosts(self) -> list:
        with self._lock:
            return sorted({h for (_, h) in self._last}, key=str)

    def aggregate(self) -> dict[str, Any]:
        """Run-level summary: per metric, stats over the per-host latest
        values plus the tail of the series."""
        with self._lock:
            out: dict[str, Any] = {"run": self.run_id,
                                   "generated_at": time.time(),
                                   "metrics": {}}
            by_name: dict[str, dict[Any, float]] = {}
            for (name, host), v in self._last.items():
                by_name.setdefault(name, {})[host] = v
            for name, per_host in sorted(by_name.items()):
                vals = list(per_host.values())
                series = list(self._series.get(name, ()))
                svals = [v for (_, v) in series]
                out["metrics"][name] = {
                    "last": vals[-1] if len(vals) == 1 else per_host[
                        sorted(per_host, key=str)[0]],
                    "per_host": {str(h): v
                                 for h, v in sorted(per_host.items(),
                                                    key=lambda kv:
                                                    str(kv[0]))},
                    "min": min(vals), "max": max(vals),
                    "mean": sum(vals) / len(vals),
                    "samples": len(series),
                    "series_tail": series[-32:],
                    # Quantiles over the OBSERVED SERIES (all samples in
                    # the window), not the per-host latest values — for
                    # per-request observations like serve_ttft_s these
                    # ARE the p50/p90/p99 an SLO is stated against.
                    "percentiles": {f"p{p}": percentile(svals, p)
                                    for p in PERCENTILES},
                }
            return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format, one gauge per (metric,
        host) with ``run`` and ``host`` labels."""
        with self._lock:
            lines: list[str] = []
            by_name: dict[str, dict[Any, float]] = {}
            for (name, host), v in self._last.items():
                by_name.setdefault(name, {})[host] = v
            for name, per_host in sorted(by_name.items()):
                metric = f"ddl_{_sane(name)}"
                lines.append(f"# TYPE {metric} gauge")
                for host, v in sorted(per_host.items(),
                                      key=lambda kv: str(kv[0])):
                    labels = f'run="{self.run_id}",host="{host}"'
                    lines.append(f"{metric}{{{labels}}} {v:.10g}")
                svals = [v for (_, v) in self._series.get(name, ())]
                if len(svals) > 1:
                    # Series quantiles as separate gauge families (the
                    # summary type would claim these are streaming
                    # quantiles; they are window quantiles over the
                    # bounded sample deque).
                    for p in PERCENTILES:
                        q = percentile(svals, p)
                        qm = f"{metric}_p{p}"
                        lines.append(f"# TYPE {qm} gauge")
                        lines.append(
                            f'{qm}{{run="{self.run_id}"}} {q:.10g}')
            return "\n".join(lines) + ("\n" if lines else "")

    # -- export ----------------------------------------------------------

    def write_prometheus(self, path: str) -> Optional[str]:
        return _publish(path, self.prometheus_text())

    def write_snapshot(self, path: str) -> Optional[str]:
        return _publish(path, json.dumps(self.aggregate(), indent=2,
                                         sort_keys=True) + "\n")


def _publish(path: str, text: str) -> Optional[str]:
    """Atomic best-effort write (same contract as sidecars.write)."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001
        return None


# -- module singleton (telemetry-style) ----------------------------------

_active = MetricsRegistry()


def get() -> MetricsRegistry:
    return _active


def configure(*, run_id: str = "", max_samples: int =
              DEFAULT_MAX_SAMPLES) -> MetricsRegistry:
    global _active
    _active = MetricsRegistry(run_id=run_id, max_samples=max_samples)
    return _active


def reset() -> None:
    configure()
