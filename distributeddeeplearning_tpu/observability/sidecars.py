"""One read/write helper for the ``.cache/*.json`` run sidecars.

Before this module each writer hand-rolled the same twelve lines —
repo-root discovery, tmp-file + ``os.replace``, bare ``except`` — and
each reader open-coded its own ``json.load``. Three sidecars had already
drifted into three slightly different shapes. Every sidecar now goes
through ``write()`` / ``read()``:

- atomic publish (tmp + ``os.replace``), best-effort: a full disk or
  read-only checkout never kills a run, ``write`` just returns ``None``;
- one envelope: the payload is stored flat, plus ``schema`` (bumped on
  incompatible layout changes) and ``written_at`` (unix seconds) so
  readers like ``tools/doctor.py`` can age-stamp what they report;
- one location: ``<repo>/.cache/<name>.json`` for named sidecars, or an
  explicit path for sidecars that live elsewhere (compile-cache stats
  live inside the cache dir they describe).

Known sidecar names (the registry is deliberately just a tuple — the
point is a shared shape, not a gatekeeper):

    last_run_sharding      train/loop.py — sharding/overlap of the last run
    last_elastic_event     train/loop.py — last elastic re-formation
    last_bench             bench.py — per-metric last good measurements
    perf_gate_last         observability/perf_gate.py — last gate result
    last_ddl_lint          tools/ddl_lint.py — last analyzer run + schedule
                           fingerprints
    schedule_fingerprints  analysis/collectives.py — config-fp -> schedule-fp
                           pairing registry for the AOT cache cross-check
    last_serve             tools/bench_serve.py — last continuous-batching
                           serve bench record (doctor.py serve report)

Pure stdlib; safe to import from jax-free tools.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

SCHEMA_VERSION = 1

KNOWN = ("last_run_sharding", "last_elastic_event", "last_bench",
         "perf_gate_last", "last_ddl_lint", "schedule_fingerprints",
         "last_serve")


def cache_dir() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, ".cache")


def path_for(name_or_path: str) -> str:
    """A bare name maps to ``<repo>/.cache/<name>.json``; anything with a
    path separator or a ``.json`` suffix is used as-is."""
    if os.sep in name_or_path or name_or_path.endswith(".json"):
        return name_or_path
    return os.path.join(cache_dir(), f"{name_or_path}.json")


def write(name_or_path: str, payload: dict[str, Any]) -> Optional[str]:
    """Atomically publish ``payload`` (+ envelope). Returns the path, or
    ``None`` on any failure. Never raises."""
    path = path_for(name_or_path)
    try:
        record = dict(payload)
        record.setdefault("schema", SCHEMA_VERSION)
        record.setdefault("written_at", time.time())
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(record, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — sidecars are best-effort telemetry
        return None


def read(name_or_path: str) -> Optional[dict[str, Any]]:
    """Load a sidecar; absent or malformed yields ``None`` (a missing
    sidecar is a note, never a failure)."""
    try:
        with open(path_for(name_or_path), encoding="utf-8") as fh:
            obj = json.load(fh)
        return obj if isinstance(obj, dict) else None
    except Exception:  # noqa: BLE001
        return None


def age_s(record: Optional[dict[str, Any]],
          now: Optional[float] = None) -> Optional[float]:
    """Seconds since the sidecar was written, when the envelope (or a
    legacy ``updated_at``) carries a timestamp."""
    if not record:
        return None
    stamp = record.get("written_at", record.get("updated_at"))
    if not isinstance(stamp, (int, float)):
        return None
    return (time.time() if now is None else now) - float(stamp)
