"""Heartbeat health: child-side writer, launcher-side staleness check.

The launcher's fail-whole monitor (launch.py) only sees *exits* — a child
that hangs (deadlocked collective, wedged data pipeline, remote-device
tunnel gone quiet) keeps the whole job alive forever. Heartbeats close that
gap: every training process touches a per-rank file on its log cadence, and
the launcher treats a heartbeat that stops aging as a hung child, kills it,
and lets the existing attribution + restart machinery (PR 3) take over.

Pure stdlib on both sides — the launcher must never import jax.

Wiring: the launcher exports ``DDL_HEARTBEAT_DIR`` to its children (plus
the pre-existing ``DDL_PROCESS_ID``); the train loop calls
:meth:`HeartbeatWriter.from_env` and beats on log cadence. A child that
never writes (old binary, crashed in startup) is never judged by the
watchdog — staleness only applies after the first beat, so startup/compile
time needs no special-cased grace period.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

ENV_HEARTBEAT_DIR = "DDL_HEARTBEAT_DIR"
_ENV_PROCESS_ID = "DDL_PROCESS_ID"  # set by launch.ProcessSpec.env()

# Elastic membership (launch.py --elastic). The launcher exports one JSON
# env var to the children of a re-formed attempt — {"trigger": "host_lost" |
# "hung" | "host_rejoin" | "host_join" | "host_drain", "degree_before": D0,
# "degree_after": D1, "epoch": E, "detect_t": monotonic-seconds-at-
# detection, "drain_done_t": monotonic-seconds-when-the-last-member-exited}
# — so the training loop can close the reconfiguration_time_s span
# (detection -> first post-resume step) AND split it into phases, all on
# the SAME CLOCK_MONOTONIC clock the launcher read. The rejoin/join marker
# file is how an arriving host announces itself to the membership
# controller: its launcher (or the host_join / host_rejoin fault, in
# simulation) touches it in the shared rendezvous (heartbeat) directory;
# drain markers announce a planned leave the same way.
ENV_ELASTIC_EVENT = "DDL_ELASTIC_EVENT"
# The membership epoch this child was formed under (namespaces its
# heartbeat file and lets it ignore the reform barrier of its OWN epoch).
ENV_ELASTIC_EPOCH = "DDL_ELASTIC_EPOCH"
# The child's ORIGINAL host identity (stable across re-formations, unlike
# DDL_PROCESS_ID which is the slot of the current attempt).
ENV_ELASTIC_HOST = "DDL_ELASTIC_HOST"
# Exit code of a child that drained voluntarily at a step boundary after
# seeing a reform barrier: "try again with the new membership", which is
# exactly os.EX_TEMPFAIL's meaning. The launcher's monitor treats it as a
# planned exit, never a failure.
EXIT_DRAIN = 75
_REJOIN_MARKER = "rejoin"
_DRAIN_PREFIX = "drain."
_REFORM_FILE = "reform.json"


def heartbeat_path(directory: str, process_id: int,
                   epoch: Optional[int] = None) -> str:
    """Per-epoch heartbeat namespace: epoch 0 (or None — every non-elastic
    caller) keeps the legacy ``heartbeat.N`` name; a re-formed membership
    epoch E > 0 beats into ``heartbeat.eE.N``, so a stale file from a
    previous epoch can never feed the new epoch's staleness clock or its
    host-loss attribution."""
    if epoch:
        return os.path.join(directory, f"heartbeat.e{int(epoch)}.{process_id}")
    return os.path.join(directory, f"heartbeat.{process_id}")


def rejoin_path(directory: str) -> str:
    return os.path.join(directory, _REJOIN_MARKER)


def _write_marker(directory: str, name: str, payload: dict) -> None:
    tmp = os.path.join(directory, f".{name}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, os.path.join(directory, name))
    except OSError:
        pass


def announce_rejoin(directory: str) -> None:
    """Touch the rejoin marker — a returned host asking the elastic
    controller to grow the job back. Atomic (tmp + replace), best-effort."""
    _write_marker(directory, _REJOIN_MARKER,
                  {"time": time.time(), "pid": os.getpid(),
                   "kind": "host_rejoin"})


def announce_join(directory: str) -> None:
    """The rendezvous-scoped spelling of the same announcement: a NEW (or
    returned) host asking to be folded in at the next step boundary. Shares
    the rejoin marker file — one grow path — but stamps its kind so the
    controller reports the trigger it actually saw."""
    _write_marker(directory, _REJOIN_MARKER,
                  {"time": time.time(), "pid": os.getpid(),
                   "kind": "host_join"})


def consume_rejoin(directory: str) -> bool:
    """True iff a rejoin/join marker existed; the marker is removed
    (consumed) so one announcement triggers exactly one re-formation."""
    try:
        os.remove(rejoin_path(directory))
        return True
    except OSError:
        return False


def consume_join(directory: str) -> Optional[str]:
    """Like :func:`consume_rejoin` but returns the announcement's kind
    (``host_join`` / ``host_rejoin``), or None when no marker existed."""
    path = rejoin_path(directory)
    kind = "host_rejoin"
    try:
        with open(path) as fh:
            payload = json.load(fh)
        if isinstance(payload, dict) and payload.get("kind"):
            kind = str(payload["kind"])
    except (OSError, ValueError):
        pass
    try:
        os.remove(path)
        return kind
    except OSError:
        return None


def announce_drain(directory: str, host: Optional[int] = None) -> None:
    """A planned leave: this host asks to be drained out of the membership
    at the next step boundary (maintenance, rebalancing — the opposite of a
    host_lost, which is involuntary and saves nothing). ``host`` is the
    ORIGINAL host identity; defaults to ``DDL_ELASTIC_HOST`` and then
    ``DDL_PROCESS_ID``."""
    if host is None:
        raw = (os.environ.get(ENV_ELASTIC_HOST)
               or os.environ.get(_ENV_PROCESS_ID, "0"))
        try:
            host = int(raw)
        except ValueError:
            host = 0
    _write_marker(directory, f"{_DRAIN_PREFIX}{int(host)}",
                  {"time": time.time(), "pid": os.getpid()})


def consume_drains(directory: str) -> list[int]:
    """Original host ids with a pending drain announcement; the markers are
    removed (consumed) so each announcement triggers one re-formation."""
    hosts = []
    try:
        names = os.listdir(directory)
    except OSError:
        return hosts
    for name in names:
        if not name.startswith(_DRAIN_PREFIX):
            continue
        suffix = name[len(_DRAIN_PREFIX):]
        if not suffix.isdigit():
            continue
        try:
            os.remove(os.path.join(directory, name))
            hosts.append(int(suffix))
        except OSError:
            pass
    return sorted(hosts)


def reform_path(directory: str) -> str:
    return os.path.join(directory, _REFORM_FILE)


def request_reform(directory: str, *, epoch: int, trigger: str,
                   save: bool = True) -> None:
    """Launcher-side: raise the join/leave barrier. Children poll this file
    at their step boundaries; one whose epoch is older than the barrier's
    saves (when ``save`` — every member is alive, so the collective save
    works) and exits :data:`EXIT_DRAIN` voluntarily. ``save=False`` marks a
    barrier raised because a member is already DEAD (host_lost/hung): a
    collective save would wedge on the missing rank, so survivors exit
    immediately and the re-formed attempt resumes from the last committed
    checkpoint."""
    _write_marker(directory, _REFORM_FILE,
                  {"epoch": int(epoch), "trigger": str(trigger),
                   "save": bool(save), "time": time.time()})


def read_reform(directory: str, *,
                newer_than_epoch: Optional[int] = None) -> Optional[dict]:
    """The pending reform barrier, or None. With ``newer_than_epoch``, a
    barrier at or below that epoch is ignored — a re-formed child must not
    re-drain on the barrier that formed it."""
    try:
        with open(reform_path(directory)) as fh:
            barrier = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(barrier, dict):
        return None
    if newer_than_epoch is not None:
        try:
            if int(barrier.get("epoch", 0)) <= int(newer_than_epoch):
                return None
        except (TypeError, ValueError):
            return None
    return barrier


def clear_reform(directory: str) -> None:
    try:
        os.remove(reform_path(directory))
    except OSError:
        pass


def current_epoch() -> int:
    """The membership epoch this process was formed under (0 outside an
    elastic launcher)."""
    try:
        return int(os.environ.get(ENV_ELASTIC_EPOCH, "0") or 0)
    except ValueError:
        return 0


def poll_drain() -> Optional[dict]:
    """Child-side step-boundary check: the reform barrier demanding THIS
    process drain, or None. One os.stat-grade read per call — cheap enough
    for every step boundary — and only armed under a heartbeat-exporting
    launcher."""
    directory = os.environ.get(ENV_HEARTBEAT_DIR)
    if not directory:
        return None
    return read_reform(directory, newer_than_epoch=current_epoch())


def read_elastic_event() -> Optional[dict]:
    """The launcher-exported membership event this process was re-formed
    under, or None on a normal (non-reconfigured) attempt."""
    raw = os.environ.get(ENV_ELASTIC_EVENT)
    if not raw:
        return None
    try:
        event = json.loads(raw)
    except ValueError:
        return None
    return event if isinstance(event, dict) else None


class HeartbeatWriter:
    """Touches this process's heartbeat file; the file's mtime IS the
    signal (content is a small JSON breadcrumb for humans)."""

    def __init__(self, directory: str, process_id: int = 0,
                 epoch: int = 0):
        self.directory = directory
        self.process_id = int(process_id)
        self.epoch = int(epoch)
        self.path = heartbeat_path(directory, self.process_id, self.epoch)
        os.makedirs(directory, exist_ok=True)

    @classmethod
    def from_env(cls) -> Optional["HeartbeatWriter"]:
        directory = os.environ.get(ENV_HEARTBEAT_DIR)
        if not directory:
            return None
        return cls(directory, int(os.environ.get(_ENV_PROCESS_ID, "0") or 0),
                   epoch=current_epoch())

    def beat(self, step: int) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump({"step": int(step), "time": time.time(),
                           "pid": os.getpid()}, fh)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a full/broken disk must never kill the training step


def check_stale(directory: str, num_processes: int, timeout_s: float,
                now: Optional[float] = None,
                epoch: Optional[int] = None) -> list[tuple[int, float]]:
    """(process_id, age_s) for every child whose heartbeat file exists and
    is older than ``timeout_s``. ``now`` is injectable (fake clock in
    tests); it is compared against file mtimes, so tests steer it with
    ``os.utime``. Children that never beat are not reported — the watchdog
    arms per child on its first beat. ``epoch`` selects the membership
    epoch's heartbeat namespace (None/0 = the legacy files)."""
    if now is None:
        now = time.time()
    stale = []
    for pid in range(num_processes):
        try:
            mtime = os.stat(heartbeat_path(directory, pid, epoch)).st_mtime
        except OSError:
            continue
        age = now - mtime
        if age > timeout_s:
            stale.append((pid, age))
    return stale
