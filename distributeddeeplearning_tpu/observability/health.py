"""Heartbeat health: child-side writer, launcher-side staleness check.

The launcher's fail-whole monitor (launch.py) only sees *exits* — a child
that hangs (deadlocked collective, wedged data pipeline, remote-device
tunnel gone quiet) keeps the whole job alive forever. Heartbeats close that
gap: every training process touches a per-rank file on its log cadence, and
the launcher treats a heartbeat that stops aging as a hung child, kills it,
and lets the existing attribution + restart machinery (PR 3) take over.

Pure stdlib on both sides — the launcher must never import jax.

Wiring: the launcher exports ``DDL_HEARTBEAT_DIR`` to its children (plus
the pre-existing ``DDL_PROCESS_ID``); the train loop calls
:meth:`HeartbeatWriter.from_env` and beats on log cadence. A child that
never writes (old binary, crashed in startup) is never judged by the
watchdog — staleness only applies after the first beat, so startup/compile
time needs no special-cased grace period.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

ENV_HEARTBEAT_DIR = "DDL_HEARTBEAT_DIR"
_ENV_PROCESS_ID = "DDL_PROCESS_ID"  # set by launch.ProcessSpec.env()

# Elastic membership (launch.py --elastic). The launcher exports one JSON
# env var to the children of a re-formed attempt — {"trigger": "host_lost" |
# "hung" | "host_rejoin", "degree_before": D0, "degree_after": D1,
# "detect_t": monotonic-seconds-at-detection} — so the training loop can
# close the reconfiguration_time_s span (detection -> first post-resume
# step) on the SAME CLOCK_MONOTONIC clock the launcher read. The rejoin
# marker file is how a returning host announces itself to the membership
# controller: its launcher (or the host_rejoin fault, in simulation)
# touches it in the shared heartbeat directory.
ENV_ELASTIC_EVENT = "DDL_ELASTIC_EVENT"
_REJOIN_MARKER = "rejoin"


def heartbeat_path(directory: str, process_id: int) -> str:
    return os.path.join(directory, f"heartbeat.{process_id}")


def rejoin_path(directory: str) -> str:
    return os.path.join(directory, _REJOIN_MARKER)


def announce_rejoin(directory: str) -> None:
    """Touch the rejoin marker — a returned host asking the elastic
    controller to grow the job back. Atomic (tmp + replace), best-effort."""
    tmp = os.path.join(directory, f".{_REJOIN_MARKER}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as fh:
            json.dump({"time": time.time(), "pid": os.getpid()}, fh)
        os.replace(tmp, rejoin_path(directory))
    except OSError:
        pass


def consume_rejoin(directory: str) -> bool:
    """True iff a rejoin marker existed; the marker is removed (consumed)
    so one announcement triggers exactly one re-formation."""
    try:
        os.remove(rejoin_path(directory))
        return True
    except OSError:
        return False


def read_elastic_event() -> Optional[dict]:
    """The launcher-exported membership event this process was re-formed
    under, or None on a normal (non-reconfigured) attempt."""
    raw = os.environ.get(ENV_ELASTIC_EVENT)
    if not raw:
        return None
    try:
        event = json.loads(raw)
    except ValueError:
        return None
    return event if isinstance(event, dict) else None


class HeartbeatWriter:
    """Touches this process's heartbeat file; the file's mtime IS the
    signal (content is a small JSON breadcrumb for humans)."""

    def __init__(self, directory: str, process_id: int = 0):
        self.directory = directory
        self.process_id = int(process_id)
        self.path = heartbeat_path(directory, self.process_id)
        os.makedirs(directory, exist_ok=True)

    @classmethod
    def from_env(cls) -> Optional["HeartbeatWriter"]:
        directory = os.environ.get(ENV_HEARTBEAT_DIR)
        if not directory:
            return None
        return cls(directory, int(os.environ.get(_ENV_PROCESS_ID, "0") or 0))

    def beat(self, step: int) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump({"step": int(step), "time": time.time(),
                           "pid": os.getpid()}, fh)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a full/broken disk must never kill the training step


def check_stale(directory: str, num_processes: int, timeout_s: float,
                now: Optional[float] = None) -> list[tuple[int, float]]:
    """(process_id, age_s) for every child whose heartbeat file exists and
    is older than ``timeout_s``. ``now`` is injectable (fake clock in
    tests); it is compared against file mtimes, so tests steer it with
    ``os.utime``. Children that never beat are not reported — the watchdog
    arms per child on its first beat."""
    if now is None:
        now = time.time()
    stale = []
    for pid in range(num_processes):
        try:
            mtime = os.stat(heartbeat_path(directory, pid)).st_mtime
        except OSError:
            continue
        age = now - mtime
        if age > timeout_s:
            stale.append((pid, age))
    return stale
