"""ctypes bindings + source adapter for the native C++ loader (csrc/).

The in-tree DALI-equivalent (SURVEY.md §2 #6): JPEG decode, ResNet-recipe
augmentation, and batch assembly run in a C++ thread pool behind a bounded
ring of batch slots; Python only memcpys finished float32 NHWC batches and
ships them to HBM. Preferred for image-folder ImageNet layouts; tf.data
(data/imagenet.py) remains the TFRecord path and the fallback when no C++
toolchain is available.

The library is compiled on first use (g++ -shared against libjpeg, ~2 s) and
cached next to the package; set ``DDL_NATIVE_LOADER=0`` to force the tf.data
fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_BUILD_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_ERR: Optional[str] = None

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_PKG_DIR, "_native", "libddl_loader.so")
_SRC_PATH = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)),
                         "csrc", "ddl_loader.cc")


def _build() -> str:
    os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
    if (os.path.exists(_SO_PATH)
            and os.path.getmtime(_SO_PATH) >= os.path.getmtime(_SRC_PATH)):
        return _SO_PATH
    # Compile to a per-pid temp path and rename into place: an interrupted
    # build can't leave a half-written .so with a fresh mtime, and multiple
    # processes racing on first use each install a complete library.
    tmp = f"{_SO_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall", "-shared",
           "-o", tmp, _SRC_PATH, "-ljpeg", "-lpthread"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300)
        if proc.returncode != 0:
            raise RuntimeError(f"native loader build failed:\n{proc.stderr}")
        os.replace(tmp, _SO_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _SO_PATH


def _load() -> ctypes.CDLL:
    global _LIB, _LIB_ERR
    with _BUILD_LOCK:
        if _LIB is not None:
            return _LIB
        if _LIB_ERR is not None:
            raise RuntimeError(_LIB_ERR)
        try:
            lib = ctypes.CDLL(_build())
        except (OSError, RuntimeError, subprocess.SubprocessError) as e:
            _LIB_ERR = f"native loader unavailable: {e}"
            raise RuntimeError(_LIB_ERR) from e
        lib.ddl_loader_create.restype = ctypes.c_void_p
        lib.ddl_loader_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),                 # paths
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,  # labels, n
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # batch,size,train
            ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,  # seed,thr,depth
            ctypes.c_int64, ctypes.c_int32,                   # start,repeat
            ctypes.POINTER(ctypes.c_float),                   # mean
            ctypes.POINTER(ctypes.c_float),                   # stdev
        ]
        lib.ddl_loader_next.restype = ctypes.c_int64
        lib.ddl_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32)]
        lib.ddl_loader_destroy.restype = None
        lib.ddl_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.ddl_loader_abi_version.restype = ctypes.c_int32
        lib.ddl_loader_abi_version.argtypes = []
        assert lib.ddl_loader_abi_version() == 1
        _LIB = lib
        return lib


def available() -> bool:
    """True when the native loader can be (or has been) built and loaded."""
    if os.environ.get("DDL_NATIVE_LOADER", "1") == "0":
        return False
    try:
        _load()
        return True
    except RuntimeError:
        return False


class NativeImageLoader:
    """Iterator over (image, label) host batches from the C++ loader.

    images: float32 NHWC, already normalized; labels: int32. The stream is
    deterministic in (seed, batch index), sharded per process, and resumable
    via ``start_batch``.
    """

    def __init__(self, paths: list[str], labels: list[int], *,
                 batch_size: int, image_size: int, train: bool, seed: int,
                 num_threads: Optional[int] = None, queue_depth: int = 3,
                 start_batch: int = 0, repeat: Optional[bool] = None,
                 mean=None, stdev=None):
        from distributeddeeplearning_tpu.data import imagenet

        lib = _load()
        n = len(paths)
        if n != len(labels):
            raise ValueError(
                f"paths/labels length mismatch: {n} vs {len(labels)}")
        if n < batch_size:
            raise ValueError(
                f"native loader needs at least one full batch: have {n} "
                f"samples but batch_size={batch_size}. With multi-process "
                f"sharding a small split can shrink below the per-process "
                f"batch — lower the batch size, or use loader='tf' "
                f"(tf.data drops the remainder instead).")
        self._lib = lib
        self._batch = batch_size
        self._size = image_size
        self.batches_per_epoch = n // batch_size
        c_paths = (ctypes.c_char_p * n)(
            *[p.encode() for p in paths])
        c_labels = (ctypes.c_int32 * n)(*labels)
        mean = np.asarray(mean if mean is not None else imagenet.MEAN_RGB,
                          np.float32)
        stdev = np.asarray(stdev if stdev is not None else
                           imagenet.STDDEV_RGB, np.float32)
        c_mean = (ctypes.c_float * 3)(*mean)
        c_std = (ctypes.c_float * 3)(*stdev)
        if repeat is None:
            repeat = train
        if num_threads is None:
            num_threads = min(max((os.cpu_count() or 4) - 1, 2), 16)
        self._handle = lib.ddl_loader_create(
            c_paths, c_labels, n, batch_size, image_size, int(train),
            seed, num_threads, queue_depth, start_batch, int(repeat),
            c_mean, c_std)
        if not self._handle:
            raise RuntimeError("ddl_loader_create failed (bad arguments?)")

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        images = np.empty((self._batch, self._size, self._size, 3),
                          np.float32)
        labels = np.empty((self._batch,), np.int32)
        idx = self._lib.ddl_loader_next(
            self._handle,
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if idx < 0:
            raise StopIteration
        return {"image": images, "label": labels}

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.ddl_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def make_native_source(config, sharding, *, train: bool = True,
                       start_step: int = 0):
    """StreamSource over the native loader for image-folder layouts.

    Shards samples across processes the same way the tf.data path does
    (every process_count-th sample), converts to the config's dtype on
    device via the StreamSource put.
    """
    import jax

    from distributeddeeplearning_tpu.data import imagenet

    d = config.data
    from distributeddeeplearning_tpu import data as datalib
    paths, labels = imagenet.folder_index(
        d.data_dir, "train" if train else "val")
    pidx, pcount = jax.process_index(), jax.process_count()
    paths = paths[pidx::pcount]
    labels = labels[pidx::pcount]
    per_process = imagenet._per_process_batch(config, pcount)
    loader = NativeImageLoader(
        paths, labels, batch_size=per_process, image_size=d.image_size,
        train=train, seed=config.seed, start_batch=start_step if train else 0,
        queue_depth=max(datalib.effective_prefetch_depth(config) + 1,
                        2))

    it = iter(loader)
    if config.dtype == "bfloat16":
        import jax.numpy as jnp

        def cast(b):
            return {"image": b["image"].astype(jnp.bfloat16),
                    "label": b["label"]}
        it = (cast(b) for b in it)
    src = imagenet.StreamSource(
        it, sharding, first_step=start_step,
        depth=datalib.effective_prefetch_depth(config),
        batches_hint=None if train else len(paths) // per_process,
        **imagenet.stream_guard_kwargs(config, train=train))
    src._native_loader = loader  # keep alive; closed on GC
    return src
