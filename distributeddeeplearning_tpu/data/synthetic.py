"""On-device synthetic batch sources (config 1, BASELINE.json:7).

The reference's synthetic mode fed host-generated fake tensors; on TPU the
idiomatic version materializes the batch *in HBM* with a tiny jitted program
— zero host↔device traffic, so the benchmark measures pure step time
(SURVEY.md §2 #5). Batches are deterministic functions of (seed, step) for
replay tests.
"""

from __future__ import annotations

import functools
from typing import Callable, Iterator, Optional

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.config import DataConfig, TrainConfig

MASK_TOKEN_ID = 103  # [MASK] in the BERT-base uncased vocab


class _SyntheticSource:
    """Deterministic on-device batches: jit-compiled generator of (seed, step).

    Subclasses provide ``_generator`` (a closure over static shape params);
    everything else — jit with output sharding, step folding, iteration —
    is shared.
    """

    def __init__(self, generator: Callable, seed: int,
                 sharding: Optional[jax.sharding.Sharding]):
        self.seed = seed
        # Raw (untraced) generator: the fused multi-step train loop
        # (steps.make_fused_train_loop) inlines batch generation into the
        # scanned step program, so K steps need zero host dispatches.
        self.gen_fn = generator
        self._gen = jax.jit(generator, out_shardings=sharding)

    def batch(self, step: int) -> dict:
        return self._gen(jax.random.key(self.seed), jnp.int32(step))

    def __iter__(self) -> Iterator[dict]:
        step = 0
        while True:
            yield self.batch(step)
            step += 1


class SyntheticImages(_SyntheticSource):
    """Fake ImageNet batches, generated in HBM.

    ``learnable=True`` embeds a fixed class-conditioned pattern under the
    noise, so the label is recoverable from the image: training drives
    top-1 toward 1.0 and the whole train→periodic-eval→best_top1 path can
    be demonstrated end-to-end without a dataset (pure-noise mode keeps
    benchmarking honest — no signal, stable step cost).
    """

    def __init__(self, batch_size: int, image_size: int = 224,
                 num_classes: int = 1000, seed: int = 0,
                 sharding: Optional[jax.sharding.Sharding] = None,
                 learnable: bool = False):
        self.batch_size = batch_size
        self.image_size = image_size
        self.num_classes = num_classes
        super().__init__(
            functools.partial(_gen_image_batch, batch=batch_size,
                              size=image_size, num_classes=num_classes,
                              learnable=learnable),
            seed, sharding)


class SyntheticTokens(_SyntheticSource):
    """Fake MLM batches: ids, mask-labels (-1 = unmasked). With
    ``max_predictions > 0``, emits gather-mode batches instead — fixed-width
    (masked_positions, masked_labels) for the projected-positions-only MLM
    head (config.data.mlm_max_predictions)."""

    def __init__(self, batch_size: int, seq_len: int = 128,
                 vocab_size: int = 30522, mask_prob: float = 0.15,
                 seed: int = 0,
                 sharding: Optional[jax.sharding.Sharding] = None,
                 max_predictions: int = 0):
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.mask_prob = mask_prob
        gen = (functools.partial(_gen_gathered_token_batch, batch=batch_size,
                                 seq_len=seq_len, vocab=vocab_size,
                                 max_pred=max_predictions)
               if max_predictions > 0 else
               functools.partial(_gen_token_batch, batch=batch_size,
                                 seq_len=seq_len, vocab=vocab_size,
                                 mask_prob=mask_prob))
        super().__init__(gen, seed, sharding)


def _gen_image_batch(key, step, *, batch, size, num_classes,
                     learnable=False):
    stepped = jax.random.fold_in(key, step)
    k1, k2 = jax.random.split(stepped)
    image = jax.random.normal(k1, (batch, size, size, 3), jnp.bfloat16)
    label = jax.random.randint(k2, (batch,), 0, num_classes, jnp.int32)
    if learnable:
        # Per-class pattern keyed on (base seed, label) — constant across
        # steps, so eval batches carry the same class signal training saw.
        def pattern(lbl):
            pk = jax.random.fold_in(jax.random.fold_in(key, 0x5157), lbl)
            return jax.random.normal(pk, (size, size, 3), jnp.bfloat16)

        image = 0.7 * image + jax.vmap(pattern)(label)
    return {"image": image, "label": label}


class SyntheticCausalTokens(_SyntheticSource):
    """Plain id sequences for causal-LM training (no masking)."""

    def __init__(self, batch_size: int, seq_len: int = 128,
                 vocab_size: int = 50257, seed: int = 0,
                 sharding: Optional[jax.sharding.Sharding] = None):
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        super().__init__(
            functools.partial(_gen_causal_batch, batch=batch_size,
                              seq_len=seq_len, vocab=vocab_size),
            seed, sharding)


def _gen_causal_batch(key, step, *, batch, seq_len, vocab):
    key = jax.random.fold_in(key, step)
    ids = jax.random.randint(key, (batch, seq_len), 1, vocab, jnp.int32)
    return {"input_ids": ids,
            "attention_mask": jnp.ones((batch, seq_len), jnp.int32)}


def _gen_token_batch(key, step, *, batch, seq_len, vocab, mask_prob):
    key = jax.random.fold_in(key, step)
    k1, k2 = jax.random.split(key)
    # Skip the reserved-token id range, but stay in-vocab for small test
    # vocabularies (ids >= vocab would NaN the cross entropy).
    lo = min(1000, vocab // 2)
    ids = jax.random.randint(k1, (batch, seq_len), lo, vocab, jnp.int32)
    masked = jax.random.bernoulli(k2, mask_prob, (batch, seq_len))
    labels = jnp.where(masked, ids, -1)
    input_ids = jnp.where(masked, MASK_TOKEN_ID, ids)
    return {"input_ids": input_ids, "labels": labels,
            "attention_mask": jnp.ones((batch, seq_len), jnp.int32)}


def _gen_gathered_token_batch(key, step, *, batch, seq_len, vocab, max_pred):
    """Gather-mode MLM batch: exactly ``max_pred`` distinct masked positions
    per sequence (sorted), original ids as labels, [MASK] written in."""
    key = jax.random.fold_in(key, step)
    k1, k2 = jax.random.split(key)
    lo = min(1000, vocab // 2)
    ids = jax.random.randint(k1, (batch, seq_len), lo, vocab, jnp.int32)
    pos = jax.vmap(lambda k: jax.random.permutation(k, seq_len)[:max_pred])(
        jax.random.split(k2, batch))
    pos = jnp.sort(pos, axis=-1).astype(jnp.int32)
    labels = jnp.take_along_axis(ids, pos, axis=1)
    input_ids = jax.vmap(lambda row, p: row.at[p].set(MASK_TOKEN_ID))(ids, pos)
    return {"input_ids": input_ids,
            "attention_mask": jnp.ones((batch, seq_len), jnp.int32),
            "masked_positions": pos, "masked_labels": labels}


def make_source(config: TrainConfig, input_kind: str = "image",
                sharding: Optional[jax.sharding.Sharding] = None,
                objective: str = "classify"):
    """Synthetic source matching the *model's* input kind (not the dataset
    string, so `--model bert_base` works with default data settings)."""
    d: DataConfig = config.data
    if input_kind == "tokens" and objective == "causal":
        return SyntheticCausalTokens(
            config.global_batch_size, d.seq_len, d.vocab_size,
            config.seed, sharding)
    if input_kind == "tokens":
        return SyntheticTokens(
            config.global_batch_size, d.seq_len, d.vocab_size,
            d.mlm_mask_prob, config.seed, sharding,
            max_predictions=d.mlm_max_predictions)
    return SyntheticImages(
        config.global_batch_size, d.image_size, d.num_classes, config.seed,
        sharding, learnable=d.synthetic_learnable)
