"""Input pipelines: the TPU-native replacement for the reference's CUDA/DALI
loaders (BASELINE.json:5 — "grain/tf.data pipelines with device-side HBM
prefetch"). Synthetic mode (SURVEY.md §2 #5) generates batches on-device for
data-independent benchmarking (config 1); real ImageNet rides tf.data's C++
op threads (data/imagenet.py) or the in-tree native C++ loader
(data/native.py)."""

from __future__ import annotations

from typing import Optional

import jax

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data import synthetic
from distributeddeeplearning_tpu.data.synthetic import (  # noqa: F401
    SyntheticImages,
    SyntheticTokens,
)


def resolve_loader(config: TrainConfig, input_kind: str) -> str:
    """Resolve ``config.data.loader`` to the concrete pipeline that will run.

    Returns one of ``synthetic | tokens | tf | native | grain``. ``auto``
    resolution is environment-dependent (C++ toolchain, DDL_NATIVE_LOADER)
    and the pipelines shuffle differently, so the resolved value is part of
    a run's determinism contract: the loop logs it at startup and persists
    it in checkpoint metadata so a resume under a different resolution fails
    loudly instead of silently switching sample streams (ADVICE r1 #1).
    ``grain`` (data/grain_pipeline.py) is explicit-only: ``auto`` keeps the
    C++ loader for folders and tf.data for TFRecords.
    """
    d = config.data
    if d.synthetic or not d.data_dir:
        return "synthetic"
    if input_kind == "tokens":
        return "tokens"
    loader = d.loader
    if loader == "auto":
        from distributeddeeplearning_tpu.data import imagenet, native
        # The C++ loader owns image-folder layouts when it can build;
        # TFRecords stay on tf.data (its native record readers).
        loader = ("native"
                  if (imagenet.detect_layout(d.data_dir) == "folder"
                      and native.available()) else "tf")
    return loader


def effective_prefetch_depth(config: TrainConfig) -> int:
    """Lookahead depth for the device prefetch buffer (StreamSource).

    ``config.data.prefetch_depth`` is the floor (legacy behavior —
    double-buffering at depth 2). Large-batch runs deepen it so host
    decode keeps ``data_wait_frac`` ~0 with 2x-batch headroom (ISSUE 20;
    the headroom is measured and stamped into every run summary's
    ``input_pipeline`` block):

    - an explicit precision policy marks a large-batch recipe run —
      double the configured depth;
    - a batch ramp additionally scales depth by ceil(final/stage) during
      the early stages, so the host pipeline is provisioned for the
      FINAL batch while the device still consumes the small one (the
      stage boundary would otherwise start with an empty buffer exactly
      when the batch doubles).

    Host-side only: ``prefetch_depth`` is a VOLATILE fingerprint field,
    so the deepened buffer never shifts the AOT program identity.
    """
    depth = config.data.prefetch_depth
    if depth <= 0:
        return depth
    scale = 1
    if getattr(config, "precision", None) is not None:
        scale = 2
    if getattr(config, "batch_ramp", None):
        from distributeddeeplearning_tpu.train import optim
        final = optim.ramp_final_batch(config)
        scale = max(scale,
                    -(-int(final) // max(config.global_batch_size, 1)))
    return depth * scale


def make_source(config: TrainConfig, input_kind: str,
                sharding: Optional[jax.sharding.Sharding] = None, *,
                start_step: int = 0, train: bool = True,
                objective: str = "classify"):
    """Route to the right pipeline for ``config.data``.

    - synthetic (or no data_dir): on-device deterministic batches, indexable
      by step — resume needs no skipping;
    - image + data_dir: tf.data ImageNet (TFRecord or image-folder layout)
      sharded per process, streamed from ``start_step``;
    - tokens + data_dir: packed-token MLM pipeline (data/tokens.py).
    """
    loader = resolve_loader(config, input_kind)
    if loader == "synthetic":
        return synthetic.make_source(config, input_kind, sharding=sharding,
                                     objective=objective)
    if loader == "tokens":
        from distributeddeeplearning_tpu.data import tokens
        return tokens.make_token_source(
            config, sharding, start_step=start_step, train=train,
            objective=objective)
    if loader == "native":
        from distributeddeeplearning_tpu.data import native
        return native.make_native_source(
            config, sharding, train=train, start_step=start_step)
    if loader == "grain":
        from distributeddeeplearning_tpu.data import grain_pipeline
        return grain_pipeline.make_grain_source(
            config, sharding, train=train, start_step=start_step)
    if loader == "tf":
        from distributeddeeplearning_tpu.data import imagenet
        return imagenet.make_imagenet_source(
            config, sharding, train=train, start_step=start_step)
    # Loud failure beats a silent sample-stream switch (the determinism
    # contract resolve_loader documents).
    raise ValueError(
        f"unknown data loader {loader!r}; expected one of "
        f"auto | tf | native | grain")
