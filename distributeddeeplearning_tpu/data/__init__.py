"""Input pipelines: the TPU-native replacement for the reference's CUDA/DALI
loaders (BASELINE.json:5 — "grain/tf.data pipelines with device-side HBM
prefetch"). Synthetic mode (SURVEY.md §2 #5) generates batches on-device for
data-independent benchmarking (config 1)."""

from distributeddeeplearning_tpu.data.synthetic import (  # noqa: F401
    SyntheticImages,
    SyntheticTokens,
    make_source,
)
