"""Real ImageNet input pipeline — the DALI/CUDA-loader replacement.

BASELINE.json:5: "the CUDA/DALI data loaders become grain/tf.data pipelines
with device-side HBM prefetch". This module is the tf.data half of that
mapping (SURVEY.md §2 #6, §3.3):

- decode/augment runs in tf.data's native C++ op threads on the host CPU —
  the role DALI's CPU/GPU workers played for the reference;
- each *process* (TPU host) reads a disjoint shard of the files
  (``shard(num_processes, process_index)``) — the per-rank sharding Horovod
  trainers did with rank/size;
- batches land in HBM through ``jax.make_array_from_process_local_data`` so
  the resulting global array carries the mesh batch sharding directly —
  no gather, no resharding collective on the hot path;
- double-buffered device prefetch (``StreamSource``'s lookahead buffer,
  below) overlaps host decode of step k+1 with device compute of step k.

Two on-disk layouts are supported:

1. **TFRecord** (canonical ImageNet-in-TFRecord: ``image/encoded`` JPEG bytes
   + ``image/class/label``), files matched by ``train-*``/``validation-*``;
2. **image folders** (``<split>/<wnid>/*.JPEG``, torchvision-style), for
   which the native C++ loader (data/native.py) is the preferred decoder and
   tf.data the fallback.

Augmentation is the standard ResNet50/ImageNet recipe the reference trainers
used (random-resized-crop 8-100% area, horizontal flip for train;
resize-256/center-crop-224 for eval; per-channel mean/std normalization) —
the details that silently cost top-1 if mismatched (SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import queue
import sys
import threading
import time
from typing import Any, Iterator, Optional

import jax
import numpy as np

from distributeddeeplearning_tpu.config import DataConfig, TrainConfig

# ImageNet RGB statistics (same constants torchvision/tf-models bake in).
MEAN_RGB = (0.485 * 255, 0.456 * 255, 0.406 * 255)
STDDEV_RGB = (0.229 * 255, 0.224 * 255, 0.225 * 255)

CROP_PADDING = 32  # eval: 224-crop from a 256-short-side frame
TRAIN_SPLIT_SIZE = 1_281_167


def _tf():
    """Import TensorFlow lazily and CPU-pinned (tf.data is host-only here)."""
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    try:
        tf.config.set_visible_devices([], "TPU")
    except (ValueError, RuntimeError):
        pass
    return tf


# ---------------------------------------------------------------------------
# Decode + augment (tf graph fns, executed by tf.data's C++ runtime threads)
# ---------------------------------------------------------------------------

def _decode_and_random_crop(tf, image_bytes, image_size: int):
    """Random-resized crop: 8-100% area, 3/4-4/3 aspect, decode-and-crop
    fused so the JPEG is only partially decoded (the DALI trick)."""
    shape = tf.io.extract_jpeg_shape(image_bytes)
    bbox = tf.constant([0.0, 0.0, 1.0, 1.0], shape=[1, 1, 4])
    begin, size, _ = tf.image.sample_distorted_bounding_box(
        shape, bbox, min_object_covered=0.1,
        aspect_ratio_range=(3 / 4, 4 / 3), area_range=(0.08, 1.0),
        max_attempts=10, use_image_if_no_bounding_boxes=True)
    offset_y, offset_x, _ = tf.unstack(begin)
    target_h, target_w, _ = tf.unstack(size)
    image = tf.image.decode_and_crop_jpeg(
        image_bytes, tf.stack([offset_y, offset_x, target_h, target_w]),
        channels=3, dct_method="INTEGER_FAST")
    return tf.image.resize(image, [image_size, image_size],
                           method=tf.image.ResizeMethod.BILINEAR)


def _decode_and_center_crop(tf, image_bytes, image_size: int):
    shape = tf.io.extract_jpeg_shape(image_bytes)
    h, w = shape[0], shape[1]
    # Equivalent of resize-shorter-side-to-(image_size+CROP_PADDING) then
    # central image_size crop, fused into a crop-then-resize (the 224/256
    # eval protocol): crop fraction = image_size / (image_size + padding).
    ratio = image_size / (image_size + CROP_PADDING)
    crop = tf.cast(
        ratio * tf.cast(tf.minimum(h, w), tf.float32), tf.int32)
    crop = tf.minimum(crop, tf.minimum(h, w))
    offset_y = (h - crop) // 2
    offset_x = (w - crop) // 2
    image = tf.image.decode_and_crop_jpeg(
        image_bytes, tf.stack([offset_y, offset_x, crop, crop]), channels=3,
        dct_method="INTEGER_FAST")
    return tf.image.resize(image, [image_size, image_size],
                           method=tf.image.ResizeMethod.BILINEAR)


def _normalize(tf, image, dtype):
    image = tf.cast(image, tf.float32)
    image -= tf.constant(MEAN_RGB, shape=[1, 1, 3], dtype=tf.float32)
    image /= tf.constant(STDDEV_RGB, shape=[1, 1, 3], dtype=tf.float32)
    return tf.cast(image, dtype)


def _preprocess(tf, image_bytes, image_size: int, train: bool, dtype):
    if train:
        image = _decode_and_random_crop(tf, image_bytes, image_size)
        image = tf.image.random_flip_left_right(image)
    else:
        image = _decode_and_center_crop(tf, image_bytes, image_size)
    image = tf.reshape(image, [image_size, image_size, 3])
    return _normalize(tf, image, dtype)


# ---------------------------------------------------------------------------
# Dataset builders
# ---------------------------------------------------------------------------

def _tfrecord_files(tf, data_dir: str, train: bool) -> Any:
    pattern = os.path.join(data_dir, "train-*" if train else "validation-*")
    files = tf.io.gfile.glob(pattern)
    if not files:
        raise FileNotFoundError(
            f"no TFRecord files matching {pattern!r}; expected ImageNet "
            "TFRecords named train-*/validation-*")
    return sorted(files)


def _parse_example(tf, serialized):
    features = tf.io.parse_single_example(serialized, {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/class/label": tf.io.FixedLenFeature([], tf.int64),
    })
    # Canonical ImageNet TFRecords label 1..1000; shift to 0-based.
    label = tf.cast(features["image/class/label"], tf.int32) - 1
    return features["image/encoded"], label


@functools.lru_cache(maxsize=8)
def folder_index(data_dir: str,
                 split: str) -> tuple[tuple[str, ...], tuple[int, ...]]:
    """Index a torchvision-style ``<split>/<wnid>/*.JPEG`` tree.

    Class ids are assigned by sorted wnid, matching torchvision's
    ``ImageFolder`` convention so checkpoints/evals line up.

    Cached per (dir, split): periodic eval rebuilds its source every
    invocation (fresh finite stream) and also derives ``batches_hint``
    from this listing — at ImageNet scale that's two 50k-file directory
    walks per eval without the cache. Contract: a split's contents are
    fixed for the life of the process (corpus generation happens before
    training processes start). Returns tuples: every consumer aliases the
    cache entry, so the index must be immutable — a list mutated through
    one alias would silently corrupt every later epoch and consumer.
    """
    root = os.path.join(data_dir, split)
    if not os.path.isdir(root):
        raise FileNotFoundError(f"no image-folder split at {root!r}")
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    paths, labels = [], []
    for idx, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith((".jpeg", ".jpg")):
                paths.append(os.path.join(cdir, fname))
                labels.append(idx)
    if not paths:
        raise FileNotFoundError(f"image-folder split {root!r} has no JPEGs")
    return tuple(paths), tuple(labels)


def detect_layout(data_dir: str) -> str:
    """'tfrecord' | 'folder' — by what's actually on disk."""
    import glob as globlib

    if globlib.glob(os.path.join(data_dir, "train-*")):
        return "tfrecord"
    if os.path.isdir(os.path.join(data_dir, "train")):
        return "folder"
    raise FileNotFoundError(
        f"{data_dir!r} contains neither train-* TFRecords nor a train/ "
        "image folder")


def build_dataset(config: TrainConfig, *, train: bool,
                  process_index: Optional[int] = None,
                  process_count: Optional[int] = None,
                  start_step: int = 0):
    """The per-process tf.data.Dataset of (image, label) host batches."""
    tf = _tf()
    d: DataConfig = config.data
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    per_process_batch = _per_process_batch(config, process_count)
    dtype = tf.bfloat16 if config.dtype == "bfloat16" else tf.float32

    layout = detect_layout(d.data_dir)
    if layout == "tfrecord":
        files = _tfrecord_files(tf, d.data_dir, train)
        ds = tf.data.Dataset.from_tensor_slices(files)
        ds = ds.shard(process_count, process_index)
        if train:
            ds = ds.shuffle(len(files), seed=config.seed)
        # deterministic=True keeps the example order a pure function of the
        # seed so skip-based resume replays the exact stream (the docstring
        # contract); AUTOTUNE still overlaps reads across the cycle.
        ds = ds.interleave(
            functools.partial(tf.data.TFRecordDataset,
                              buffer_size=16 * 1024 * 1024),
            cycle_length=8, num_parallel_calls=tf.data.AUTOTUNE,
            deterministic=True)
        ds = ds.map(functools.partial(_parse_example, tf),
                    num_parallel_calls=tf.data.AUTOTUNE)
    else:
        paths, labels = folder_index(d.data_dir,
                                     "train" if train else "val")
        ds = tf.data.Dataset.from_tensor_slices(
            (tf.constant(paths), tf.constant(labels, tf.int32)))
        ds = ds.shard(process_count, process_index)
        ds = ds.map(lambda p, l: (tf.io.read_file(p), l),
                    num_parallel_calls=tf.data.AUTOTUNE)

    if train:
        ds = ds.repeat()
        ds = ds.shuffle(min(d.shuffle_buffer, 2048 * 8), seed=config.seed)
    if train and start_step:
        # Resume: skip raw records (cheap) rather than decoded batches —
        # placed after shuffle so the replayed order matches the original run.
        ds = ds.skip(start_step * per_process_batch)

    ds = ds.map(
        lambda image_bytes, label: {
            "image": _preprocess(tf, image_bytes, d.image_size, train, dtype),
            "label": label,
        },
        num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.batch(per_process_batch, drop_remainder=True)
    ds = ds.prefetch(tf.data.AUTOTUNE)
    opts = tf.data.Options()
    opts.threading.private_threadpool_size = max(os.cpu_count() or 8, 8)
    opts.experimental_optimization.map_parallelization = True
    return ds.with_options(opts)


def _per_process_batch(config: TrainConfig, process_count: int) -> int:
    if config.global_batch_size % process_count:
        raise ValueError(
            f"global_batch_size={config.global_batch_size} not divisible by "
            f"process_count={process_count}")
    return config.global_batch_size // process_count


# ---------------------------------------------------------------------------
# Source adapter (loop-facing)
# ---------------------------------------------------------------------------

class _ProducerError:
    """Carrier moving a loader-thread exception to the consuming thread."""

    def __init__(self, err: BaseException):
        self.err = err


def _stalling_iterator(it: Iterator[dict], stalls: dict[int, float],
                       first_index: int) -> Iterator[dict]:
    """Fault injection (robustness/faults.py ``loader_stall@N``): sleep
    before yielding the batch destined for step N. Wraps the raw host
    iterator so the stall is indistinguishable from a genuinely slow
    pipeline — which is exactly what the watchdog must catch."""
    idx = first_index
    for item in it:
        delay = stalls.get(idx)
        if delay:
            time.sleep(delay)
        yield item
        idx += 1


def stream_guard_kwargs(config: TrainConfig, *, train: bool = True) -> dict:
    """StreamSource watchdog/stall kwargs for a config — shared by every
    host-streaming loader builder (tf/native/tokens/grain)."""
    from distributeddeeplearning_tpu.robustness import faults

    return faults.stream_guard_kwargs(config, train=train)


class StreamSource:
    """Adapts a host-batch iterator to the loop's ``batch(step)`` protocol.

    Each pulled host batch becomes a *global* jax.Array via
    ``make_array_from_process_local_data`` with the mesh batch sharding —
    per-process shards go straight to their local devices' HBM. A
    ``depth``-deep lookahead buffer (``DataConfig.prefetch_depth``; 1 =
    double buffering) keeps host decode of steps k+1..k+depth in flight
    while the device executes step k — the "device-side HBM prefetch" of
    BASELINE.json:5; deeper pipelining lives inside tf.data's prefetch + the
    jitted step's async dispatch.
    """

    _EXHAUSTED = object()

    def __init__(self, it: Iterator[dict], sharding, *, first_step: int = 0,
                 lookahead: bool = True, depth: int = 1,
                 batches_hint: Optional[int] = None,
                 timeout_s: float = 0.0, max_retries: int = 2,
                 stall_steps: Optional[dict] = None):
        if stall_steps:
            it = _stalling_iterator(it, dict(stall_steps), first_step)
        self._it = it
        self._sharding = sharding
        self._next_step = first_step
        # How many full local batches this finite stream will yield, when
        # the builder can know it (imagefolder val splits: file count //
        # per-process batch). None = unknown. Multi-process eval uses it to
        # agree on the global batch count with ONE collective up front
        # instead of a per-batch allgather (ADVICE r4).
        self.batches_hint = batches_hint
        # depth <= 0 (or lookahead=False) disables prefetch entirely —
        # batches are pulled on demand (used by short bounded evals).
        self._depth = max(depth, 0) if lookahead else 0
        # Watchdog (DataConfig.loader_timeout_s > 0): host items flow
        # through a bounded queue fed by a daemon thread, so a hung
        # iterator surfaces as a timeout here instead of wedging the run
        # silently. device_put stays on the consuming thread — only the
        # host-side next() moves. Disabled by default: the hot path pulls
        # straight from the iterator with zero extra machinery.
        self._timeout_s = float(timeout_s)
        self._max_retries = max(int(max_retries), 0)
        self._q: Optional[queue.Queue] = None
        if self._timeout_s > 0:
            self._q = queue.Queue(maxsize=max(self._depth, 1) + 1)
            threading.Thread(target=self._produce, args=(it,),
                             name="ddl-loader", daemon=True).start()
        self._pending: list = []
        self._fill()

    def _produce(self, it: Iterator[dict]) -> None:
        try:
            for item in it:
                self._q.put(item)
            self._q.put(self._EXHAUSTED)
        except BaseException as e:  # ferried to the consumer, re-raised there
            self._q.put(_ProducerError(e))

    def _fill(self) -> None:
        while (len(self._pending) < self._depth
               and not (self._pending
                        and self._pending[-1] is self._EXHAUSTED)):
            self._pending.append(self._pull())

    def _pull(self):
        """Next device batch, or the _EXHAUSTED sentinel on a finite stream
        (eval split) running dry — deferred so batch k is still deliverable
        when the k+1 lookahead hits end-of-data."""
        if self._q is None:
            try:
                return self._device_put(next(self._it))
            except StopIteration:
                return self._EXHAUSTED
        item = self._next_host_item()
        if item is self._EXHAUSTED:
            return self._EXHAUSTED
        return self._device_put(item)

    def _next_host_item(self):
        attempts = self._max_retries + 1
        for attempt in range(attempts):
            try:
                item = self._q.get(timeout=self._timeout_s)
            except queue.Empty:
                print(f"# data watchdog: no host batch within "
                      f"{self._timeout_s:.1f}s "
                      f"(attempt {attempt + 1}/{attempts})",
                      file=sys.stderr, flush=True)
                continue
            if isinstance(item, _ProducerError):
                raise item.err
            return item
        raise RuntimeError(
            f"data loader stalled: no host batch within {self._timeout_s:.1f}s"
            f" across {attempts} attempts — the input pipeline is hung or "
            "starved; restart the job (the launcher restart budget treats "
            "this as a transient fault)")

    def _device_put(self, host_batch: dict) -> dict:
        def put(x):
            x = np.asarray(x)
            return jax.make_array_from_process_local_data(
                self._sharding_for(x), x)
        return {k: put(v) for k, v in host_batch.items()}

    def _sharding_for(self, x):
        # Labels (rank 1) and images (rank 4) both shard on dim 0 only.
        spec = self._sharding.spec
        ndim = np.asarray(x).ndim
        trimmed = jax.sharding.PartitionSpec(
            *(list(spec) + [None] * ndim)[:ndim])
        return jax.sharding.NamedSharding(self._sharding.mesh, trimmed)

    def batch(self, step: int) -> dict:
        if step != self._next_step:
            raise ValueError(
                f"StreamSource consumed out of order: asked for step {step}, "
                f"expected {self._next_step} (resume must rebuild the source "
                "with first_step=start_step)")
        self._next_step += 1
        if self._depth:
            out = self._pending.pop(0)
            self._fill()
        else:
            out = self._pull()
        if out is self._EXHAUSTED:
            raise StopIteration(f"data stream exhausted at step {step}")
        return out


def make_imagenet_source(config: TrainConfig, sharding, *, train: bool = True,
                         start_step: int = 0) -> StreamSource:
    ds = build_dataset(config, train=train, start_step=start_step)
    hint = None
    if not train and detect_layout(config.data.data_dir) == "folder":
        # Finite val split with a listable size: this process's shard is
        # paths[process_index::process_count] (the ds.shard stride above).
        n_local = len(folder_index(config.data.data_dir, "val")[0]
                      [jax.process_index()::jax.process_count()])
        hint = n_local // _per_process_batch(config, jax.process_count())
    from distributeddeeplearning_tpu import data as datalib
    return StreamSource(ds.as_numpy_iterator(), sharding,
                        first_step=start_step,
                        depth=datalib.effective_prefetch_depth(config),
                        batches_hint=hint,
                        **stream_guard_kwargs(config, train=train))
