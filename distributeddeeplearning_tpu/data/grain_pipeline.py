"""grain-native ImageNet pipeline — the grain half of BASELINE.json:5
("CUDA/DALI data loaders → grain / tf.data pipelines with device-side HBM
prefetch"), alongside tf.data (data/imagenet.py) and the in-tree C++ loader
(data/native.py).

Why grain fits TPU hosts: the whole pipeline is a deterministic index
transform (``MapDataset``) — shard, shuffle, repeat, skip are all O(1)
index arithmetic, so per-process sharding is exact, every epoch reshuffles
deterministically from the seed, and **resume is a slice**: skipping
``start_step`` batches costs nothing (no decode of skipped records), unlike
stream-skip loaders. Decode/augment runs in grain's prefetch threads (PIL
releases the GIL during JPEG work); records land in the same
``StreamSource`` HBM path as the other loaders.

Supports the image-folder layout (``<split>/<wnid>/*.JPEG``). TFRecords
stay on tf.data — grain reads ArrayRecord, not TFRecord, natively.

The augmentation recipe matches data/imagenet.py exactly (random-resized
crop 8-100% area / 3-4 aspect, flip, mean/std normalize; eval
center-crop-with-padding protocol) — the details that silently cost top-1
if mismatched (SURVEY.md §7). Train decodes at full resolution (the
ADVICE r1 crop-quality rule: DCT-scaled decode only for eval's fixed
center crop).
"""

from __future__ import annotations

import dataclasses
import io
import os
from typing import Any, Optional

import numpy as np

from distributeddeeplearning_tpu.config import DataConfig, TrainConfig
from distributeddeeplearning_tpu.data.imagenet import (
    CROP_PADDING, MEAN_RGB, STDDEV_RGB, StreamSource, _per_process_batch,
    stream_guard_kwargs,
    folder_index)

# grain dispatches two-arg random_map(record, rng) ONLY to isinstance
# subclasses of its RandomMapTransform protocol — a plain callable gets the
# one-arg map() call and the per-record RNG never arrives. Import guarded:
# the transform stays directly usable (PIL decode paths, tests) on hosts
# without grain installed.
try:
    from grain.python import RandomMapTransform as _RandomMapBase
except Exception:  # pragma: no cover - grain is an optional dependency
    class _RandomMapBase:
        pass


class ImageFolderSource:
    """grain RandomAccessDataSource over an indexed image-folder split."""

    def __init__(self, paths: list[str], labels: list[int]):
        self._paths = paths
        self._labels = np.asarray(labels, np.int32)

    def __len__(self) -> int:
        return len(self._paths)

    def __getitem__(self, i: int) -> dict:
        with open(self._paths[i], "rb") as f:
            return {"bytes": f.read(), "label": self._labels[i]}


def _random_crop_box(rng: np.random.Generator, width: int, height: int,
                     attempts: int = 10) -> tuple[int, int, int, int]:
    """Sample an 8-100%-area, 3/4-4/3-aspect crop (x, y, w, h) — the
    tf.image.sample_distorted_bounding_box recipe in numpy."""
    area = width * height
    for _ in range(attempts):
        target_area = area * rng.uniform(0.08, 1.0)
        aspect = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        w = int(round(np.sqrt(target_area * aspect)))
        h = int(round(np.sqrt(target_area / aspect)))
        if 0 < w <= width and 0 < h <= height:
            x = int(rng.integers(0, width - w + 1))
            y = int(rng.integers(0, height - h + 1))
            return x, y, w, h
    # Fallback: central max-square (same as tf's use_image_if_no_bounding_boxes
    # degenerate path).
    side = min(width, height)
    return (width - side) // 2, (height - side) // 2, side, side


@dataclasses.dataclass
class DecodeAndAugment(_RandomMapBase):
    """Per-record decode + augment, run under grain's per-record RNG
    (a grain.python.RandomMapTransform; ``__call__`` stays as a direct-use
    alias of ``random_map``).

    JPEG bytes take tf's fused partial decode (``decode_and_crop_jpeg``
    touches only the DCT blocks under the crop — the same C++ fast path
    that makes the tf.data pipeline the per-core throughput winner,
    VERDICT r2 Weak #4); anything else falls back to PIL. Both run in
    grain's prefetch threads (the C++ decode and PIL both release the GIL)
    and share the crop-box sampling, flip, and normalize code, so the
    augmentation distribution is decoder-independent."""

    image_size: int
    train: bool
    dtype: Any

    def random_map(self, record: dict, rng: np.random.Generator) -> dict:
        data = record["bytes"]
        size = self.image_size
        if data[:3] == b"\xff\xd8\xff":  # JPEG magic
            arr = self._decode_tf(data, rng)
        else:
            arr = self._decode_pil(data, rng)
        if self.train and rng.random() < 0.5:
            arr = arr[:, ::-1]
        arr = (arr - np.asarray(MEAN_RGB, np.float32)) / np.asarray(
            STDDEV_RGB, np.float32)
        return {"image": arr.astype(self.dtype),
                "label": record["label"]}

    __call__ = random_map

    def _crop_box(self, rng, width: int, height: int):
        """(x, y, w, h) for this record: sampled for train, the padded
        center-crop protocol for eval."""
        if self.train:
            return _random_crop_box(rng, width, height)
        ratio = self.image_size / (self.image_size + CROP_PADDING)
        crop = min(int(ratio * min(width, height)), min(width, height))
        return (width - crop) // 2, (height - crop) // 2, crop, crop

    def _decode_tf(self, data: bytes, rng) -> np.ndarray:
        # _tf(), not a raw import: TF must come up with GPU/TPU hidden or
        # its runtime grabs the accelerator JAX already owns in-process.
        from distributeddeeplearning_tpu.data.imagenet import _tf

        tf = _tf()
        h, w = tf.io.extract_jpeg_shape(data).numpy()[:2]
        x, y, cw, ch = self._crop_box(rng, int(w), int(h))
        img = tf.io.decode_and_crop_jpeg(
            data, [y, x, ch, cw], channels=3,
            # Both branches decode the crop at full DCT resolution (the
            # partial decode only touches blocks under the crop); eval
            # additionally takes the faster lower-precision IDCT, which the
            # fixed center crop tolerates — train keeps the default IDCT
            # so small crops lose nothing before the resize.
            dct_method="" if self.train else "INTEGER_FAST")
        img = tf.image.resize(img, [self.image_size, self.image_size],
                              method="bilinear", antialias=False)
        return img.numpy().astype(np.float32)

    def _decode_pil(self, data: bytes, rng) -> np.ndarray:
        from PIL import Image

        img = Image.open(io.BytesIO(data))
        size = self.image_size
        img = img.convert("RGB")
        x, y, w, h = self._crop_box(rng, img.width, img.height)
        img = img.crop((x, y, x + w, y + h)).resize(
            (size, size), Image.BILINEAR)
        return np.asarray(img, np.float32)


def _np_dtype(config: TrainConfig):
    if config.dtype == "bfloat16":
        import ml_dtypes

        return ml_dtypes.bfloat16
    return np.float32


def build_grain_dataset(config: TrainConfig, *, train: bool,
                        process_index: Optional[int] = None,
                        process_count: Optional[int] = None,
                        start_step: int = 0):
    """Per-process grain IterDataset of host batches (dict of np arrays)."""
    import grain.python as grain
    import jax

    d: DataConfig = config.data
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    per_process = _per_process_batch(config, process_count)

    paths, labels = folder_index(d.data_dir, "train" if train else "val")
    ds = grain.MapDataset.source(ImageFolderSource(paths, labels))
    ds = ds.seed(config.seed)
    # Per-process shard: exact index interleave (record i -> process i % N),
    # the role tf.data's shard() / Horovod's rank-sharding played.
    ds = ds.slice(slice(process_index, None, process_count))
    if train:
        # shuffle-then-repeat: each epoch reshuffles deterministically
        # (reseed_each_epoch), matching the tf path's seeded shuffle.
        ds = ds.shuffle(seed=config.seed).repeat(None)
    # random_map BEFORE the resume slice: grain keys each element's RNG by
    # its index in the dataset it was mapped onto, so mapping first keys
    # augmentation draws by GLOBAL stream position — a resumed run replays
    # the exact crops/flips of the uninterrupted run, not just the same
    # records (ADVICE r2 #2). MapDataset is lazy either way: the slice
    # below still never decodes a skipped record.
    ds = ds.random_map(DecodeAndAugment(d.image_size, train,
                                        _np_dtype(config)))
    if train and start_step:
        # Resume = index arithmetic; skipped records are never decoded.
        ds = ds.slice(slice(start_step * per_process, None))
    threads = max(os.cpu_count() or 8, 8)
    # Batch AFTER to_iter_dataset: prefetch threads then parallelize and
    # buffer individual decoded records (prefetch_buffer_size counts
    # elements — batching first would make it count whole batches and the
    # buffer could grow to GBs of decoded images on a fast host).
    ds = ds.to_iter_dataset(grain.ReadOptions(
        num_threads=threads,
        prefetch_buffer_size=max(2 * per_process, 64)))
    return ds.batch(per_process, drop_remainder=True)


def make_grain_source(config: TrainConfig, sharding, *, train: bool = True,
                      start_step: int = 0) -> StreamSource:
    import jax

    ds = build_grain_dataset(config, train=train,
                             start_step=start_step if train else 0)
    hint = None
    if not train:
        # Finite val split: this process's slice(pidx, None, pcount) of the
        # folder index, in full per-process batches (drop_remainder).
        n_local = len(folder_index(config.data.data_dir, "val")[0]
                      [jax.process_index()::jax.process_count()])
        hint = n_local // _per_process_batch(config, jax.process_count())
    from distributeddeeplearning_tpu import data as datalib
    return StreamSource(iter(ds), sharding, first_step=start_step,
                        depth=datalib.effective_prefetch_depth(config),
                        batches_hint=hint,
                        **stream_guard_kwargs(config, train=train))
