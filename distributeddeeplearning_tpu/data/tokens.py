"""Packed-token MLM pipeline (config 4, BASELINE.json:10).

The reference era's BERT pretraining consumed pre-tokenized, fixed-length
sequence shards; the TPU-native version reads those shards (``.npy`` files of
int32 token ids, shape (N, seq_len), matched by ``<split>-*.npy`` under
``data_dir``) per process, applies *dynamic* BERT masking on the host
(80% [MASK] / 10% random / 10% keep), and ships batches to HBM with the mesh
batch sharding — same StreamSource mechanics as the image path.

Dynamic masking is deterministic in (seed, step) so resume replays the same
mask stream.
"""

from __future__ import annotations

import glob
import os
from typing import Iterator, Optional

import jax
import numpy as np

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.imagenet import (
    StreamSource, stream_guard_kwargs)
from distributeddeeplearning_tpu.data.synthetic import MASK_TOKEN_ID

# BERT-base uncased special ids; ids <= UNUSED_MAX are never masked targets.
PAD_ID, CLS_ID, SEP_ID = 0, 101, 102
UNUSED_MAX = 999


def token_files(data_dir: str, split: str = "train") -> list[str]:
    files = sorted(glob.glob(os.path.join(data_dir, f"{split}-*.npy")))
    if not files:
        raise FileNotFoundError(
            f"no packed-token shards matching {split}-*.npy in {data_dir!r}")
    return files


def _sequence_stream(files: list[str], seq_len: int, *, repeat: bool,
                     shard_index: int, shard_count: int,
                     seed: int) -> Iterator[np.ndarray]:
    """Round-robin-sharded, epoch-shuffled stream of (seq_len,) id rows."""
    rng = np.random.default_rng(seed)
    epoch = 0
    while True:
        order = rng.permutation(len(files)) if repeat else np.arange(len(files))
        for fi in order:
            arr = np.load(files[fi], mmap_mode="r")
            if arr.ndim != 2 or arr.shape[1] < seq_len:
                raise ValueError(
                    f"{files[fi]}: expected (N, >= {seq_len}) int array, "
                    f"got {arr.shape}")
            rows = np.arange(arr.shape[0])
            rows = rows[rows % shard_count == shard_index]
            if repeat:
                rows = rng.permutation(rows)
            for r in rows:
                yield np.asarray(arr[r, :seq_len], np.int32)
        epoch += 1
        if not repeat:
            return


def _special_mask(ids: np.ndarray) -> np.ndarray:
    """Positions that must never be masking targets — ONE definition shared
    by the dense and gather maskers so the two protocols can't silently
    diverge on what is maskable."""
    return (ids == PAD_ID) | (ids == CLS_ID) | (ids == SEP_ID) | (
        ids <= UNUSED_MAX)


def _rand_lo(vocab_size: int) -> int:
    """Lowest id for 10%-random replacements: skip the reserved range when
    the vocab is big enough (small test vocabs use the full id space)."""
    return UNUSED_MAX + 1 if vocab_size > UNUSED_MAX + 2 else 1


def mask_batch(ids: np.ndarray, *, mask_prob: float, vocab_size: int,
               rng: np.random.Generator) -> dict:
    """Dynamic BERT masking: labels=-1 except at masked positions; inputs get
    80% [MASK], 10% random id, 10% unchanged."""
    special = _special_mask(ids)
    pick = (rng.random(ids.shape) < mask_prob) & ~special
    labels = np.where(pick, ids, -1).astype(np.int32)
    roll = rng.random(ids.shape)
    input_ids = ids.copy()
    input_ids[pick & (roll < 0.8)] = MASK_TOKEN_ID
    rand_pos = pick & (roll >= 0.8) & (roll < 0.9)
    input_ids[rand_pos] = rng.integers(
        _rand_lo(vocab_size), vocab_size, rand_pos.sum(), dtype=np.int32)
    return {"input_ids": input_ids, "labels": labels,
            "attention_mask": (ids != PAD_ID).astype(np.int32)}


def gather_mask_batch(ids: np.ndarray, *, max_pred: int, mask_prob: float,
                      vocab_size: int, rng: np.random.Generator) -> dict:
    """Gather-mode dynamic masking (canonical create_pretraining_data
    semantics): per row, mask ``min(max_pred, round(maskable * mask_prob))``
    distinct non-special positions with the 80/10/10 recipe; emit fixed-width
    sorted ``masked_positions`` + ``masked_labels`` (-1 padding) for the
    projected-positions-only MLM head."""
    b, s = ids.shape
    special = _special_mask(ids)
    # Vectorized selection (this runs per step on the host hot path): rank
    # every position by a random key, +1 pushes specials behind all maskable
    # positions, then each row takes its first `take` ranks.
    maskable = (~special).sum(axis=1)
    take = np.minimum(
        np.minimum(max_pred,
                   np.maximum(1, np.round(maskable * mask_prob).astype(int))),
        maskable)
    order = np.argsort(rng.random(ids.shape) + special, axis=1)[:, :max_pred]
    valid = np.arange(max_pred)[None, :] < take[:, None]
    pos_sorted = np.sort(np.where(valid, order, s), axis=1)
    valid = pos_sorted < s
    positions = np.where(valid, pos_sorted, 0).astype(np.int32)
    labels = np.where(valid, np.take_along_axis(ids, positions, axis=1),
                      -1).astype(np.int32)
    input_ids = ids.copy()
    rows = np.broadcast_to(np.arange(b)[:, None], (b, max_pred))
    roll = rng.random((b, max_pred))
    m80 = valid & (roll < 0.8)
    input_ids[rows[m80], positions[m80]] = MASK_TOKEN_ID
    r10 = valid & (roll >= 0.8) & (roll < 0.9)
    input_ids[rows[r10], positions[r10]] = rng.integers(
        _rand_lo(vocab_size), vocab_size, int(r10.sum()), dtype=np.int32)
    return {"input_ids": input_ids,
            "attention_mask": (ids != PAD_ID).astype(np.int32),
            "masked_positions": positions, "masked_labels": labels}


def _batch_stream(config: TrainConfig, *, train: bool,
                  start_step: int,
                  objective: str = "mlm") -> Iterator[dict]:
    d = config.data
    proc, nproc = jax.process_index(), jax.process_count()
    per_process = config.global_batch_size // nproc
    if config.global_batch_size % nproc:
        raise ValueError("global_batch_size not divisible by process count")
    files = token_files(d.data_dir, "train" if train else "validation")
    seqs = _sequence_stream(files, d.seq_len, repeat=train,
                            shard_index=proc, shard_count=nproc,
                            seed=config.seed)
    step = 0
    while True:
        rows = []
        for _ in range(per_process):
            try:
                rows.append(next(seqs))
            except StopIteration:
                return  # finite (eval) stream drained mid-batch: drop remainder
        if step >= start_step:
            ids = np.stack(rows)
            if objective == "causal":
                # Causal LM consumes the raw packed ids; the loss shifts.
                yield {"input_ids": ids,
                       "attention_mask": (ids != PAD_ID).astype(np.int32)}
            else:
                # Mask keyed by (seed, step, proc): deterministic resume.
                rng = np.random.default_rng(
                    (config.seed * 1_000_003 + step) * 4099 + proc)
                if d.mlm_max_predictions > 0:
                    yield gather_mask_batch(
                        ids, max_pred=d.mlm_max_predictions,
                        mask_prob=d.mlm_mask_prob,
                        vocab_size=d.vocab_size, rng=rng)
                else:
                    yield mask_batch(ids, mask_prob=d.mlm_mask_prob,
                                     vocab_size=d.vocab_size, rng=rng)
        step += 1


def make_token_source(config: TrainConfig, sharding, *, start_step: int = 0,
                      train: bool = True,
                      objective: str = "mlm") -> StreamSource:
    it = _batch_stream(config, train=train, start_step=start_step,
                       objective=objective)
    from distributeddeeplearning_tpu import data as datalib
    return StreamSource(it, sharding, first_step=start_step,
                        depth=datalib.effective_prefetch_depth(config),
                        **stream_guard_kwargs(config, train=train))
