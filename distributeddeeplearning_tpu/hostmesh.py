"""Virtual-CPU-mesh pinning for tools, tests, and driver entry points.

The container's axon sitecustomize imports jax at interpreter start and
pins ``jax_platforms`` at a remote TPU whose backend init can hang, so env
vars alone are too late — callers that must never touch real hardware
(dryrun_multichip, CPU-only tools) pin through jax.config as well. This
module itself imports nothing heavy (the package root pulls in only
config.py), so it is safe to call before jax's first backend use.
"""

from __future__ import annotations

import os
import re


def virtual_host_env(devices_per_host: int) -> dict[str, str]:
    """Env vars that give a CHILD process a virtual CPU host with
    ``devices_per_host`` devices — the per-host half of a simulated pod
    (launch.py --num-processes N gives the other half). Used by the elastic
    soak (bench.py, tests/test_elastic_resume.py): N hosts x M fake devices
    re-form to a smaller N at the same M after a host loss.

    Unlike :func:`pin_virtual_cpu_mesh` this only RETURNS the env (for
    subprocess spawning); the child's own jax init applies it.
    """
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={int(devices_per_host)}",
    }


def ring_neighbors(host: int, ring_size: int) -> tuple[int, int]:
    """The two ICI-ring neighbors of ``host`` on a ``ring_size`` host ring.
    The simulated pod's inter-host topology is a single ring (the wraparound
    torus axis hosts sit on): host h links to (h-1) % N and (h+1) % N."""
    n = int(ring_size)
    h = int(host) % n
    return ((h - 1) % n, (h + 1) % n)


def is_contiguous_arc(hosts: list[int], ring_size: int) -> bool:
    """True iff ``hosts`` occupy one unbroken arc of the ring — every
    consecutive pair of survivors is ICI-adjacent, so ring collectives run
    at full link bandwidth instead of hopping over evicted hosts."""
    n = int(ring_size)
    members = sorted(set(int(h) % n for h in hosts))
    if len(members) <= 1 or len(members) == n:
        return bool(members)
    in_arc = set(members)
    # An arc of k hosts has exactly k-1 adjacent pairs along the ring,
    # equivalently exactly one "gap edge" endpoint pair. Walk from any
    # member forward until leaving the set; if we collected everyone, the
    # set is one arc.
    start = members[0]
    # Find an arc start: a member whose predecessor is NOT a member.
    for h in members:
        if (h - 1) % n not in in_arc:
            start = h
            break
    seen = 0
    h = start
    while h in in_arc and seen < len(members):
        seen += 1
        h = (h + 1) % n
    return seen == len(members)


def select_survivors(candidates: list[int], k: int,
                     ring_size: int) -> tuple[list[int], list[int]]:
    """Deterministic topology-aware shrink: from the live ``candidates``
    (original host ids on a ``ring_size`` ICI ring), keep the ``k`` hosts
    forming the most ring-contiguous subset. Scans every length-``k`` arc of
    the ring and keeps the one covering the most candidates (smallest start
    offset wins ties → fully deterministic); shortfall is filled from the
    remaining candidates walking the ring forward from the arc. Returns
    ``(survivors, rejected)``, both sorted ascending.

    With every candidate alive this always yields a contiguous arc; after
    scattered losses it yields the least-bisected subset reachable.
    """
    n = int(ring_size)
    alive = sorted(set(int(h) % n for h in candidates))
    k = int(k)
    if k >= len(alive):
        return alive, []
    if k <= 0:
        return [], alive
    alive_set = set(alive)
    best_start, best_score = 0, -1
    for start in range(n):
        score = sum(1 for i in range(k) if (start + i) % n in alive_set)
        if score > best_score:
            best_start, best_score = start, score
    chosen = [(best_start + i) % n for i in range(k)
              if (best_start + i) % n in alive_set]
    # Fill any shortfall by walking forward from the arc's end — keeps the
    # patched-in hosts as close to the arc as the ring allows.
    offset = k
    while len(chosen) < k and offset < k + n:
        h = (best_start + offset) % n
        if h in alive_set and h not in chosen:
            chosen.append(h)
        offset += 1
    survivors = sorted(chosen)
    rejected = sorted(alive_set - set(survivors))
    return survivors, rejected


def pin_virtual_cpu_mesh(n_devices: int = 8) -> None:
    """Force an ``n_devices`` virtual-CPU platform before any backend init."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
