"""Virtual-CPU-mesh pinning for tools, tests, and driver entry points.

The container's axon sitecustomize imports jax at interpreter start and
pins ``jax_platforms`` at a remote TPU whose backend init can hang, so env
vars alone are too late — callers that must never touch real hardware
(dryrun_multichip, CPU-only tools) pin through jax.config as well. This
module itself imports nothing heavy (the package root pulls in only
config.py), so it is safe to call before jax's first backend use.
"""

from __future__ import annotations

import os
import re


def virtual_host_env(devices_per_host: int) -> dict[str, str]:
    """Env vars that give a CHILD process a virtual CPU host with
    ``devices_per_host`` devices — the per-host half of a simulated pod
    (launch.py --num-processes N gives the other half). Used by the elastic
    soak (bench.py, tests/test_elastic_resume.py): N hosts x M fake devices
    re-form to a smaller N at the same M after a host loss.

    Unlike :func:`pin_virtual_cpu_mesh` this only RETURNS the env (for
    subprocess spawning); the child's own jax init applies it.
    """
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={int(devices_per_host)}",
    }


def pin_virtual_cpu_mesh(n_devices: int = 8) -> None:
    """Force an ``n_devices`` virtual-CPU platform before any backend init."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
