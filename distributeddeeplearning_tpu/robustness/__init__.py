"""Robustness tooling: deterministic fault injection (faults.py) and the
chaos-harness helpers that exercise the recovery paths end-to-end
(docs/fault_tolerance.md)."""
