"""Deterministic fault injection — the chaos side of the recovery story.

The reference's failure model is "mpirun dies whole, the scheduler resubmits,
training resumes from the last checkpoint" (SURVEY.md §5.3). The repo's
recovery machinery (checkpoint-resume, the launcher's fail-whole monitor +
restart wrapper, the loop's SIGTERM-save path, the bad-step guard, the loader
watchdog) only earns trust if faults can be injected *deterministically* and
*in combination* — a mid-run kill AND a corrupted checkpoint AND a NaN step
in one scripted run. This module is that script.

A **fault plan** is a comma-separated list of ``kind@step`` entries::

    sigkill@6,corrupt_latest_ckpt@6,nan_grads@5
    crash@3                      # SystemExit after completing step 3
    loader_stall@4:2.5s          # the pull of batch 4 sleeps 2.5 s
    sigterm@4:a1                 # fires on restart attempt 1 only
    crash@3:always               # re-fires on every restart attempt

Kinds
-----
- ``crash@N``      — raise SystemExit after completing step N (checkpoint
  writes are awaited first, like the legacy ``--fail-at-step``).
- ``sigterm@N``    — deliver SIGTERM to self after step N; exercises the
  loop's preemption handler (save-at-next-boundary, then exit).
- ``sigkill@N``    — SIGKILL to self after step N: a hard death with no
  cleanup, the closest model of a preempted/failed host.
- ``nan_grads@N``  — the compiled train step poisons the gradients of the
  update that completes step N (compiled in ONLY when the plan asks for it,
  so a plan-free run's hot path carries zero injection code).
- ``loader_stall@N[:Ts]`` — the host-streaming data source sleeps T seconds
  (default 5) before yielding the batch for step N; exercises the loader
  watchdog.
- ``corrupt_latest_ckpt@N`` — after step N (and after awaiting the async
  save), garbage the newest committed checkpoint's files on disk, leaving
  its commit marker intact so it still *looks* restorable; exercises the
  restore path's quarantine-and-fall-back.
- ``host_lost@N``   — a permanently-dead host: SIGKILL to self *after
  removing this process's heartbeat file*. A plain ``sigkill`` leaves a
  fresh heartbeat behind (the process was alive moments ago — a transient
  crash); a lost host's heartbeat vanishes with the host. The elastic
  membership controller (``launch.py --elastic``) must tell the two apart
  from the heartbeat evidence alone and re-form at the surviving degree
  instead of burning the restart budget retrying a dead rank.
- ``host_rejoin@N`` — the counterpart: after step N, touch the rejoin
  marker in the heartbeat directory, exactly as a repaired host's launcher
  would, then keep training. The elastic controller stops the job at the
  next step boundary (graceful preemption save) and re-forms at the grown
  degree. Fired from a *surviving* process — the dead host has no process
  to fire from.
- ``host_join@N``   — rendezvous-scoped grow: after step N, announce a NEW
  host at the rendezvous (same marker file as ``host_rejoin``, kind-tagged
  ``host_join``), then keep training. The membership controller raises the
  reform barrier; every member drains voluntarily at its next step boundary
  (exit code 75) and the job re-forms at the grown degree — no teardown of
  surviving children.
- ``host_drain@N``  — rendezvous-scoped planned leave: after step N, write
  this host's drain marker (original host id from ``DDL_ELASTIC_HOST``),
  then keep training. Unlike ``host_lost`` every member is still alive, so
  the barrier is save-capable: members checkpoint collectively before
  exiting and the re-formed attempt resumes one step behind the drain
  point, not from the last periodic save.

Serve-scoped kinds (fired at ``serve/engine.py`` step boundaries; ``crash``
and ``sigkill`` are shared with training and mean the same thing there —
the replica dies mid-decode):

- ``page_leak@N``          — after engine step N, allocate one KV page and
  drop it on the floor: held by the allocator, owned by no slot. The
  engine's integrity check (``Engine.check_integrity``) must catch it at
  the next step boundary and fail the replica loudly.
- ``decode_stall@N[:Ts]``  — the decode of engine step N sleeps T seconds
  (default 5) first; exercises deadline enforcement and the brownout path.
- ``corrupt_page_table@N`` — after step N, scribble over a live slot's
  host page-table row; the integrity check must detect the divergence
  from the slot's owned pages before the corrupt row reaches a dispatch.

Qualifiers (colon-separated, any order): ``aK`` — fire only on restart
attempt K (the launcher's ``run_with_restarts`` exports the attempt index as
``DDL_RESTART_ATTEMPT``); ``always`` — fire on every attempt; ``<float>s`` —
stall duration for ``loader_stall``. Default is attempt 0 only, so a
restarted job replays the step range clean — which is what lets the chaos
soak (tests/test_faults.py) demand bitwise-identical final params vs a
fault-free run.

Plans come from ``--fault-plan`` / ``TrainConfig.fault_plan``, from the
``DDL_FAULT_PLAN`` env var (the launcher's ``--child-fault-plan`` sets it
per child, faulting one process of a multi-process job), and from the legacy
``fail_at_step`` flag (shimmed to ``crash@N:always``, preserving its
re-fires-on-resume semantics). This module is pure stdlib — the data
pipeline, launcher, and train loop all import it.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

ENV_PLAN = "DDL_FAULT_PLAN"
ENV_ATTEMPT = "DDL_RESTART_ATTEMPT"

ALWAYS = -1  # Fault.attempt sentinel: fire on every restart attempt

KINDS = frozenset({
    "crash", "sigterm", "sigkill", "nan_grads", "loader_stall",
    "corrupt_latest_ckpt", "host_lost", "host_rejoin",
    "host_join", "host_drain",
    "page_leak", "decode_stall", "corrupt_page_table",
})
# Faults the train loop fires between steps (vs nan_grads: compiled into the
# step; loader_stall: injected into the data source).
_PROCESS_KINDS = frozenset({
    "crash", "sigterm", "sigkill", "corrupt_latest_ckpt",
    "host_lost", "host_rejoin", "host_join", "host_drain"})
# Faults the serve engine understands. crash/sigkill are shared with
# training; the rest only make sense against a live engine.
SERVE_KINDS = frozenset({
    "crash", "sigkill", "page_leak", "decode_stall", "corrupt_page_table"})
# Serve faults fired at the step boundary (vs decode_stall: injected into
# the step itself, before the decode dispatch).
_SERVE_BOUNDARY_KINDS = SERVE_KINDS - {"decode_stall"}


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str
    step: int
    attempt: int = 0      # restart attempt this fires on; ALWAYS = every one
    seconds: float = 5.0  # loader_stall duration

    def describe(self) -> str:
        return f"{self.kind}@{self.step}"


def current_attempt() -> int:
    """This process's restart-attempt index (0 = first launch), exported by
    ``launch.run_with_restarts`` so faults can be scoped per attempt."""
    try:
        return int(os.environ.get(ENV_ATTEMPT, "0"))
    except ValueError:
        return 0


def parse_plan(text: str) -> tuple[Fault, ...]:
    """Parse the ``kind@step[:qualifier...]`` grammar. Raises ValueError on
    anything it does not understand — a fault plan that silently parses to
    nothing would fake chaos coverage."""
    faults = []
    for raw in text.split(","):
        entry = raw.strip()
        if not entry:
            continue
        kind, sep, rest = entry.partition("@")
        kind = kind.strip()
        if not sep:
            raise ValueError(
                f"bad fault entry {entry!r}: expected kind@step[:qualifier"
                f"...] (e.g. sigkill@6, loader_stall@3:2.5s, crash@4:a1)")
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {entry!r}; expected one of "
                f"{sorted(KINDS)}")
        bits = rest.split(":")
        try:
            step = int(bits[0])
        except ValueError:
            raise ValueError(
                f"bad fault step in {entry!r}: {bits[0]!r} is not an "
                f"integer") from None
        if step <= 0:
            raise ValueError(f"fault step must be positive in {entry!r}")
        attempt, seconds = 0, 5.0
        for q in bits[1:]:
            q = q.strip()
            if q == "always":
                attempt = ALWAYS
            elif len(q) > 1 and q[0] == "a" and q[1:].isdigit():
                attempt = int(q[1:])
            elif q.endswith("s"):
                try:
                    seconds = float(q[:-1])
                except ValueError:
                    raise ValueError(
                        f"bad stall duration {q!r} in {entry!r}") from None
                if seconds < 0:
                    raise ValueError(
                        f"stall duration must be >= 0 in {entry!r}")
            else:
                raise ValueError(
                    f"unknown fault qualifier {q!r} in {entry!r} (expected "
                    f"aN, always, or <seconds>s)")
        faults.append(Fault(kind, step, attempt, seconds))
    return tuple(faults)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """The faults live for THIS process on THIS restart attempt."""

    faults: tuple[Fault, ...] = ()

    def __bool__(self) -> bool:
        return bool(self.faults)

    def boundary_steps(self) -> tuple[int, ...]:
        """Steps where the loop must take host-side action (block splits for
        the fused runner): every step-scoped fault."""
        return tuple(sorted({f.step for f in self.faults}))

    def nan_grad_steps(self) -> tuple[int, ...]:
        """``state.step`` values at which the compiled step poisons grads:
        ``nan_grads@N`` hits the update advancing step N-1 -> N."""
        return tuple(sorted(
            {f.step - 1 for f in self.faults if f.kind == "nan_grads"}))

    def loader_stalls(self) -> dict[int, float]:
        """step -> stall seconds for the host-streaming data source."""
        return {f.step: f.seconds for f in self.faults
                if f.kind == "loader_stall"}

    def process_faults_at(self, step: int) -> tuple[Fault, ...]:
        """Process-level faults at ``step``, in plan order — order matters
        (corrupt-then-kill is not kill-then-corrupt)."""
        return tuple(f for f in self.faults
                     if f.step == step and f.kind in _PROCESS_KINDS)

    def serve_stalls(self) -> dict[int, float]:
        """engine step -> stall seconds injected before that step's decode."""
        return {f.step: f.seconds for f in self.faults
                if f.kind == "decode_stall"}

    def serve_faults_at(self, step: int) -> tuple[Fault, ...]:
        """Serve boundary faults at engine ``step``, in plan order (leak-
        then-kill is not kill-then-leak)."""
        return tuple(f for f in self.faults
                     if f.step == step and f.kind in _SERVE_BOUNDARY_KINDS)

    @property
    def has_serve_boundary_faults(self) -> bool:
        return any(f.kind in _SERVE_BOUNDARY_KINDS for f in self.faults)

    @property
    def has_process_faults(self) -> bool:
        return any(f.kind in _PROCESS_KINDS for f in self.faults)

    def validate(self, total_steps: int, *,
                 checkpoint_dir: Optional[str] = None) -> None:
        for f in self.faults:
            if f.step > total_steps:
                raise ValueError(
                    f"{f.describe()} is beyond total_steps={total_steps}; "
                    f"the injected fault would never fire")
            if f.kind == "corrupt_latest_ckpt" and not checkpoint_dir:
                raise ValueError(
                    f"{f.describe()} needs a checkpoint_dir — there is no "
                    f"checkpoint to corrupt")


def resolve(config=None) -> FaultPlan:
    """The effective plan for this process: ``config.fault_plan`` +
    ``DDL_FAULT_PLAN`` (per-child injection) + the legacy ``fail_at_step``
    shim, filtered down to the current restart attempt. With no plan
    configured this returns an empty (falsy) plan and every injection site
    compiles/installs nothing."""
    parts: list[Fault] = []
    if config is not None:
        text = getattr(config, "fault_plan", None)
        if text:
            parts.extend(parse_plan(text))
        fail_at = getattr(config, "fail_at_step", None)
        if fail_at is not None:
            # Deprecation shim: the single-fault flag is exactly crash@N,
            # with ALWAYS semantics (the flag re-fired on resumed runs that
            # passed it again — attempt scoping arrived with plans).
            parts.append(Fault("crash", int(fail_at), attempt=ALWAYS))
    env_text = os.environ.get(ENV_PLAN)
    if env_text:
        parts.extend(parse_plan(env_text))
    attempt = current_attempt()
    return FaultPlan(tuple(
        f for f in parts if f.attempt in (ALWAYS, attempt)))


def resolve_serve(extra: Optional[str] = None) -> FaultPlan:
    """The effective serve-side plan for this engine: an explicit plan text
    (``Engine(fault_plan=...)`` / bench ``--chaos``) merged with
    ``DDL_FAULT_PLAN`` (the supervisor's per-replica injection), filtered to
    the current restart attempt and to serve-relevant kinds. Attempt scoping
    is what makes a warm-restarted replica replay its victims clean: the
    default attempt-0 fault does not re-fire under ``DDL_RESTART_ATTEMPT=1``.
    """
    parts: list[Fault] = []
    if extra:
        parts.extend(parse_plan(extra))
    env_text = os.environ.get(ENV_PLAN)
    if env_text:
        parts.extend(parse_plan(env_text))
    attempt = current_attempt()
    return FaultPlan(tuple(
        f for f in parts
        if f.attempt in (ALWAYS, attempt) and f.kind in SERVE_KINDS))


def stream_guard_kwargs(config, *, train: bool = True) -> dict:
    """Watchdog + stall-injection kwargs for StreamSource, derived from the
    config (DataConfig.loader_timeout_s/loader_retries) and the resolved
    plan. Empty dict = watchdog off, no injection — the default."""
    kw: dict = {}
    data = getattr(config, "data", None)
    timeout_s = float(getattr(data, "loader_timeout_s", 0.0) or 0.0)
    if timeout_s > 0:
        kw["timeout_s"] = timeout_s
        kw["max_retries"] = int(getattr(data, "loader_retries", 2))
    if train:
        stalls = resolve(config).loader_stalls()
        if stalls:
            kw["stall_steps"] = stalls
    return kw


# ---------------------------------------------------------------------------
# Loop-side injector (process-level faults)
# ---------------------------------------------------------------------------

def make_injector(plan: FaultPlan, ckpt, checkpoint_dir: Optional[str]):
    """A per-step callable firing the plan's process-level faults, or None
    when the plan has none — the loop then executes zero fault code per
    step (one ``is not None`` check)."""
    if not plan.has_process_faults:
        return None
    steps_with_faults = {f.step for f in plan.faults
                         if f.kind in _PROCESS_KINDS}

    def fire(step: int) -> None:
        if step not in steps_with_faults:
            return
        for f in plan.process_faults_at(step):
            _fire_one(f, step, ckpt, checkpoint_dir)

    return fire


def _fire_one(fault: Fault, step: int, ckpt, checkpoint_dir) -> None:
    import sys

    from distributeddeeplearning_tpu.observability import flight, telemetry

    # Instant event BEFORE firing: sigkill/crash never return, and the
    # surviving buffer is exported by the loop's finally (sigkill loses the
    # attempt's unexported events by design — that is what sigkill means).
    telemetry.get().instant(f"fault:{fault.kind}", step=step)
    # The flight record is the one that SURVIVES sigkill: appended and
    # fsync'd here, before the fault fires.
    flight.get().record("fault", kind=fault.kind, step=step)
    if fault.kind == "corrupt_latest_ckpt":
        if ckpt is not None:
            ckpt.wait()  # damage a COMMITTED save, not an in-flight one
        hit = corrupt_latest_checkpoint(checkpoint_dir)
        print(f"# fault injection: corrupted checkpoint step {hit} in "
              f"{checkpoint_dir}", file=sys.stderr, flush=True)
    elif fault.kind == "sigterm":
        import signal
        print(f"# fault injection: SIGTERM to self after step {step}",
              file=sys.stderr, flush=True)
        # The loop's preemption handler (when installed) turns this into a
        # forced save + clean-ish exit; without a handler the process dies
        # with the default disposition — both are the point of the fault.
        os.kill(os.getpid(), signal.SIGTERM)
    elif fault.kind == "sigkill":
        import signal
        print(f"# fault injection: SIGKILL to self after step {step}",
              file=sys.stderr, flush=True)
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    elif fault.kind == "host_lost":
        import signal

        from distributeddeeplearning_tpu.observability import health

        # A lost host takes its heartbeat with it: suppress the file FIRST,
        # then die with no cleanup. The launcher's membership controller
        # must attribute host-loss from the missing heartbeat — the kill
        # itself looks exactly like a transient sigkill.
        hb = health.HeartbeatWriter.from_env()
        if hb is not None:
            try:
                os.remove(hb.path)
            except OSError:
                pass
        print(f"# fault injection: host lost after step {step} "
              f"(heartbeat suppressed, SIGKILL to self)",
              file=sys.stderr, flush=True)
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    elif fault.kind == "host_rejoin":
        from distributeddeeplearning_tpu.observability import health

        directory = os.environ.get(health.ENV_HEARTBEAT_DIR)
        if directory:
            health.announce_rejoin(directory)
            print(f"# fault injection: host rejoin announced after step "
                  f"{step}", file=sys.stderr, flush=True)
        else:
            print(f"# fault injection: host_rejoin@{step} ignored — no "
                  f"{health.ENV_HEARTBEAT_DIR} (not under a heartbeat-"
                  f"armed launcher)", file=sys.stderr, flush=True)
    elif fault.kind == "host_join":
        from distributeddeeplearning_tpu.observability import health

        directory = os.environ.get(health.ENV_HEARTBEAT_DIR)
        if directory:
            health.announce_join(directory)
            print(f"# fault injection: host join announced after step "
                  f"{step}", file=sys.stderr, flush=True)
        else:
            print(f"# fault injection: host_join@{step} ignored — no "
                  f"{health.ENV_HEARTBEAT_DIR} (not under a heartbeat-"
                  f"armed launcher)", file=sys.stderr, flush=True)
    elif fault.kind == "host_drain":
        from distributeddeeplearning_tpu.observability import health

        directory = os.environ.get(health.ENV_HEARTBEAT_DIR)
        if directory:
            health.announce_drain(directory)
            print(f"# fault injection: host drain announced after step "
                  f"{step}", file=sys.stderr, flush=True)
        else:
            print(f"# fault injection: host_drain@{step} ignored — no "
                  f"{health.ENV_HEARTBEAT_DIR} (not under a heartbeat-"
                  f"armed launcher)", file=sys.stderr, flush=True)
    elif fault.kind == "crash":
        if ckpt is not None:
            ckpt.wait()
        raise SystemExit(f"fault injection: killed after step {step}")


# ---------------------------------------------------------------------------
# Serve-side injector (fired by Engine.step at its step boundaries)
# ---------------------------------------------------------------------------

def make_serve_injector(plan: FaultPlan, engine):
    """A per-step callable firing the plan's serve boundary faults against
    ``engine``, or None when the plan has none — a plan-free engine then
    executes zero fault code per step (one ``is not None`` check), matching
    the training loop's discipline."""
    if not plan.has_serve_boundary_faults:
        return None
    steps_with_faults = {f.step for f in plan.faults
                         if f.kind in _SERVE_BOUNDARY_KINDS}

    def fire(step: int) -> None:
        if step not in steps_with_faults:
            return
        for f in plan.serve_faults_at(step):
            _fire_serve(f, step, engine)

    return fire


def _fire_serve(fault: Fault, step: int, engine) -> None:
    import sys

    from distributeddeeplearning_tpu.observability import flight

    # Same fsync-before-fire discipline as _fire_one: the flight record is
    # appended and fsync'd BEFORE the fault fires, so a sigkill'd replica
    # still leaves an attributable record behind.
    flight.get().record("fault", kind=fault.kind, step=step, scope="serve")
    if fault.kind == "sigkill":
        import signal
        print(f"# fault injection: SIGKILL to serve replica after engine "
              f"step {step}", file=sys.stderr, flush=True)
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    elif fault.kind == "crash":
        raise SystemExit(
            f"fault injection: serve replica killed after engine step "
            f"{step}")
    elif fault.kind == "page_leak":
        leaked = engine.allocator.alloc(1)
        print(f"# fault injection: leaked KV page(s) {leaked} after engine "
              f"step {step}", file=sys.stderr, flush=True)
    elif fault.kind == "corrupt_page_table":
        slot = engine.corrupt_page_table()
        if slot is None:
            print(f"# fault injection: corrupt_page_table@{step} ignored — "
                  f"no live slot", file=sys.stderr, flush=True)
        else:
            print(f"# fault injection: corrupted page-table row of slot "
                  f"{slot} after engine step {step}",
                  file=sys.stderr, flush=True)


def corrupt_latest_checkpoint(directory: str) -> Optional[int]:
    """Deterministically damage the newest committed checkpoint step:
    garbage bytes over its array/metadata files, commit marker left intact
    so the step still *claims* to be restorable — the shape of a partial or
    bit-rotted write that the restore path must quarantine. Returns the
    damaged step, or None when there is nothing to damage."""
    if not directory or not os.path.isdir(directory):
        return None
    steps = [int(e) for e in os.listdir(directory) if e.isdigit()]
    if not steps:
        return None
    step = max(steps)
    root = os.path.join(directory, str(step))
    hit = 0
    for dirpath, _, filenames in os.walk(root):
        for name in filenames:
            if name == "_CHECKPOINT_METADATA":
                continue  # the commit marker: the step must stay "latest"
            try:
                with open(os.path.join(dirpath, name), "wb") as fh:
                    fh.write(b"\x00DDL_FAULT_CORRUPTED\x00")
                hit += 1
            except OSError:
                pass
    return step if hit else None
