"""Shared attention-mask helpers for the blockwise kernels.

One source of truth for the global-position causal triangle used by both
the Pallas flash kernels (ops/flash_attention.py, per grid block) and ring
attention (parallel/ring_attention.py, per ring step). Built from
``broadcasted_iota`` so it lowers inside Pallas kernel bodies and plain
jitted code alike.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def block_causal_mask(q_block, k_block, sq: int, sk: int):
    """(sq, sk) bool: global kv position <= global q position, for the
    query block at index ``q_block`` (rows sized sq) against the key block
    at index ``k_block`` (cols sized sk). Block indices may be traced."""
    qpos = q_block * sq + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    kpos = k_block * sk + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return kpos <= qpos
