"""Pallas TPU kernels for the hot ops.

XLA's fusion covers most of the framework (SURVEY.md §1b); these kernels take
over where fusion can't: flash attention keeps the (S, S) score matrix out of
HBM entirely, computing softmax online in VMEM blocks on the MXU.
"""

from distributeddeeplearning_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_sharded,
)
