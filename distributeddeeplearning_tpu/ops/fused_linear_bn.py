"""Matmul with BN prologue/epilogue — conv-epilogue fusion for 1x1 convs.

Why (BASELINE.md "On-chip A/B", 2026-07-31): standalone inter-conv BN
kernels lose to XLA end to end — they add full activation passes while
XLA's epilogue fusions get elementwise BN work for free inside passes the
convolutions already make. The only Pallas shape that can win fuses the BN
work INTO the matmul: this module's ops stream the raw previous-layer
output through VMEM, normalize it on the VPU as a *prologue* (no separate
apply pass, no materialized normalized tensor), feed the MXU, and
accumulate the output's per-channel Σy/Σy² as an *epilogue* (no separate
statistics pass). A ResNet bottleneck's 1x1 convolutions are exactly
matmuls over M = B·H·W rows, so they take this path; 3x3/7x7 convolutions
stay on the XLA conv path.

Math. With per-input-channel vectors μ, inv (=rsqrt(var+ε)), γ, β:

    x̂ = (x_raw − μ)·inv          a = relu(x̂·γ + β)        y = a @ w
    s = Σ_m y                     ss = Σ_m y²               (per out-channel)

μ and inv are *differentiable inputs* (the caller derives them from the
previous op's s/ss outputs), so unlike a self-contained BatchNorm VJP the
backward here needs no −mean/−x̂·cov correction terms inside the kernel:

    dY = dy + ds + 2·y·dss        (epilogue-sum cotangents folded in)
    da = dY @ wᵀ                  dzl = da·1[a>0]
    dx_raw = dzl·γ·inv            (pure elementwise — written by the da
                                   kernel's epilogue, no separate pass)
    dβ = Σ_m dzl                  dγ = Σ_m dzl·x̂   (da-kernel epilogue)
    dμ = −γ·inv·dβ                dinv = γ·dγ/inv   (vector math, outside)
    dw = aᵀ @ dY                  (second kernel; a, dY recomputed in its
                                   prologue from streamed x_raw, y tiles)

So training traffic is two matmuls forward-equivalents backward and ONE
matmul forward, with every BN read riding a tile the MXU already needs.
Statistics are taken over y as stored (bf16) so they match exactly what
the next layer's prologue will normalize.

All kernels read bf16, accumulate float32 (MXU preferred_element_type and
VMEM scratch), and run in interpret mode off-TPU with jnp twins under
shard_map's check_vma — same policy as ops/fused_batchnorm.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributeddeeplearning_tpu.ops.fused_batchnorm import (
    _jnp_twin, _match_vma, _should_interpret, _struct, _tile)


def _tiles(m: int, k: int, n: int):
    return _tile(m, 512), _tile(k, 512), _tile(n, 512)


# ---------------------------------------------------------------------------
# Forward: y = prologue(x) @ w, epilogue Σy / Σy²
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, mu_ref, inv_ref, g_ref, b_ref,
                y_ref, s_ref, ss_ref, acc, s_scr, ss_scr, *,
                relu: bool, bn: bool, nk: int):
    mi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    @pl.when((ki == 0) & (mi == 0))
    def _():
        s_scr[...] = jnp.zeros_like(s_scr)
        ss_scr[...] = jnp.zeros_like(ss_scr)

    a = x_ref[...]
    if bn:
        af = (a.astype(jnp.float32) - mu_ref[...]) * (inv_ref[...]
                                                      * g_ref[...])
        af = af + b_ref[...]
        if relu:
            af = jnp.maximum(af, 0.0)
        a = af.astype(x_ref.dtype)
    acc[...] += jax.lax.dot(a, w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        y = acc[...].astype(y_ref.dtype)
        y_ref[...] = y
        yf = y.astype(jnp.float32)
        s_scr[...] += yf.sum(axis=0, keepdims=True)
        ss_scr[...] += (yf * yf).sum(axis=0, keepdims=True)

    @pl.when((ki == nk - 1) & (mi == pl.num_programs(1) - 1))
    def _():
        s_ref[...] = s_scr[...]
        ss_ref[...] = ss_scr[...]


def _fwd(x, mu, inv, gamma, beta, w, relu, bn,
         interpret: Optional[bool] = None):
    m, k = x.shape
    n = w.shape[1]
    tm, tk, tn = _tiles(m, k, n)
    nk = k // tk
    interp = _should_interpret() if interpret is None else interpret
    xs = pl.BlockSpec((tm, tk), lambda ni, mi, ki: (mi, ki))
    ws = pl.BlockSpec((tk, tn), lambda ni, mi, ki: (ki, ni))
    vk = pl.BlockSpec((1, tk), lambda ni, mi, ki: (0, ki))
    ys = pl.BlockSpec((tm, tn), lambda ni, mi, ki: (mi, ni))
    vn = pl.BlockSpec((1, tn), lambda ni, mi, ki: (0, ni))
    y, s, ss = pl.pallas_call(
        functools.partial(_fwd_kernel, relu=relu, bn=bn, nk=nk),
        grid=(n // tn, m // tm, nk),
        in_specs=[xs, ws, vk, vk, vk, vk],
        out_specs=[ys, vn, vn],
        out_shape=[_struct((m, n), x.dtype, x),
                   _struct((1, n), jnp.float32, x),
                   _struct((1, n), jnp.float32, x)],
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32),
                        pltpu.VMEM((1, tn), jnp.float32),
                        pltpu.VMEM((1, tn), jnp.float32)],
        interpret=interp,
    )(x, w, mu[None], inv[None], gamma[None], beta[None])
    return y, s[0], ss[0]


# ---------------------------------------------------------------------------
# Backward kernel 1: da = dY @ wᵀ; epilogue writes dx directly + dβ/dγ sums
# ---------------------------------------------------------------------------

def _bwd_dx_kernel(dy_ref, y_ref, ds_ref, dss_ref, w_ref, x_ref,
                   mu_ref, inv_ref, g_ref, b_ref,
                   dx_ref, db_ref, dg_ref, acc, db_scr, dg_scr, *,
                   relu: bool, bn: bool, nn: int):
    mi, ni = pl.program_id(1), pl.program_id(2)

    @pl.when(ni == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    @pl.when((ni == 0) & (mi == 0))
    def _():
        db_scr[...] = jnp.zeros_like(db_scr)
        dg_scr[...] = jnp.zeros_like(dg_scr)

    y = y_ref[...].astype(jnp.float32)
    dyf = (dy_ref[...].astype(jnp.float32) + ds_ref[...]
           + 2.0 * y * dss_ref[...])
    # Contract over the out-channel axis of both dY (tm,tn) and w (tk,tn).
    acc[...] += jax.lax.dot_general(
        dyf.astype(dy_ref.dtype), w_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ni == nn - 1)
    def _():
        da = acc[...]
        if bn:
            xh = ((x_ref[...].astype(jnp.float32) - mu_ref[...])
                  * inv_ref[...])
            dzl = da
            if relu:
                z = xh * g_ref[...] + b_ref[...]
                dzl = jnp.where(z > 0, da, 0.0)
            dx_ref[...] = (dzl * (g_ref[...] * inv_ref[...])).astype(
                dx_ref.dtype)
            db_scr[...] += dzl.sum(axis=0, keepdims=True)
            dg_scr[...] += (dzl * xh).sum(axis=0, keepdims=True)
        else:
            dx_ref[...] = da.astype(dx_ref.dtype)

    @pl.when((ni == nn - 1) & (mi == pl.num_programs(1) - 1))
    def _():
        db_ref[...] = db_scr[...]
        dg_ref[...] = dg_scr[...]


def _bwd_dx(dy, y, ds, dss, w, x, mu, inv, gamma, beta, relu, bn,
            interpret: Optional[bool] = None):
    m, k = x.shape
    n = w.shape[1]
    tm, tk, tn = _tiles(m, k, n)
    nn = n // tn
    interp = _should_interpret() if interpret is None else interpret
    dys = pl.BlockSpec((tm, tn), lambda ki, mi, ni: (mi, ni))
    ws = pl.BlockSpec((tk, tn), lambda ki, mi, ni: (ki, ni))
    xs = pl.BlockSpec((tm, tk), lambda ki, mi, ni: (mi, ki))
    vn = pl.BlockSpec((1, tn), lambda ki, mi, ni: (0, ni))
    vk = pl.BlockSpec((1, tk), lambda ki, mi, ni: (0, ki))
    dx, db, dg = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, relu=relu, bn=bn, nn=nn),
        grid=(k // tk, m // tm, nn),
        in_specs=[dys, dys, vn, vn, ws, xs, vk, vk, vk, vk],
        out_specs=[xs, vk, vk],
        out_shape=[_struct((m, k), x.dtype, x),
                   _struct((1, k), jnp.float32, x),
                   _struct((1, k), jnp.float32, x)],
        scratch_shapes=[pltpu.VMEM((tm, tk), jnp.float32),
                        pltpu.VMEM((1, tk), jnp.float32),
                        pltpu.VMEM((1, tk), jnp.float32)],
        interpret=interp,
    )(dy, y, ds[None], dss[None], w, x, mu[None], inv[None],
      gamma[None], beta[None])
    return dx, db[0], dg[0]


# ---------------------------------------------------------------------------
# Backward kernel 2: dw = aᵀ @ dY, both operands recomputed in the prologue
# ---------------------------------------------------------------------------

def _bwd_dw_kernel(x_ref, mu_ref, inv_ref, g_ref, b_ref,
                   dy_ref, y_ref, ds_ref, dss_ref,
                   dw_ref, acc, *, relu: bool, bn: bool, nm: int):
    mi = pl.program_id(2)

    @pl.when(mi == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    a = x_ref[...]
    if bn:
        af = ((a.astype(jnp.float32) - mu_ref[...])
              * (inv_ref[...] * g_ref[...]) + b_ref[...])
        if relu:
            af = jnp.maximum(af, 0.0)
        a = af.astype(x_ref.dtype)
    y = y_ref[...].astype(jnp.float32)
    dyf = (dy_ref[...].astype(jnp.float32) + ds_ref[...]
           + 2.0 * y * dss_ref[...])
    # aᵀ @ dY: contract the row (M) axis of both tiles.
    acc[...] += jax.lax.dot_general(
        a, dyf.astype(dy_ref.dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(mi == nm - 1)
    def _():
        dw_ref[...] = acc[...].astype(dw_ref.dtype)


def _bwd_dw(x, mu, inv, gamma, beta, dy, y, ds, dss, relu, bn,
            interpret: Optional[bool] = None):
    m, k = x.shape
    n = dy.shape[1]
    tm, tk, tn = _tiles(m, k, n)
    nm = m // tm
    interp = _should_interpret() if interpret is None else interpret
    xs = pl.BlockSpec((tm, tk), lambda ki, ni, mi: (mi, ki))
    dys = pl.BlockSpec((tm, tn), lambda ki, ni, mi: (mi, ni))
    vk = pl.BlockSpec((1, tk), lambda ki, ni, mi: (0, ki))
    vn = pl.BlockSpec((1, tn), lambda ki, ni, mi: (0, ni))
    ws = pl.BlockSpec((tk, tn), lambda ki, ni, mi: (ki, ni))
    return pl.pallas_call(
        functools.partial(_bwd_dw_kernel, relu=relu, bn=bn, nm=nm),
        grid=(k // tk, n // tn, nm),
        in_specs=[xs, vk, vk, vk, vk, dys, dys, vn, vn],
        out_specs=ws,
        out_shape=_struct((k, n), dy.dtype, x),
        scratch_shapes=[pltpu.VMEM((tk, tn), jnp.float32)],
        interpret=interp,
    )(x, mu[None], inv[None], gamma[None], beta[None], dy, y,
      ds[None], dss[None])


# ---------------------------------------------------------------------------
# jnp twin (interpret-under-shard_map contexts) and the public custom-VJP op
# ---------------------------------------------------------------------------

def _twin_fwd(x, mu, inv, gamma, beta, w, relu, bn):
    a = x
    if bn:
        af = (x.astype(jnp.float32) - mu) * (inv * gamma) + beta
        if relu:
            af = jnp.maximum(af, 0.0)
        a = af.astype(x.dtype)
    y = jnp.dot(a, w, preferred_element_type=jnp.float32).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, yf.sum(axis=0), (yf * yf).sum(axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def bn_linear_stats(x, mu, inv, gamma, beta, w, relu: bool = True,
                    bn: bool = True):
    """y = relu((x−μ)·inv·γ + β) @ w with per-out-channel (Σy, Σy²).

    With ``bn=False`` the prologue is the identity (x is consumed as-is;
    μ/inv/γ/β are ignored but must still be (N_in,)-shaped arrays) — the
    shape used for matmuls whose input is already materialized, keeping
    only the statistics epilogue. Returns ``(y, s, ss)``.
    """
    y, s, ss = _fwd_any(x, mu, inv, gamma, beta, w, relu, bn)
    return y, s, ss


def _fwd_any(x, mu, inv, gamma, beta, w, relu, bn):
    if _jnp_twin(x):
        return _twin_fwd(x, mu, inv, gamma, beta, w, relu, bn)
    return _fwd(x, mu, inv, gamma, beta, w, relu, bn)


def _vjp_fwd(x, mu, inv, gamma, beta, w, relu, bn):
    y, s, ss = _fwd_any(x, mu, inv, gamma, beta, w, relu, bn)
    return (y, s, ss), (x, mu, inv, gamma, beta, w, y)


def _vjp_bwd(relu, bn, saved, cots):
    x, mu, inv, gamma, beta, w, y = saved
    dy, ds, dss = cots
    if _jnp_twin(x):
        dx, db, dg, dw = _twin_bwd(dy, ds, dss, x, mu, inv, gamma, beta,
                                   w, y, relu, bn)
    else:
        dx, db, dg = _bwd_dx(dy, y, ds, dss, w, x, mu, inv, gamma, beta,
                             relu, bn)
        dw = _bwd_dw(x, mu, inv, gamma, beta, dy, y, ds, dss, relu, bn)
    dw = _match_vma(dw, w)  # w is replicated under DP; psum its cotangent
    if not bn:
        zero = jnp.zeros_like(mu)
        return (dx, zero, zero, zero, zero, dw)
    dmu = -gamma * inv * db
    dinv = gamma * dg / inv
    return (dx,
            _match_vma(dmu, mu), _match_vma(dinv, inv),
            _match_vma(dg.astype(gamma.dtype), gamma),
            _match_vma(db.astype(beta.dtype), beta),
            dw)


def _twin_bwd(dy, ds, dss, x, mu, inv, gamma, beta, w, y, relu, bn):
    yf = y.astype(jnp.float32)
    dyf = dy.astype(jnp.float32) + ds + 2.0 * yf * dss
    da = jnp.dot(dyf.astype(dy.dtype), w.T,
                 preferred_element_type=jnp.float32)
    if bn:
        xh = (x.astype(jnp.float32) - mu) * inv
        dzl = da
        if relu:
            z = xh * gamma + beta
            dzl = jnp.where(z > 0, da, 0.0)
        dx = (dzl * (gamma * inv)).astype(x.dtype)
        db = dzl.sum(axis=0)
        dg = (dzl * xh).sum(axis=0)
        af = xh * gamma + beta
        if relu:
            af = jnp.maximum(af, 0.0)
        a = af.astype(x.dtype)
    else:
        dx = da.astype(x.dtype)
        db = dg = jnp.zeros_like(mu)
        a = x
    dw = jnp.dot(a.T, dyf.astype(dy.dtype),
                 preferred_element_type=jnp.float32).astype(dy.dtype)
    return dx, db, dg, dw


bn_linear_stats.defvjp(_vjp_fwd, _vjp_bwd)


def linear_stats(x, w):
    """y = x @ w with (Σy, Σy²) — the bn=False shape, for matmuls whose
    input is already a materialized activation."""
    zeros = jnp.zeros((x.shape[1],), jnp.float32)
    return bn_linear_stats(x, zeros, zeros, zeros, zeros, w, False, False)
