"""Flash attention as a Pallas TPU kernel (forward + custom-VJP backward).

Why a kernel at all: dense attention materializes the (S, S) probability
matrix in HBM — at BERT-base shapes that is B*H*S*S*4 bytes of write+read
traffic per layer, and HBM bandwidth is the TPU's usual bottleneck. These
kernels iterate a (batch*heads, Q-tiles, K-tiles) grid where each step holds
only (BLOCK, D) tiles of Q/K/V in VMEM — Pallas streams the tiles per grid
step — with the online-softmax running state (m, l, acc) carried across the
K dimension in f32 VMEM scratch. HBM traffic is O(S·D) per Q-tile row and
VMEM residency is O(BLOCK·D), so sequence length is bounded by HBM, not VMEM.

Key-padding mask, non-causal (BERT, models/bert.py) or causal
(``causal=True`` — GPT, models/gpt.py; above-diagonal blocks are skipped
entirely, halving FLOPs at large S). The backward pass recomputes block
scores from the saved
logsumexp (the flash recurrence) in two kernels: dq (accumulated over the
K-tile grid axis) and dk/dv (accumulated over the Q-tile grid axis); the
revisited output blocks stay resident in VMEM across the accumulation axis.

Kernels run compiled on TPU and in Pallas interpret mode elsewhere, so the
CPU test mesh exercises the same code path (SURVEY.md §4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributeddeeplearning_tpu import compat
from distributeddeeplearning_tpu.ops.masks import block_causal_mask

_NEG = -1e30


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


_PAD_GRANULE = 128  # TPU lane width; also the floor _block can return after
#                     flash_attention pads S to a multiple of it.


def _block(size: int, target: int) -> int:
    """Largest divisor of ``size`` not exceeding ``target``.

    Exact-divisor grids need no padding logic in the kernels, but a ``size``
    with no good divisor (e.g. a prime S > target) would degrade to a tiny
    block and a degenerate grid — a silent perf cliff (VERDICT r3 Weak #6).
    :func:`flash_attention` therefore pads S to a multiple of
    ``_PAD_GRANULE`` first, which guarantees a divisor >= min(size, 128);
    this function asserts that invariant for any future direct caller."""
    b = min(size, target)
    while size % b:
        b -= 1
    # A modestly smaller block (e.g. 48 for target 64) is fine; a block
    # FAR below the target (a prime S > target resolves to 1) means a
    # degenerate grid. Warn rather than raise — results stay correct, and
    # flash_attention's padding keeps its own calls out of here entirely.
    if b * 4 < min(size, target):
        import warnings

        warnings.warn(
            f"_block({size}, {target}) degenerated to {b}: the grid will "
            f"be severely under-tiled. Pad the sequence to a multiple of "
            f"{_PAD_GRANULE} (flash_attention does this automatically).",
            stacklevel=2)
    return b


# ---------------------------------------------------------------------------
# Forward: grid (B*H, nQ, nK); m/l/acc scratch carries across the K axis.
# ---------------------------------------------------------------------------

def _block_keep(seed_ref, pid, i, j, bq: int, bk: int, rate: float):
    """The (BQ, BK) keep-mask for block (i, j) of grid row ``pid``
    (= pl.program_id(0), hoisted to the kernel top level — program_id may
    not be bound under a pl.when body), in GLOBAL coordinates — the same
    mask regardless of which kernel (forward, dq, dk/dv) or block geometry
    asks for it. seed_ref (SMEM): [seed, b_start, h_start, h_local,
    h_total] — the last four place this shard's (batch, head) range in the
    global index space so the realized mask is sharding-invariant
    (dense == flash at any dp x tp)."""
    from distributeddeeplearning_tpu.ops.hash_dropout import keep_mask

    h_n = seed_ref[3]
    bh = ((seed_ref[1] + pid // h_n) * seed_ref[4]
          + seed_ref[2] + pid % h_n)
    rows = (jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0)
            + (i * bq).astype(jnp.uint32))
    cols = (jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1)
            + (j * bk).astype(jnp.uint32))
    return keep_mask(seed_ref[0], jnp.uint32(0) + bh.astype(jnp.uint32),
                     rows, cols, rate)


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, seed_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                dropout_rate: float):
    i, j = pl.program_id(1), pl.program_id(2)
    pid0 = pl.program_id(0)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def work():
        # Matmul operands stay in their storage dtype (bf16 on the training
        # path): the MXU takes bf16 inputs at full rate with f32 accumulation
        # via preferred_element_type — upcasting first would halve MXU
        # throughput and double VMEM traffic for zero precision gain.
        q = q_ref[0]                                      # (BQ, D)
        k = k_ref[0]                                      # (BK, D)
        v = v_ref[0]
        valid = jnp.broadcast_to((mask_ref[0, 0] != 0)[None, :], (bq, bk))
        if causal:
            valid = valid & block_causal_mask(i, j, bq, bk)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (BQ, BK) f32
        s = jnp.where(valid, s, _NEG)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        # l accumulates UNdropped p: dense semantics normalize first
        # (softmax), then drop — o = (softmax ∘ keep/(1-r)) v.
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _block_keep(seed_ref, pid0, i, j, bq, bk, dropout_rate)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # Blocks strictly above the diagonal contribute nothing — skip the
        # matmuls entirely (halves causal FLOPs at large S).
        pl.when(j * bk < (i + 1) * bq)(work)
    else:
        work()

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        l = l_scr[:]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # Fully-masked rows: zero output, lse pinned to 0 so backward's
        # exp(_NEG - 0) underflows to 0 rather than NaN.
        lse_ref[0, 0] = jnp.where(
            l[:, 0] > 0, m_scr[:][:, 0] + jnp.log(safe_l[:, 0]), 0.0)


def _fwd(q, k, v, mask, seed, *, scale, block_q, block_k, interpret, causal,
         dropout_rate):
    # Rank-1-per-tile operands (mask, lse) ride as (BH, 1, S) so every block
    # shape is rank >= 2 with a compiled-lowering-legal tail: Mosaic requires
    # the last two block dims be (multiples of, or equal to) the array dims —
    # a (1, BK) block over a (BH, S) array is not (VERDICT r1 #6, found on
    # first real-TPU run).
    bh, s, d = q.shape
    bq, bk = _block(s, block_q), _block(s, block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          dropout_rate=dropout_rate),
        grid=(bh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, mask[:, None, :], seed)
    return out, lse.reshape(bh, s)


# ---------------------------------------------------------------------------
# Backward: dq accumulates over the K grid axis; dk/dv over the Q grid axis.
# Scores are recomputed from the saved lse (flash recurrence).
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
               seed_ref, dq_ref, dq_scr, *, scale: float, causal: bool,
               dropout_rate: float):
    i, j = pl.program_id(1), pl.program_id(2)
    pid0 = pl.program_id(0)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def work():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        k = k_ref[0]
        v = v_ref[0]
        valid = jnp.broadcast_to((mask_ref[0, 0] != 0)[None, :], (bq, bk))
        if causal:
            valid = valid & block_causal_mask(i, j, bq, bk)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = jnp.where(valid, s, _NEG)
        p = jnp.exp(s - lse)                              # (BQ, BK)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # Regenerate the forward's exact mask. delta = sum(do*o)
            # already IS sum_k p*m*dp (o carries the dropped probs), so the
            # flash delta trick needs no dropout correction — only dp does:
            # ds = p * (m*dp - delta).
            keep = _block_keep(seed_ref, pid0, i, j, bq, bk, dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(j * bk < (i + 1) * bq)(work)
    else:
        work()

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                seed_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool, dropout_rate: float):
    j, i = pl.program_id(1), pl.program_id(2)  # j: K tile; i: Q (accum) tile
    pid0 = pl.program_id(0)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def work():
        k = k_ref[0]                                      # (BK, D)
        v = v_ref[0]
        q = q_ref[0]                                      # (BQ, D)
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        valid = jnp.broadcast_to((mask_ref[0, 0] != 0)[None, :], (bq, bk))
        if causal:
            valid = valid & block_causal_mask(i, j, bq, bk)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = jnp.where(valid, s, _NEG)
        p = jnp.exp(s - lse)                              # (BQ, BK)
        if dropout_rate > 0.0:
            # (i, j) here are the same logical (Q-tile, K-tile) indices the
            # forward used (the grid swaps their nesting, not their
            # meaning), so this regenerates the forward's exact mask.
            keep = _block_keep(seed_ref, pid0, i, j, bq, bk, dropout_rate)
            inv_keep = 1.0 / (1.0 - dropout_rate)
            p_drop = jnp.where(keep, p * inv_keep, 0.0)
        else:
            keep, p_drop = None, p
        dv_scr[:] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if keep is not None:
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = (p * (dp - delta) * scale).astype(q.dtype)   # (BQ, BK)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(j * bk < (i + 1) * bq)(work)
    else:
        work()

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, block_q, block_k, interpret, causal, dropout_rate,
         residuals, g):
    q, k, v, mask, seed, out, lse = residuals
    bh, s, d = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    # (BH, 1, S) lift for the rank-1-per-tile operands — see _fwd.
    mask3, lse3, delta3 = (x[:, None, :] for x in (mask, lse, delta))

    bq, bk = _block(s, block_q), _block(s, block_k)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    q_tile = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    k_tile = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    maskk = pl.BlockSpec((1, 1, bk), lambda b, i, j: (b, 0, j))
    vec_q = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          dropout_rate=dropout_rate),
        grid=(bh, s // bq, s // bk),
        in_specs=[q_tile, k_tile, k_tile, maskk, q_tile, vec_q, vec_q,
                  smem],
        out_specs=[q_tile],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, mask3, g, lse3, delta3, seed)[0]

    # dk/dv: K tiles are the revisited outputs, Q is the accumulation axis
    # (innermost grid dim), so swap the roles of the last two grid indices.
    q_acc = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    k_out = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    maskk2 = pl.BlockSpec((1, 1, bk), lambda b, j, i: (b, 0, j))
    vec_q2 = pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          dropout_rate=dropout_rate),
        grid=(bh, s // bk, s // bq),
        in_specs=[q_acc, k_out, k_out, maskk2, q_acc, vec_q2, vec_q2,
                  smem],
        out_specs=[k_out, k_out],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, mask3, g, lse3, delta3, seed)
    return dq, dk, dv, None, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, mask, seed, scale, block_q, block_k, interpret, causal,
           dropout_rate):
    out, _ = _fwd(q, k, v, mask, seed, scale=scale, block_q=block_q,
                  block_k=block_k, interpret=interpret, causal=causal,
                  dropout_rate=dropout_rate)
    return out


def _flash_fwd(q, k, v, mask, seed, scale, block_q, block_k, interpret,
               causal, dropout_rate):
    out, lse = _fwd(q, k, v, mask, seed, scale=scale, block_q=block_q,
                    block_k=block_k, interpret=interpret, causal=causal,
                    dropout_rate=dropout_rate)
    return out, (q, k, v, mask, seed, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(q, k, v, kv_mask=None, *, block_q: int = 512,
                    block_k: int = 1024, causal: bool = False,
                    interpret: Optional[bool] = None,
                    dropout_rate: float = 0.0, dropout_seed=None,
                    bh_offsets=None):
    """Fused attention with a key-padding mask; ``causal=True`` adds the
    autoregressive lower-triangular mask (and skips above-diagonal blocks).

    q/k/v: (B, S, H, D) — the models' layout; kv_mask: (B, S) (True/nonzero
    = attend), or None for all-valid. Returns (B, S, H, D) in q.dtype.
    Differentiable w.r.t. q/k/v via the flash backward kernels.

    ``dropout_rate`` > 0 applies attention-probability dropout INSIDE the
    kernels via a counter-based hash mask (ops/hash_dropout.py) that the
    backward kernels regenerate exactly — no (S, S) mask ever exists.
    ``dropout_seed``: int32 scalar (required when rate > 0). ``bh_offsets``:
    optional (b_start, h_start, h_total) placing this shard's batch/head
    range in global coordinates so the realized mask is sharding-invariant;
    defaults to the unsharded identity.
    """
    b, s, h, d = q.shape
    if interpret is None:
        interpret = _should_interpret()
    if kv_mask is None:
        kv_mask = jnp.ones((b, s), jnp.int32)
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("flash_attention: dropout_rate > 0 needs a "
                         "dropout_seed (int32 scalar)")
    b_start, h_start, h_total = (bh_offsets if bh_offsets is not None
                                 else (0, 0, h))
    seed = jnp.stack([
        jnp.asarray(dropout_seed if dropout_seed is not None else 0,
                    jnp.int32),
        jnp.asarray(b_start, jnp.int32), jnp.asarray(h_start, jnp.int32),
        jnp.asarray(h, jnp.int32), jnp.asarray(h_total, jnp.int32)])
    # Non-power-of-two S (ViT's 197, odd packed corpora): pad S to a lane
    # multiple so the block search can't degenerate (see _block). Padded
    # keys are masked out (zero attention weight everywhere, including the
    # backward's recomputed scores) and padded query rows are dead rows
    # sliced off below; grad flows through pad/slice transparently since
    # both sit outside the custom-VJP boundary.
    s_orig = s
    if s > _PAD_GRANULE and s % _PAD_GRANULE:
        pad = _PAD_GRANULE - s % _PAD_GRANULE
        q, k, v = (jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
                   for x in (q, k, v))
        kv_mask = jnp.pad(kv_mask.astype(jnp.int32), ((0, 0), (0, pad)))
        s += pad
    kv_mask = jnp.broadcast_to(
        kv_mask.astype(jnp.int32)[:, None, :], (b, h, s)).reshape(b * h, s)

    def to_bh(x):  # (B, S, H, D) -> (B*H, S, D)
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    out = _flash(to_bh(q), to_bh(k), to_bh(v), kv_mask, seed,
                 d ** -0.5, block_q, block_k, interpret, causal,
                 float(dropout_rate))
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)[:, :s_orig]


def flash_attention_sharded(q, k, v, kv_mask=None, *,
                            batch_axes=("data", "fsdp"),
                            head_axis: str = "model",
                            dropout_rate: float = 0.0, dropout_seed=None,
                            **kw):
    """GSPMD-embeddable flash attention: Pallas calls don't partition under
    jit's sharding propagation, so inside a sharded program the kernel must
    run per-shard via shard_map — batch over the DP axes, heads over
    ``model``, sequence local (for a sharded sequence use ring attention).

    Falls through to the plain kernel when no mesh context is active
    (single-device apply/tests). Dropout: each shard offsets its (batch,
    head) hash coordinates by its mesh position, so the realized mask is
    the same one the unsharded call produces — dp/tp sharding cannot change
    training semantics.
    """
    from jax.sharding import PartitionSpec as P

    mesh = compat.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return flash_attention(q, k, v, kv_mask,
                               dropout_rate=dropout_rate,
                               dropout_seed=dropout_seed, **kw)
    if mesh.shape.get("seq", 1) > 1:
        raise ValueError(
            "flash attention keeps the full sequence on every device and "
            "would silently all-gather a seq-sharded activation; with "
            "seq-axis parallelism use attention_impl='ring' instead")
    qkv_spec = P(batch_axes, None, head_axis, None)
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], jnp.int32)
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("flash_attention_sharded: dropout_rate > 0 needs "
                         "a dropout_seed")
    seed_arr = jnp.reshape(
        jnp.asarray(dropout_seed if dropout_seed is not None else 0,
                    jnp.int32), (1,))

    def fn(qs, ks, vs, ms, seed1):
        from distributeddeeplearning_tpu.ops.hash_dropout import (
            shard_bh_offsets)

        offs = shard_bh_offsets(batch_axes, head_axis, qs.shape[0],
                                qs.shape[2])
        return flash_attention(qs, ks, vs, ms,
                               dropout_rate=dropout_rate,
                               dropout_seed=seed1[0], bh_offsets=offs, **kw)

    # compat.shard_map runs check-off: pallas_call's out_shape carries no
    # varying-axes info; the body is pure per-shard compute (no
    # collectives), so the check adds nothing here.
    return compat.shard_map(
        fn, in_specs=(qkv_spec, qkv_spec, qkv_spec, P(batch_axes, None),
                      P(None)),
        out_specs=qkv_spec)(q, k, v, kv_mask, seed_arr)
