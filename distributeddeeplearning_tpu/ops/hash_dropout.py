"""Counter-based dropout masks for attention probabilities.

The problem (VERDICT r3 Missing #6): flash/ring/zigzag never materialize the
(S, S) probability matrix, so ``nn.Dropout``-over-probs only worked for the
dense impl — training regularization silently differed across impls.

The TPU-native answer is a *counter-based* mask: ``keep(b·h, row, col)`` is a
pure hash of the global coordinates and a per-call seed, so

- the flash backward kernels REGENERATE the forward's exact mask from block
  indices (no (S, S) mask tensor is ever stored or shipped to HBM);
- every impl (dense / flash / ring / zigzag) realizes the IDENTICAL mask for
  the same seed, which turns cross-impl dropout parity into an exact-equality
  test instead of a statistical one;
- the mask is independent of block sizes, ring schedules, and sharding
  (coordinates are global), so kernel tuning can never change training
  semantics.

The mixer is the murmur3 finalizer (full avalanche) over a linear combine of
the coordinates — measured uniform on this backend (mean .4985, std .2896 vs
ideal .2887 for 2^20 draws). Dropout needs decorrelation, not cryptography;
the finalizer is 5 VPU ops per element and works identically in compiled
Mosaic and Pallas interpret mode (the TPU PRNG primitive does not lower on
CPU interpret — measured NotImplementedError — which rules it out here: the
CPU test mesh must execute the same code path).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from distributeddeeplearning_tpu import compat

# Odd 32-bit constants for the coordinate combine (golden-ratio family) and
# the murmur3 finalizer multipliers.
_C_ROW = 0x9E3779B9
_C_COL = 0x85EBCA6B
_C_BH = 0xC2B2AE35
_M1 = 0x7FEB352D
_M2 = 0x846CA68B


def _mix32(h):
    """murmur3-style finalizer: full avalanche on uint32."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_M1)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(_M2)
    return h ^ (h >> 16)


def keep_mask(seed, bh, rows, cols, rate: float):
    """Boolean keep-mask: True with probability ``1 - rate``.

    seed: uint32/int32 scalar (traced ok); bh/rows/cols: broadcastable
    uint32 arrays of GLOBAL batch·head / query / key coordinates. Pure
    function — callers in forward and backward regenerate identical masks.
    """
    h = (rows.astype(jnp.uint32) * jnp.uint32(_C_ROW)
         ^ cols.astype(jnp.uint32) * jnp.uint32(_C_COL)
         ^ bh.astype(jnp.uint32) * jnp.uint32(_C_BH))
    h = _mix32(h ^ lax.convert_element_type(seed, jnp.uint32))
    # uniform in [0, 1): keep iff u >= rate  =>  P(keep) = 1 - rate.
    u = h.astype(jnp.float32) * jnp.float32(2.0 ** -32)
    return u >= jnp.float32(rate)


def dense_keep_mask(seed, b: int, h: int, s_q: int, s_k: int, rate: float):
    """(B, H, Sq, Sk) keep-mask in global coordinates — the materialized
    form for the dense impl and for test references."""
    bh = lax.broadcasted_iota(jnp.uint32, (b, h, 1, 1), 0) * jnp.uint32(h) \
        + lax.broadcasted_iota(jnp.uint32, (b, h, 1, 1), 1)
    rows = lax.broadcasted_iota(jnp.uint32, (1, 1, s_q, 1), 2)
    cols = lax.broadcasted_iota(jnp.uint32, (1, 1, 1, s_k), 3)
    return keep_mask(seed, bh, rows, cols, rate)


def shard_bh_offsets(batch_axes, head_axis: str, b_local: int,
                     h_local: int):
    """(b_start, h_start, h_total) placing this shard's (batch, head) range
    in GLOBAL coordinates — call inside shard_map. The ONE combine order
    for every sharded attention wrapper: the cross-impl mask-parity
    contract breaks silently if two wrappers ever disagree on it."""
    from jax import lax

    b_idx = jnp.int32(0)
    for ax in batch_axes:
        b_idx = b_idx * compat.axis_size(ax) + lax.axis_index(ax)
    return (b_idx * b_local, lax.axis_index(head_axis) * h_local,
            h_local * compat.axis_size(head_axis))


def seed_from_key(key):
    """Fold a JAX PRNG key into the int32 scalar the kernels take (SMEM on
    TPU wants int32; the hash bitcasts back to uint32)."""
    import jax

    bits = jax.random.bits(key, (), jnp.uint32)
    return lax.bitcast_convert_type(bits, jnp.int32)
