"""Fused BatchNorm(+residual)+ReLU — Pallas kernels for the BN bandwidth tax.

Why this exists (BASELINE.md "Where the step goes", measured on-chip at
batch 512): ResNet50's convolutions take ~41 ms of a 209 ms step at ~78%
MXU efficiency, while ~113 ms goes to BatchNorm statistics / dγ/dβ/dx
``convert_reduce`` fusions and ~47 ms to BN-apply/ReLU/residual elementwise
passes — all HBM-bandwidth-bound reads of the ~12 GB of activations. XLA
schedules these as several separate fusion passes; the arithmetic minimum
is far fewer:

- forward: ONE pass computing per-channel Σx and Σx² together (XLA's
  pattern reads x for mean and again for variance in separate fusions on
  some schedules), then ONE normalize+scale+shift[+residual]+ReLU pass;
- backward: ONE pass computing dβ = Σ dz and dγ = Σ dz·x̂ together (dz is
  the ReLU-masked cotangent, recomputed in-register from dy and y), then
  ONE elementwise pass for dx (and the residual cotangent, free in the
  same pass).

Every kernel reads bf16 activations and accumulates float32 in VMEM
scratch, so numerics match the unfused float32-statistics BatchNorm to
rounding (tests/test_fused_batchnorm.py asserts fwd+grads vs the flax
composition). Kernels run compiled on TPU and in Pallas interpret mode
elsewhere, same policy as ops/flash_attention.py.

The module :class:`FusedBatchNormAct` is variable-compatible with
``flax.linen.BatchNorm`` (params ``scale``/``bias``, batch_stats
``mean``/``var``, float32, same momentum/eps semantics and biased variance),
so checkpoints and param-count tests are unaffected by toggling the fusion
flag (models/resnet.py ``fused_bn``).

Running statistics are returned with stop_gradient applied — like flax's
mutable batch_stats, they are state updates, not differentiable outputs.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributeddeeplearning_tpu import compat


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _struct(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s varying-mesh-axes (vma) type:
    under shard_map with check_vma (the explicit-collective DP train step),
    pallas_call outputs must declare how they vary across mesh axes — they
    vary exactly as the activations they are computed from."""
    vma = getattr(compat.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _match_vma(ct, primal):
    """Give a cotangent the primal's varying-mesh-axes type.

    Under shard_map (the DP train step), activations vary over the data
    axes while params are unvarying (replicated); the cotangent of an
    unvarying input must itself be unvarying, which means summing the
    per-shard contributions — exactly the psum that shard_map's AD inserts
    when transposing the implicit broadcast in the unfused composition.
    Outside shard_map both vma sets are empty and this is the identity."""
    ct_vma = getattr(compat.typeof(ct), "vma", None) or frozenset()
    primal_vma = getattr(compat.typeof(primal), "vma", None) or frozenset()
    extra = tuple(sorted(ct_vma - primal_vma))
    if extra:
        ct = jax.lax.psum(ct, extra)
    return ct


def _tile(size: int, target: int) -> int:
    """Largest divisor of ``size`` <= target (shapes here are built from
    powers of two and small odd spatial factors; no padding logic)."""
    t = min(size, target)
    while size % t:
        t -= 1
    return t


def _fold_factor(m: int, c: int) -> int:
    """Lane-folding factor for narrow-channel layers.

    TPU tiles the minor dimension to 128 lanes; a (M, 64) bf16 tensor is
    stored 2x padded, so every kernel pass streams (and every saved
    residual holds) twice the real bytes — measured on-chip, this put the
    stem kernels at half the HBM roofline and pushed batch-512 residency
    past HBM (the padding alone turned 784M stem tensors into 1.53G).
    Viewing the buffer as (M/f, C*f) with f = 128//C is a row-major
    bitcast — element (i, c) lands at row i//f, lane (i%f)*C + c — so
    channel identity survives as lane%C and per-channel sums fold back
    with one (f, C) reshape-sum. No data moves; padding disappears."""
    if c >= 128 or 128 % c:
        return 1
    f = 128 // c
    while m % f:
        f //= 2
    return f


def _fold(x2d, f: int):
    m, c = x2d.shape
    return x2d if f == 1 else x2d.reshape(m // f, c * f)


def _unfold(x2d, f: int):
    mf, cf = x2d.shape
    return x2d if f == 1 else x2d.reshape(mf * f, cf // f)


def _tile_vec(v, f: int):
    """Replicate a per-channel vector across the f folded sub-rows so lane
    l of the folded view sees the parameter for channel l % C."""
    return v if f == 1 else jnp.tile(v, f)


def _fold_sum(v, f: int):
    """Collapse a folded per-lane reduction (C*f,) back to per-channel (C,)."""
    return v if f == 1 else v.reshape(f, -1).sum(axis=0)


def _jnp_twin(x) -> bool:
    """Use the jnp equivalent instead of a Pallas kernel: interpret mode
    inside shard_map. Interpreted kernels inline into the traced program,
    where their unvarying scratch-buffer inits collide with varying
    operands under check_vma; the jnp twins are mathematically identical.
    Compiled TPU kernels are opaque to vma tracking (only the declared
    boundary types matter — see :func:`_struct`), so on hardware the
    kernels always run."""
    return (_should_interpret()
            and bool(getattr(compat.typeof(x), "vma", None)))


# ---------------------------------------------------------------------------
# Forward: per-channel sum/sumsq in one pass over (M, C)
# ---------------------------------------------------------------------------

def _stats_kernel(x_ref, sum_ref, sumsq_ref, s_scr, ss_scr):
    m = pl.program_id(1)

    @pl.when(m == 0)
    def _():
        s_scr[...] = jnp.zeros_like(s_scr)
        ss_scr[...] = jnp.zeros_like(ss_scr)

    x = x_ref[...].astype(jnp.float32)
    s_scr[...] += x.sum(axis=0, keepdims=True)
    ss_scr[...] += (x * x).sum(axis=0, keepdims=True)

    @pl.when(m == pl.num_programs(1) - 1)
    def _():
        sum_ref[...] = s_scr[...]
        sumsq_ref[...] = ss_scr[...]


def bn_stats(x2d: jax.Array, *, interpret: Optional[bool] = None):
    """(M, C) -> (mean, var) per channel, float32, biased variance."""
    m_true, c_true = x2d.shape
    if _jnp_twin(x2d):
        xf = x2d.astype(jnp.float32)
        mean = xf.mean(axis=0)
        return mean, jnp.maximum((xf * xf).mean(axis=0) - mean * mean, 0.0)
    f = _fold_factor(m_true, c_true)
    x2d = _fold(x2d, f)
    m, c = x2d.shape
    tm, tc = _tile(m, 1024), _tile(c, 512)
    interp = _should_interpret() if interpret is None else interpret
    s, ss = pl.pallas_call(
        _stats_kernel,
        grid=(c // tc, m // tm),
        in_specs=[pl.BlockSpec((tm, tc), lambda ci, mi: (mi, ci))],
        out_specs=[pl.BlockSpec((1, tc), lambda ci, mi: (0, ci)),
                   pl.BlockSpec((1, tc), lambda ci, mi: (0, ci))],
        out_shape=[_struct((1, c), jnp.float32, x2d),
                   _struct((1, c), jnp.float32, x2d)],
        scratch_shapes=[pltpu.VMEM((1, tc), jnp.float32),
                        pltpu.VMEM((1, tc), jnp.float32)],
        interpret=interp,
    )(x2d)
    mean = _fold_sum(s[0], f) / m_true
    var = _fold_sum(ss[0], f) / m_true - mean * mean
    return mean, jnp.maximum(var, 0.0)


# ---------------------------------------------------------------------------
# Forward: normalize + scale/shift (+ residual) (+ ReLU) in one pass
# ---------------------------------------------------------------------------

def _apply_kernel(x_ref, mean_ref, inv_ref, gamma_ref, beta_ref, o_ref, *,
                  relu: bool, res_ref=None):
    x = x_ref[...].astype(jnp.float32)
    y = (x - mean_ref[...]) * (inv_ref[...] * gamma_ref[...]) + beta_ref[...]
    if res_ref is not None:
        y = y + res_ref[...].astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def bn_apply(x2d, mean, inv, gamma, beta, residual2d=None, *, relu: bool,
             interpret: Optional[bool] = None):
    m_true, c_true = x2d.shape
    if _jnp_twin(x2d):
        y = (x2d.astype(jnp.float32) - mean) * (inv * gamma) + beta
        if residual2d is not None:
            y = y + residual2d.astype(jnp.float32)
        if relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(x2d.dtype)
    f = _fold_factor(m_true, c_true)
    x2d = _fold(x2d, f)
    if residual2d is not None:
        residual2d = _fold(residual2d, f)
    mean, inv = _tile_vec(mean, f), _tile_vec(inv, f)
    gamma, beta = _tile_vec(gamma, f), _tile_vec(beta, f)
    m, c = x2d.shape
    tm, tc = _tile(m, 1024), _tile(c, 512)
    interp = _should_interpret() if interpret is None else interpret
    vec = pl.BlockSpec((1, tc), lambda mi, ci: (0, ci))
    tile = pl.BlockSpec((tm, tc), lambda mi, ci: (mi, ci))
    operands = [x2d, mean[None], inv[None], gamma[None], beta[None]]
    in_specs = [tile, vec, vec, vec, vec]
    if residual2d is not None:
        operands.append(residual2d)
        in_specs.append(tile)

        def kernel(x, mn, iv, g, b, r, o):
            _apply_kernel(x, mn, iv, g, b, o, relu=relu, res_ref=r)
    else:
        def kernel(x, mn, iv, g, b, o):
            _apply_kernel(x, mn, iv, g, b, o, relu=relu)
    return _unfold(pl.pallas_call(
        kernel,
        grid=(m // tm, c // tc),
        in_specs=in_specs,
        out_specs=tile,
        out_shape=_struct((m, c), x2d.dtype, x2d),
        interpret=interp,
    )(*operands), f)


# ---------------------------------------------------------------------------
# Backward pass 1: dβ = Σ dz, dγ = Σ dz·x̂ in one pass
# (dz = dy ⊙ 1[y>0] recomputed in-register; x̂ = (x-μ)·inv)
# ---------------------------------------------------------------------------

def _bwd_reduce_kernel(dy_ref, x_ref, mean_ref, inv_ref,
                       dbeta_ref, dgamma_ref, db_scr, dg_scr, *,
                       y_ref=None):
    """``y_ref`` present only for relu layers — the ReLU mask is the only
    use of y, and declaring it unconditionally would stream a dead
    full-activation read from HBM on the relu=False (downsample-BN) path."""
    m = pl.program_id(1)

    @pl.when(m == 0)
    def _():
        db_scr[...] = jnp.zeros_like(db_scr)
        dg_scr[...] = jnp.zeros_like(dg_scr)

    dy = dy_ref[...].astype(jnp.float32)
    if y_ref is not None:
        dy = jnp.where(y_ref[...].astype(jnp.float32) > 0, dy, 0.0)
    xh = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...]
    db_scr[...] += dy.sum(axis=0, keepdims=True)
    dg_scr[...] += (dy * xh).sum(axis=0, keepdims=True)

    @pl.when(m == pl.num_programs(1) - 1)
    def _():
        dbeta_ref[...] = db_scr[...]
        dgamma_ref[...] = dg_scr[...]


def bn_bwd_reduce(dy2d, y2d, x2d, mean, inv, *, relu: bool,
                  interpret: Optional[bool] = None):
    m_true, c_true = x2d.shape
    if _jnp_twin(x2d):
        dz = dy2d.astype(jnp.float32)
        if relu:
            dz = jnp.where(y2d.astype(jnp.float32) > 0, dz, 0.0)
        xh = (x2d.astype(jnp.float32) - mean) * inv
        return dz.sum(axis=0), (dz * xh).sum(axis=0)
    f = _fold_factor(m_true, c_true)
    dy2d, x2d = _fold(dy2d, f), _fold(x2d, f)
    if relu:
        y2d = _fold(y2d, f)
    mean, inv = _tile_vec(mean, f), _tile_vec(inv, f)
    m, c = x2d.shape
    tm, tc = _tile(m, 1024), _tile(c, 512)
    interp = _should_interpret() if interpret is None else interpret
    vec = pl.BlockSpec((1, tc), lambda ci, mi: (0, ci))
    tile = pl.BlockSpec((tm, tc), lambda ci, mi: (mi, ci))
    operands = [dy2d, x2d, mean[None], inv[None]]
    in_specs = [tile, tile, vec, vec]
    if relu:
        operands.append(y2d)
        in_specs.append(tile)

        def kernel(dy, x, mn, iv, y, db_o, dg_o, db_s, dg_s):
            _bwd_reduce_kernel(dy, x, mn, iv, db_o, dg_o, db_s, dg_s,
                               y_ref=y)
    else:
        def kernel(dy, x, mn, iv, db_o, dg_o, db_s, dg_s):
            _bwd_reduce_kernel(dy, x, mn, iv, db_o, dg_o, db_s, dg_s)
    db, dg = pl.pallas_call(
        kernel,
        grid=(c // tc, m // tm),
        in_specs=in_specs,
        out_specs=[vec, vec],
        out_shape=[_struct((1, c), jnp.float32, x2d),
                   _struct((1, c), jnp.float32, x2d)],
        scratch_shapes=[pltpu.VMEM((1, tc), jnp.float32),
                        pltpu.VMEM((1, tc), jnp.float32)],
        interpret=interp,
    )(*operands)
    return _fold_sum(db[0], f), _fold_sum(dg[0], f)


# ---------------------------------------------------------------------------
# Backward pass 2: dx = γ·inv·(dz - dβ/M - x̂·dγ/M), dres = dz — one pass
# ---------------------------------------------------------------------------

def _bwd_dx_kernel(dy_ref, x_ref, mean_ref, inv_ref, c1_ref, c2_ref,
                   c3_ref, dx_ref, *, y_ref=None, dres_ref=None):
    """``y_ref`` only for relu layers (its sole use is the ReLU mask — see
    :func:`_bwd_reduce_kernel`); ``dres_ref`` only for fused-residual ones."""
    dz = dy_ref[...].astype(jnp.float32)
    if y_ref is not None:
        dz = jnp.where(y_ref[...].astype(jnp.float32) > 0, dz, 0.0)
    xh = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...]
    dx = c1_ref[...] * (dz - c2_ref[...] - xh * c3_ref[...])
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if dres_ref is not None:
        dres_ref[...] = dz.astype(dres_ref.dtype)


def bn_bwd_dx(dy2d, y2d, x2d, mean, inv, gamma, dbeta, dgamma, *,
              relu: bool, want_dres: bool,
              interpret: Optional[bool] = None):
    m_true, c_true = x2d.shape
    if _jnp_twin(x2d):
        dz = dy2d.astype(jnp.float32)
        if relu:
            dz = jnp.where(y2d.astype(jnp.float32) > 0, dz, 0.0)
        xh = (x2d.astype(jnp.float32) - mean) * inv
        dx = (gamma * inv) * (dz - dbeta / m_true - xh * (dgamma / m_true))
        return (dx.astype(x2d.dtype),
                dz.astype(x2d.dtype) if want_dres else None)
    f = _fold_factor(m_true, c_true)
    dy2d, x2d = _fold(dy2d, f), _fold(x2d, f)
    if relu:
        y2d = _fold(y2d, f)
    c1 = _tile_vec(gamma * inv, f)
    c2 = _tile_vec(dbeta / m_true, f)
    c3 = _tile_vec(dgamma / m_true, f)
    mean, inv = _tile_vec(mean, f), _tile_vec(inv, f)
    m, c = x2d.shape
    tm, tc = _tile(m, 1024), _tile(c, 512)
    interp = _should_interpret() if interpret is None else interpret
    vec = pl.BlockSpec((1, tc), lambda mi, ci: (0, ci))
    tile = pl.BlockSpec((tm, tc), lambda mi, ci: (mi, ci))
    operands = [dy2d, x2d, mean[None], inv[None], c1[None], c2[None],
                c3[None]]
    in_specs = [tile, tile, vec, vec, vec, vec, vec]
    if relu:
        operands.append(y2d)
        in_specs.append(tile)
    out_shape = [_struct((m, c), x2d.dtype, x2d)]
    out_specs = [tile]
    if want_dres:
        out_shape.append(_struct((m, c), x2d.dtype, x2d))
        out_specs.append(tile)
    n_in = len(operands)

    def kernel(*refs):
        dy, x, mn, iv, a, b, d = refs[:7]
        y = refs[7] if relu else None
        outs = refs[n_in:]
        _bwd_dx_kernel(dy, x, mn, iv, a, b, d, outs[0], y_ref=y,
                       dres_ref=outs[1] if want_dres else None)

    out = pl.pallas_call(
        kernel,
        grid=(m // tm, c // tc),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interp,
    )(*operands)
    return ((_unfold(out[0], f), _unfold(out[1], f)) if want_dres
            else (_unfold(out[0], f), None))


# ---------------------------------------------------------------------------
# Differentiable train-mode op (custom VJP over the kernels)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def bn_act_train(x2d, gamma, beta, relu: bool, eps: float):
    """y = [relu](x̂·γ + β) with batch statistics; returns (y, mean, var).

    mean/var are the biased batch statistics (for the running-stat update);
    their cotangents are ignored by the VJP — callers must treat them as
    state (stop_gradient), exactly like flax's mutable batch_stats.
    """
    y, mean, var, _ = _bn_fwd(x2d, gamma, beta, relu, eps)
    return y, mean, var


def _bn_fwd(x2d, gamma, beta, relu, eps, residual2d=None):
    mean, var = bn_stats(x2d)
    inv = jax.lax.rsqrt(var + eps)
    y = bn_apply(x2d, mean, inv, gamma.astype(jnp.float32),
                 beta.astype(jnp.float32), residual2d, relu=relu)
    return y, mean, var, inv


def _bn_act_fwd(x2d, gamma, beta, relu, eps):
    y, mean, var, inv = _bn_fwd(x2d, gamma, beta, relu, eps)
    return (y, mean, var), (x2d, y, mean, inv, gamma)


def _bn_act_bwd(relu, eps, saved, cots):
    x2d, y, mean, inv, gamma = saved
    dy, _, _ = cots  # mean/var cotangents are state, not gradients
    dbeta, dgamma = bn_bwd_reduce(dy, y, x2d, mean, inv, relu=relu)
    dx, _ = bn_bwd_dx(dy, y, x2d, mean, inv, gamma.astype(jnp.float32),
                      dbeta, dgamma, relu=relu, want_dres=False)
    return (dx, _match_vma(dgamma.astype(gamma.dtype), gamma),
            _match_vma(dbeta.astype(gamma.dtype), gamma))


bn_act_train.defvjp(_bn_act_fwd, _bn_act_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def bn_act_res_train(x2d, gamma, beta, residual2d, relu: bool, eps: float):
    """Same as :func:`bn_act_train` with a fused residual add before ReLU
    (the block-exit pattern ``relu(bn(conv(x)) + shortcut)``)."""
    y, mean, var, _ = _bn_fwd(x2d, gamma, beta, relu, eps, residual2d)
    return y, mean, var


def _bn_act_res_fwd(x2d, gamma, beta, residual2d, relu, eps):
    y, mean, var, inv = _bn_fwd(x2d, gamma, beta, relu, eps, residual2d)
    return (y, mean, var), (x2d, y, mean, inv, gamma)


def _bn_act_res_bwd(relu, eps, saved, cots):
    x2d, y, mean, inv, gamma = saved
    dy, _, _ = cots
    dbeta, dgamma = bn_bwd_reduce(dy, y, x2d, mean, inv, relu=relu)
    dx, dres = bn_bwd_dx(dy, y, x2d, mean, inv, gamma.astype(jnp.float32),
                         dbeta, dgamma, relu=relu, want_dres=True)
    return (dx, _match_vma(dgamma.astype(gamma.dtype), gamma),
            _match_vma(dbeta.astype(gamma.dtype), gamma), dres)


bn_act_res_train.defvjp(_bn_act_res_fwd, _bn_act_res_bwd)


# ---------------------------------------------------------------------------
# Flax module, variable-compatible with nn.BatchNorm
# ---------------------------------------------------------------------------

class FusedBatchNormAct(nn.Module):
    """Drop-in BN[+residual][+ReLU] with the fused Pallas path in training.

    Variable layout matches ``nn.BatchNorm`` exactly (params ``scale`` and
    ``bias``; batch_stats ``mean``/``var``; float32; biased variance in the
    running update), so toggling models/resnet.py's ``fused_bn`` flag does
    not change checkpoints or parameter counts. Inference mode uses plain
    jnp (running stats, no reductions — XLA already fuses that well).
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    relu: bool = True
    scale_init: Any = nn.initializers.ones

    @nn.compact
    def __call__(self, x, residual=None):
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32), (c,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32), (c,))
        x = jnp.asarray(x, self.dtype)
        x2d = x.reshape(-1, c)
        res2d = (jnp.asarray(residual, self.dtype).reshape(-1, c)
                 if residual is not None else None)

        if self.use_running_average:
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon)
            y = ((x2d.astype(jnp.float32) - ra_mean.value)
                 * (inv * scale.astype(jnp.float32))
                 + bias.astype(jnp.float32))
            if res2d is not None:
                y = y + res2d.astype(jnp.float32)
            if self.relu:
                y = jnp.maximum(y, 0.0)
            return y.astype(self.dtype).reshape(x.shape)

        if res2d is None:
            y2d, mean, var = bn_act_train(
                x2d, scale, bias, self.relu, self.epsilon)
        else:
            y2d, mean, var = bn_act_res_train(
                x2d, scale, bias, res2d, self.relu, self.epsilon)
        mean = jax.lax.stop_gradient(mean)
        var = jax.lax.stop_gradient(var)
        if not self.is_initializing():
            ra_mean.value = (self.momentum * ra_mean.value
                             + (1.0 - self.momentum) * mean)
            ra_var.value = (self.momentum * ra_var.value
                            + (1.0 - self.momentum) * var)
        return y2d.reshape(x.shape)
