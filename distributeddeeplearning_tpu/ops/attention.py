"""The one attention-impl dispatch shared by every transformer family
(models/bert.py, models/gpt.py, models/llama.py).

Three impls, one semantic: softmax(QK^T * d^-1/2 + mask) V with a key-padding
mask, optionally causal.

- ``dense``: materialized (S, S) scores, f32 softmax, XLA-fused — right for
  short sequences; the only impl that can apply attention-probability
  dropout (pass ``prob_dropout``).
- ``flash``: Pallas TPU kernel (ops/flash_attention.py), O(S·D) HBM traffic,
  causal variant skips above-diagonal blocks.
- ``ring``: exact blockwise ring over the ``seq`` mesh axis
  (parallel/ring_attention.py) — the sharded-sequence long-context path.

Keeping the dispatch here means a masking/dtype/backend fix lands in every
model family at once instead of drifting across three near-copies.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp


def multihead_attention(q, k, v, pad_mask, *, impl: str, causal: bool,
                        dtype: Any,
                        prob_dropout: Optional[Callable] = None,
                        warn_dropout_rate: float = 0.0,
                        deterministic: bool = True):
    """q/k/v: (B, S, H, D); pad_mask: (B, S) bool (True = attend) or None.

    Returns (B, S, H*D) in ``dtype``. ``prob_dropout`` (dense only) is a
    callable applied to the probabilities — pass a closure constructing
    ``nn.Dropout`` inside the calling module's scope. ``warn_dropout_rate``
    triggers the trace-time warning that non-dense impls skip
    attention-probability dropout.
    """
    b, s, h, d = q.shape
    if pad_mask is None:
        pad_mask = jnp.ones((b, s), jnp.bool_)
    pad_mask = pad_mask.astype(jnp.bool_)

    if impl != "dense" and warn_dropout_rate > 0 and not deterministic:
        # Trace-time (once per compile): flash/ring never materialize the
        # probs, so attention-probability dropout is skipped.
        import warnings
        warnings.warn(
            f"attention_impl={impl!r} does not apply attention-probability "
            f"dropout (the probs are never materialized); training "
            f"regularization differs from 'dense' at "
            f"dropout_rate={warn_dropout_rate}. Residual/MLP dropouts still "
            f"apply.", UserWarning, stacklevel=3)

    if impl == "flash":
        from distributeddeeplearning_tpu.ops.flash_attention import (
            flash_attention_sharded)
        out = flash_attention_sharded(q, k, v, pad_mask, causal=causal)
    elif impl == "ring":
        from distributeddeeplearning_tpu.parallel import ring_attention
        out = ring_attention.ring_attention_sharded(
            q, k, v, pad_mask, causal=causal)
    elif impl == "zigzag":
        # Load-balanced causal ring: caller (models/gpt.py) has already put
        # the sequence in zigzag layout, so q/k/v/mask arrive permuted and
        # the output stays permuted.
        if not causal:
            raise ValueError(
                "attention_impl='zigzag' is causal-only (the zigzag layout "
                "balances the causal triangle; bidirectional work is "
                "already uniform — use 'ring')")
        from distributeddeeplearning_tpu.parallel import ring_attention
        out = ring_attention.zigzag_ring_attention_sharded(
            q, k, v, pad_mask)
    elif impl == "dense":
        scale = d ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        keep = pad_mask[:, None, None, :]
        if causal:
            keep = keep & jnp.tril(jnp.ones((s, s), jnp.bool_))[None, None]
        scores = jnp.where(keep, scores, jnp.finfo(jnp.float32).min)
        probs = nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
        if prob_dropout is not None:
            probs = prob_dropout(probs)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    else:
        raise ValueError(f"unknown attention_impl {impl!r}")
    return out.reshape(b, s, -1)
