"""The one attention-impl dispatch shared by every transformer family
(models/bert.py, models/gpt.py, models/llama.py).

Four impls, one semantic: dropout(softmax(QK^T * d^-1/2 + mask)) V with a
key-padding mask, optionally causal.

- ``dense``: materialized (S, S) scores, f32 softmax, XLA-fused — right for
  short sequences.
- ``flash``: Pallas TPU kernel (ops/flash_attention.py), O(S·D) HBM traffic,
  causal variant skips above-diagonal blocks.
- ``ring``: exact blockwise ring over the ``seq`` mesh axis
  (parallel/ring_attention.py) — the sharded-sequence long-context path.
- ``zigzag``: load-balanced causal ring (caller supplies zigzag layout).

Attention-probability dropout applies in EVERY impl via one counter-based
hash mask keyed on global (batch·head, query, key) coordinates
(ops/hash_dropout.py): flash regenerates it inside its backward kernels,
ring/zigzag build it per block pair, dense materializes it — and all four
realize the IDENTICAL mask for the same RNG, at any sharding. That closes
the r3 semantics gap where non-dense impls silently skipped this dropout
(VERDICT r3 Missing #6), and it upgrades the old trace-time UserWarning to
exact cross-impl parity (tests/test_attention_dropout.py asserts equality,
not statistics).

Keeping the dispatch here means a masking/dtype/backend fix lands in every
model family at once instead of drifting across four near-copies.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp


def multihead_attention(q, k, v, pad_mask, *, impl: str, causal: bool,
                        dtype: Any,
                        dropout_rate: float = 0.0,
                        dropout_rng: Optional[Any] = None,
                        deterministic: bool = True):
    """q/k/v: (B, S, H, D); pad_mask: (B, S) bool (True = attend) or None.

    Returns (B, S, H*D) in ``dtype``. ``dropout_rate`` is the
    attention-probability dropout rate, applied only when
    ``deterministic=False``; ``dropout_rng`` (a JAX PRNG key, e.g.
    ``self.make_rng('dropout')``) is required then.
    """
    b, s, h, d = q.shape
    if pad_mask is None:
        pad_mask = jnp.ones((b, s), jnp.bool_)
    pad_mask = pad_mask.astype(jnp.bool_)

    rate = float(dropout_rate) if not deterministic else 0.0
    seed = None
    if rate > 0.0:
        if dropout_rng is None:
            raise ValueError(
                "attention-probability dropout (dropout_rate "
                f"{dropout_rate}) needs dropout_rng — pass "
                "self.make_rng('dropout') from the calling module")
        from distributeddeeplearning_tpu.ops.hash_dropout import (
            seed_from_key)
        seed = seed_from_key(dropout_rng)

    if impl == "flash":
        from distributeddeeplearning_tpu.ops.flash_attention import (
            flash_attention_sharded)
        out = flash_attention_sharded(q, k, v, pad_mask, causal=causal,
                                      dropout_rate=rate, dropout_seed=seed)
    elif impl == "ring":
        from distributeddeeplearning_tpu.parallel import ring_attention
        out = ring_attention.ring_attention_sharded(
            q, k, v, pad_mask, causal=causal,
            dropout_rate=rate, dropout_seed=seed)
    elif impl == "zigzag":
        # Load-balanced causal ring: caller (models/gpt.py, models/llama.py)
        # has already put the sequence in zigzag layout, so q/k/v/mask
        # arrive permuted and the output stays permuted. The dropout hash
        # keys on natural positions, so the realized mask still equals the
        # dense impl's.
        if not causal:
            raise ValueError(
                "attention_impl='zigzag' is causal-only (the zigzag layout "
                "balances the causal triangle; bidirectional work is "
                "already uniform — use 'ring')")
        from distributeddeeplearning_tpu.parallel import ring_attention
        out = ring_attention.zigzag_ring_attention_sharded(
            q, k, v, pad_mask, dropout_rate=rate, dropout_seed=seed)
    elif impl == "dense":
        scale = d ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        keep = pad_mask[:, None, None, :]
        if causal:
            keep = keep & jnp.tril(jnp.ones((s, s), jnp.bool_))[None, None]
        scores = jnp.where(keep, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(dtype)
        if rate > 0.0:
            from distributeddeeplearning_tpu.ops.hash_dropout import (
                dense_keep_mask)
            km = dense_keep_mask(seed, b, h, s, s, rate)
            probs = jnp.where(km, probs * (1.0 / (1.0 - rate)),
                              jnp.zeros((), probs.dtype))
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    else:
        raise ValueError(f"unknown attention_impl {impl!r}")
    return out.reshape(b, s, -1)
