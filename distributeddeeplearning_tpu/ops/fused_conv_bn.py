"""3x3 convolution with BN prologue/epilogue — conv-epilogue fusion for
the bottleneck's spatial conv (VERDICT r4 Next #2).

ops/fused_linear_bn.py covers the 1x1 convolutions (matmuls over
M = B.H.W rows); after it, the remaining BN traffic in a bottleneck block
rides the 3x3: bn1's apply must materialize a normalized tensor as the
XLA conv's input, and bn2's statistics are a separate full read of the
conv's output (BASELINE.md round-2 profile: the BN statistics passes sit
at the HBM roofline). This module fuses both into the convolution itself:

    a  = relu((x_raw − μ)·inv·γ + β)     -- prologue, on VMEM tiles
    y  = conv3x3(a, w), stride 1, pad 1  -- in-VMEM im2col + one MXU dot
    s  = Σ y,  ss = Σ y²                 -- epilogue, per out-channel

so the raw conv1 output streams straight into the MXU (no materialized a1)
and bn2's sums ride tiles the conv already wrote. Per block this removes
~2 full f-channel activation passes versus the v1 fused path.

Implementation: the kernel walks (batch, row-block) grid cells; each cell
DMAs a (th+2, W+2, Cin) halo slab from HBM (three conditional copies:
body rows always, one top / one bottom halo row when they exist),
normalizes it on the VPU, ZEROES everything outside the image (halo rows
beyond the border, the two side columns — so SAME-padding semantics hold
on *normalized* activations exactly as XLA's pad-then-conv), builds the
(th·W, 9·Cin) patch matrix in VMEM (free of HBM traffic — the 9x read
amplification of materialized im2col is the whole reason this is a
kernel), and issues ONE (th·W, 9Cin) x (9Cin, Cout) MXU dot.

Backward (same two-matmul structure as fused_linear_bn):

    dY = dy + ds + 2·y·dss                  (epilogue cotangents folded)
    da = conv3x3(dY, flip(w)ᵀ)              (kernel 1: patch matmul over
    dzl = da·1[z>0];  dx = dzl·γ·inv         dY's halo slab; epilogue
    dβ = Σ dzl;  dγ = Σ dzl·x̂               writes dx, dβ, dγ)
    dw[t] = aᵀ_shifted @ dY                 (kernel 2: a recomputed in its
                                             prologue; (9Cin, Cout) acc)
    dμ = −γ·inv·dβ;  dinv = γ·dγ/inv        (vector math, outside)

Stride-2 bottlenecks keep the XLA conv path (models/fused_block.py falls
back per block); the fusion targets the 13/16 stride-1 blocks where the
traffic lives. bf16 reads, f32 accumulation, interpret mode off-TPU, jnp
twins under shard_map's check_vma — the ops/fused_batchnorm.py policy.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributeddeeplearning_tpu import compat
from distributeddeeplearning_tpu.ops.fused_batchnorm import (
    _jnp_twin, _match_vma, _should_interpret, _struct, _tile)


def _row_block(h: int, w: int) -> int:
    """Rows per tile: the largest divisor of H keeping th·W near the MXU
    sweet spot (and the halo slab comfortably in VMEM)."""
    return _tile(h, max(1, 512 // w))


def _normalize_mask(slab, mu, inv, g, b, *, relu: bool, bn: bool,
                    r0, h: int, w: int, out_dtype):
    """Prologue + SAME-padding semantics: bn(+relu) the halo slab, then
    zero every position outside the image. ``r0`` is the global row of
    slab row 1 (slab row j holds global row r0 - 1 + j)."""
    th2, w2, _ = slab.shape
    a = slab.astype(jnp.float32)
    if bn:
        a = (a - mu) * (inv * g) + b
        if relu:
            a = jnp.maximum(a, 0.0)
    rows = jax.lax.broadcasted_iota(jnp.int32, (th2, w2, 1), 0) + (r0 - 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (th2, w2, 1), 1)
    valid = ((rows >= 0) & (rows < h) & (cols >= 1) & (cols <= w))
    return jnp.where(valid, a, 0.0).astype(out_dtype)


def _patches(a, th: int, w: int, cin: int):
    """(th+2, W+2, C) normalized slab -> (th*W, 9C) im2col matrix, tap
    order (dy, dx) lexicographic — matching w.reshape(9C, Cout)."""
    parts = [a[dy:dy + th, dx:dx + w, :] for dy in range(3)
             for dx in range(3)]
    return jnp.concatenate(parts, axis=-1).reshape(th * w, 9 * cin)


def _start_slab_dmas(x_any, slab, sem, b_i, r, *, th: int, nh: int):
    """Three conditional copies of the halo slab's valid rows; returns the
    descriptors so the caller can wait on exactly the ones started."""
    r0 = r * th
    mid = pltpu.make_async_copy(
        x_any.at[b_i, pl.ds(r0, th)],
        slab.at[pl.ds(1, th), pl.ds(1, slab.shape[1] - 2)], sem.at[0])
    mid.start()
    top = pltpu.make_async_copy(
        x_any.at[b_i, pl.ds(jnp.maximum(r0 - 1, 0), 1)],
        slab.at[pl.ds(0, 1), pl.ds(1, slab.shape[1] - 2)], sem.at[1])
    bot = pltpu.make_async_copy(
        x_any.at[b_i, pl.ds(jnp.minimum(r0 + th, (nh * th) - 1), 1)],
        slab.at[pl.ds(th + 1, 1), pl.ds(1, slab.shape[1] - 2)], sem.at[2])

    @pl.when(r > 0)
    def _():
        top.start()

    @pl.when(r < nh - 1)
    def _():
        bot.start()

    mid.wait()

    @pl.when(r > 0)
    def _():
        top.wait()

    @pl.when(r < nh - 1)
    def _():
        bot.wait()


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(x_any, w_ref, mu_ref, inv_ref, g_ref, b_ref,
                y_ref, s_ref, ss_ref, slab, sem, s_scr, ss_scr, *,
                relu: bool, bn: bool, th: int, h: int, w: int, cin: int,
                nb: int, nh: int):
    b_i, r = pl.program_id(0), pl.program_id(1)

    @pl.when((b_i == 0) & (r == 0))
    def _():
        s_scr[...] = jnp.zeros_like(s_scr)
        ss_scr[...] = jnp.zeros_like(ss_scr)

    _start_slab_dmas(x_any, slab, sem, b_i, r, th=th, nh=nh)
    a = _normalize_mask(slab[...], mu_ref[...], inv_ref[...], g_ref[...],
                        b_ref[...], relu=relu, bn=bn, r0=r * th, h=h, w=w,
                        out_dtype=y_ref.dtype)
    acc = jax.lax.dot(_patches(a, th, w, cin), w_ref[...],
                      preferred_element_type=jnp.float32)
    y_cast = acc.astype(y_ref.dtype)
    y_ref[0] = y_cast.reshape(th, w, -1)
    # Statistics over y AS STORED (match what the next prologue will read).
    yf = y_cast.astype(jnp.float32)
    s_scr[...] += yf.sum(axis=0, keepdims=True)
    ss_scr[...] += (yf * yf).sum(axis=0, keepdims=True)

    @pl.when((b_i == nb - 1) & (r == nh - 1))
    def _():
        s_ref[...] = s_scr[...]
        ss_ref[...] = ss_scr[...]


def _fwd(x, mu, inv, gamma, beta, w, relu, bn,
         interpret: Optional[bool] = None):
    nb, h, ww, cin = x.shape
    cout = w.shape[-1]
    th = _row_block(h, ww)
    nh = h // th
    interp = _should_interpret() if interpret is None else interpret
    w2r = w.reshape(9 * cin, cout).astype(x.dtype)
    vec = pl.BlockSpec((1, cin), lambda b_i, r: (0, 0))
    y, s, ss = pl.pallas_call(
        functools.partial(_fwd_kernel, relu=relu, bn=bn, th=th, h=h, w=ww,
                          cin=cin, nb=nb, nh=nh),
        grid=(nb, nh),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec((9 * cin, cout), lambda b_i, r: (0, 0)),
                  vec, vec, vec, vec],
        out_specs=[pl.BlockSpec((1, th, ww, cout),
                                lambda b_i, r: (b_i, r, 0, 0)),
                   pl.BlockSpec((1, cout), lambda b_i, r: (0, 0)),
                   pl.BlockSpec((1, cout), lambda b_i, r: (0, 0))],
        out_shape=[_struct((nb, h, ww, cout), x.dtype, x),
                   _struct((1, cout), jnp.float32, x),
                   _struct((1, cout), jnp.float32, x)],
        scratch_shapes=[pltpu.VMEM((th + 2, ww + 2, cin), x.dtype),
                        pltpu.SemaphoreType.DMA((3,)),
                        pltpu.VMEM((1, cout), jnp.float32),
                        pltpu.VMEM((1, cout), jnp.float32)],
        # The stats scratch accumulates ACROSS grid cells (zeroed at cell 0,
        # flushed at the last): pin every grid axis sequential so a future
        # parallel/megacore default can't silently split the accumulator.
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interp,
    )(x, w2r, mu[None], inv[None], gamma[None], beta[None])
    return y, s[0], ss[0]


# ---------------------------------------------------------------------------
# Backward kernel 1: da via flipped-kernel conv on dY; epilogue dx, dβ, dγ
# ---------------------------------------------------------------------------

def _bwd_dx_kernel(dy_any, y_any, ds_ref, dss_ref, wf_ref, x_ref,
                   mu_ref, inv_ref, g_ref, b_ref,
                   dx_ref, db_ref, dg_ref,
                   slab_dy, slab_y, sem_dy, sem_y, db_scr, dg_scr, *,
                   relu: bool, bn: bool, th: int, h: int, w: int, cout: int,
                   nb: int, nh: int):
    b_i, r = pl.program_id(0), pl.program_id(1)

    @pl.when((b_i == 0) & (r == 0))
    def _():
        db_scr[...] = jnp.zeros_like(db_scr)
        dg_scr[...] = jnp.zeros_like(dg_scr)

    _start_slab_dmas(dy_any, slab_dy, sem_dy, b_i, r, th=th, nh=nh)
    _start_slab_dmas(y_any, slab_y, sem_y, b_i, r, th=th, nh=nh)
    # dY = dy + ds + 2 y dss on the slab; zero outside the image (those
    # output positions do not exist, so they contribute nothing).
    dyf = (slab_dy[...].astype(jnp.float32) + ds_ref[...]
           + 2.0 * slab_y[...].astype(jnp.float32) * dss_ref[...])
    th2, w2, _ = slab_dy.shape
    rows = jax.lax.broadcasted_iota(jnp.int32, (th2, w2, 1), 0) + (r * th - 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (th2, w2, 1), 1)
    valid = ((rows >= 0) & (rows < h) & (cols >= 1) & (cols <= w))
    dyt = jnp.where(valid, dyf, 0.0).astype(dx_ref.dtype)
    da = jax.lax.dot(_patches(dyt, th, w, cout), wf_ref[...],
                     preferred_element_type=jnp.float32)
    if bn:
        xf = x_ref[0].reshape(th * w, -1).astype(jnp.float32)
        xh = (xf - mu_ref[...]) * inv_ref[...]
        dzl = da
        if relu:
            z = xh * g_ref[...] + b_ref[...]
            dzl = jnp.where(z > 0, da, 0.0)
        dx_ref[0] = (dzl * (g_ref[...] * inv_ref[...])).reshape(
            th, w, -1).astype(dx_ref.dtype)
        db_scr[...] += dzl.sum(axis=0, keepdims=True)
        dg_scr[...] += (dzl * xh).sum(axis=0, keepdims=True)
    else:
        dx_ref[0] = da.reshape(th, w, -1).astype(dx_ref.dtype)

    @pl.when((b_i == nb - 1) & (r == nh - 1))
    def _():
        db_ref[...] = db_scr[...]
        dg_ref[...] = dg_scr[...]


def _bwd_dx(dy, y, ds, dss, w, x, mu, inv, gamma, beta, relu, bn,
            interpret: Optional[bool] = None):
    nb, h, ww, cin = x.shape
    cout = w.shape[-1]
    th = _row_block(h, ww)
    nh = h // th
    interp = _should_interpret() if interpret is None else interpret
    # flip(w)ᵀ: da[i] = Σ_u dY[i+u] @ w[1-u]ᵀ, tap order (du, dx') must
    # match _patches' lexicographic order over the dY slab.
    wf = w[::-1, ::-1].transpose(0, 1, 3, 2).reshape(9 * cout, cin)
    wf = wf.astype(dy.dtype)
    vk = pl.BlockSpec((1, cin), lambda b_i, r: (0, 0))
    vn = pl.BlockSpec((1, cout), lambda b_i, r: (0, 0))
    dx, db, dg = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, relu=relu, bn=bn, th=th, h=h,
                          w=ww, cout=cout, nb=nb, nh=nh),
        grid=(nb, nh),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY),
                  vn, vn,
                  pl.BlockSpec((9 * cout, cin), lambda b_i, r: (0, 0)),
                  pl.BlockSpec((1, th, ww, cin),
                               lambda b_i, r: (b_i, r, 0, 0)),
                  vk, vk, vk, vk],
        out_specs=[pl.BlockSpec((1, th, ww, cin),
                                lambda b_i, r: (b_i, r, 0, 0)),
                   vk, vk],
        out_shape=[_struct((nb, h, ww, cin), x.dtype, x),
                   _struct((1, cin), jnp.float32, x),
                   _struct((1, cin), jnp.float32, x)],
        scratch_shapes=[pltpu.VMEM((th + 2, ww + 2, cout), dy.dtype),
                        pltpu.VMEM((th + 2, ww + 2, cout), y.dtype),
                        pltpu.SemaphoreType.DMA((3,)),
                        pltpu.SemaphoreType.DMA((3,)),
                        pltpu.VMEM((1, cin), jnp.float32),
                        pltpu.VMEM((1, cin), jnp.float32)],
        # db/dg scratch accumulates across grid cells — sequential grid only.
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interp,
    )(dy, y, ds[None], dss[None], wf, x, mu[None], inv[None],
      gamma[None], beta[None])
    return dx, db[0], dg[0]


# ---------------------------------------------------------------------------
# Backward kernel 2: dw[t] = aᵀ_shifted @ dY, a recomputed in the prologue
# ---------------------------------------------------------------------------

def _bwd_dw_kernel(x_any, mu_ref, inv_ref, g_ref, b_ref,
                   dy_ref, y_ref, ds_ref, dss_ref,
                   dw_ref, slab, sem, acc, *,
                   relu: bool, bn: bool, th: int, h: int, w: int, cin: int,
                   nb: int, nh: int):
    b_i, r = pl.program_id(1), pl.program_id(2)

    @pl.when((b_i == 0) & (r == 0))
    def _():
        acc[...] = jnp.zeros_like(acc)

    _start_slab_dmas(x_any, slab, sem, b_i, r, th=th, nh=nh)
    a = _normalize_mask(slab[...], mu_ref[...], inv_ref[...], g_ref[...],
                        b_ref[...], relu=relu, bn=bn, r0=r * th, h=h, w=w,
                        out_dtype=dy_ref.dtype)
    yf = y_ref[0].reshape(th * w, -1).astype(jnp.float32)
    dyf = (dy_ref[0].reshape(th * w, -1).astype(jnp.float32)
           + ds_ref[...] + 2.0 * yf * dss_ref[...])
    # aᵀ_shifted @ dY: contract the row axis of the patch matrix.
    acc[...] += jax.lax.dot_general(
        _patches(a, th, w, cin), dyf.astype(dy_ref.dtype),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when((b_i == nb - 1) & (r == nh - 1))
    def _():
        dw_ref[...] = acc[...].astype(dw_ref.dtype)


def _bwd_dw(x, mu, inv, gamma, beta, dy, y, ds, dss, relu, bn,
            interpret: Optional[bool] = None):
    nb, h, ww, cin = x.shape
    cout = dy.shape[-1]
    th = _row_block(h, ww)
    nh = h // th
    tn = _tile(cout, 256)  # bound the (9Cin, tn) f32 accumulator in VMEM
    interp = _should_interpret() if interpret is None else interpret
    vk = pl.BlockSpec((1, cin), lambda ni, b_i, r: (0, 0))
    vn = pl.BlockSpec((1, tn), lambda ni, b_i, r: (0, ni))
    ys = pl.BlockSpec((1, th, ww, tn), lambda ni, b_i, r: (b_i, r, 0, ni))
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, relu=relu, bn=bn, th=th, h=h,
                          w=ww, cin=cin, nb=nb, nh=nh),
        grid=(cout // tn, nb, nh),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  vk, vk, vk, vk, ys, ys, vn, vn],
        out_specs=pl.BlockSpec((9 * cin, tn), lambda ni, b_i, r: (0, ni)),
        out_shape=_struct((9 * cin, cout), dy.dtype, x),
        scratch_shapes=[pltpu.VMEM((th + 2, ww + 2, cin), x.dtype),
                        pltpu.SemaphoreType.DMA((3,)),
                        pltpu.VMEM((9 * cin, tn), jnp.float32)],
        # The dw accumulator carries across the (nb, nh) axes per cout tile
        # — sequential grid only.
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interp,
    )(x, mu[None], inv[None], gamma[None], beta[None], dy, y,
      ds[None], dss[None])
    return dw.reshape(3, 3, cin, cout)


# ---------------------------------------------------------------------------
# jnp twin and the public custom-VJP op
# ---------------------------------------------------------------------------

def _twin_a(x, mu, inv, gamma, beta, relu, bn):
    if not bn:
        return x
    a = (x.astype(jnp.float32) - mu) * (inv * gamma) + beta
    if relu:
        a = jnp.maximum(a, 0.0)
    return a.astype(x.dtype)


def _conv3(a, w):
    return lax.conv_general_dilated(
        a, w.astype(a.dtype), window_strides=(1, 1),
        padding=[(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)


def _twin_fwd(x, mu, inv, gamma, beta, w, relu, bn):
    a = _twin_a(x, mu, inv, gamma, beta, relu, bn)
    y = _conv3(a, w).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, yf.sum(axis=(0, 1, 2)), (yf * yf).sum(axis=(0, 1, 2))


def _twin_bwd(dy, ds, dss, x, mu, inv, gamma, beta, w, y, relu, bn):
    yf = y.astype(jnp.float32)
    dyf = (dy.astype(jnp.float32) + ds + 2.0 * yf * dss).astype(dy.dtype)
    a = _twin_a(x, mu, inv, gamma, beta, relu, bn)
    # f32 vjp: a bf16 conv with preferred f32 output transposes into a
    # conv over mixed (f32 cotangent, bf16 weight) operands, which lax
    # rejects; the twin is the CPU/check_vma path, so full f32 is both
    # legal and the better reference.
    _, vjp = jax.vjp(lambda a_, w_: _conv3(a_, w_),
                     a.astype(jnp.float32), w.astype(jnp.float32))
    da, dw = vjp(dyf.astype(jnp.float32))
    da = da.astype(jnp.float32)
    if bn:
        xh = (x.astype(jnp.float32) - mu) * inv
        dzl = da
        if relu:
            z = xh * gamma + beta
            dzl = jnp.where(z > 0, da, 0.0)
        dx = (dzl * (gamma * inv)).astype(x.dtype)
        db = dzl.sum(axis=(0, 1, 2))
        dg = (dzl * xh).sum(axis=(0, 1, 2))
    else:
        dx = da.astype(x.dtype)
        db = dg = jnp.zeros_like(mu)
    return dx, db, dg, dw.astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def bn_conv3x3_stats(x, mu, inv, gamma, beta, w, relu: bool = True,
                     bn: bool = True):
    """y = conv3x3(relu((x−μ)·inv·γ + β), w) with per-out-channel (Σy, Σy²).

    x: (B, H, W, Cin) raw previous-layer output; w: (3, 3, Cin, Cout);
    stride 1, SAME (pad 1). With ``bn=False`` the prologue is the identity
    (μ/inv/γ/β ignored but must be (Cin,)-shaped). Returns ``(y, s, ss)``.
    """
    y, s, ss = _fwd_any(x, mu, inv, gamma, beta, w, relu, bn)
    return y, s, ss


def _fwd_any(x, mu, inv, gamma, beta, w, relu, bn):
    if _jnp_twin(x):
        return _twin_fwd(x, mu, inv, gamma, beta, w, relu, bn)
    return _fwd(x, mu, inv, gamma, beta, w, relu, bn)


def _vjp_fwd(x, mu, inv, gamma, beta, w, relu, bn):
    y, s, ss = _fwd_any(x, mu, inv, gamma, beta, w, relu, bn)
    return (y, s, ss), (x, mu, inv, gamma, beta, w, y)


def _vjp_bwd(relu, bn, saved, cots):
    x, mu, inv, gamma, beta, w, y = saved
    dy, ds, dss = cots
    if _jnp_twin(x):
        dx, db, dg, dw = _twin_bwd(dy, ds, dss, x, mu, inv, gamma, beta,
                                   w, y, relu, bn)
    else:
        dx, db, dg = _bwd_dx(dy, y, ds, dss, w, x, mu, inv, gamma, beta,
                             relu, bn)
        dw = _bwd_dw(x, mu, inv, gamma, beta, dy, y, ds, dss, relu, bn)
    dw = _match_vma(dw.astype(jnp.float32), w)
    if not bn:
        zero = jnp.zeros_like(mu)
        return (dx, zero, zero, zero, zero, dw)
    dmu = -gamma * inv * db
    dinv = gamma * dg / inv
    return (dx,
            _match_vma(dmu, mu), _match_vma(dinv, inv),
            _match_vma(dg.astype(gamma.dtype), gamma),
            _match_vma(db.astype(beta.dtype), beta),
            dw)


bn_conv3x3_stats.defvjp(_vjp_fwd, _vjp_bwd)


def conv3x3_stats(x, w):
    """y = conv3x3(x, w) with (Σy, Σy²) — identity prologue (the shape for
    inputs that are already materialized activations)."""
    zeros = jnp.zeros((x.shape[-1],), jnp.float32)
    return bn_conv3x3_stats(x, zeros, zeros, zeros, zeros, w, False, False)
