"""Embedding-table lookup with an SPMD-friendly backward.

Why this exists (VERDICT r4 Missing #5): with ZeRO-3-style parameter
sharding on (`fsdp` axis, logical ``embed`` dim), the plain ``table[ids]``
backward is a scatter-add whose *updates* are the token-gradient activation
— batch-sharded over every device (the ``batch -> (data, fsdp)`` rule) —
while its *output* (the table gradient) is embed-sharded over ``fsdp``.
XLA's scatter partitioner cannot bridge those layouts and falls back to
"Involuntary full rematerialization": it all-gathers the full updates
tensor to every device, scatters redundantly, then re-partitions. The
dp4xfsdp2 dryrun (``__graft_entry__.dryrun_multichip``) surfaced the
warning on ``BertMLM/embeddings_ln``'s backward.

The fix: scatter into an explicitly *replicated* gradient instead. With a
replicated output XLA partitions the scatter as local-partial-scatter +
all-reduce — a supported, collective-efficient path (the all-reduce moves
one table, V x D, instead of replicating a B x S x D activation) — and the
optimizer's embed-sharded gradient use then costs one local slice.
Headroom note: a reduce-scatter straight into the fsdp shards would halve
the all-reduce traffic; XLA cannot be constrained into that form through a
scatter today, so this op trades that factor for never hitting the
replicate-everything path. Numerics pinned by tests/test_spmd_hygiene.py::
test_embedding_lookup_matches_plain_gather; the same file's subprocess
test greps a real dp x fsdp compile's stderr for the warning so the bad
path cannot silently return.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu import compat


def _replicate_if_meshed(x):
    """with_sharding_constraint(x, P()) under an ambient mesh, identity
    otherwise (plain single-device unit tests run without a mesh)."""
    try:
        mesh = compat.get_abstract_mesh()
        if mesh is None or not mesh.shape_tuple:
            return x
        return jax.lax.with_sharding_constraint(x, P())
    except Exception:
        return x


@functools.lru_cache(maxsize=None)
def _lookup_for(shape: tuple, dtype_name: str):
    """custom_vjp specialized per table shape/dtype — the residual then
    carries only ``ids`` (shapes/dtypes are not valid JAX residual leaves,
    and saving the table itself would pin it across the backward)."""

    @jax.custom_vjp
    def lookup(table, ids):
        return table[ids]

    def fwd(table, ids):
        return table[ids], ids

    def bwd(ids, dx):
        grad = jnp.zeros(shape, dx.dtype).at[ids].add(dx)
        grad = _replicate_if_meshed(grad).astype(dtype_name)
        zero_ids = np.zeros(ids.shape, jax.dtypes.float0)  # ids: integral
        return grad, zero_ids

    lookup.defvjp(fwd, bwd)
    return lookup


def embedding_lookup(table, ids):
    """``table[ids]`` (gather over dim 0) with the SPMD-friendly backward
    described in the module docstring."""
    return _lookup_for(tuple(table.shape),
                       jnp.dtype(table.dtype).name)(table, ids)
