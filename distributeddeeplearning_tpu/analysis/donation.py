"""Donation/aliasing hazard pass — the PR 5 / PR 9 invariant, statically.

The invariant (train/loop.py, train/checkpoint.py): an array that came out
of a checkpoint restore — or any orbax-aliased / snapshot-shared buffer —
must pass through ``checkpoint.device_copy`` before it may reach a donated
parameter of a compiled step. Violating it is not a crash at the violation
site: the donated-over buffer is memory orbax still owns, so the live state
(and every checkpoint saved from it) silently turns to garbage a few steps
later (PR 5: SIGSEGV steps after a warm resume through a deserialized AOT
executable; PR 9: async cadence saves serializing zero-copy views the next
step had already donated over). Both were found at runtime by the flight
recorder; this pass encodes the rule so the corpus in
``tests/test_ddl_lint.py`` proves it would have caught each statically.

Mechanics: an intra-procedural AST taint walk per function.

- *Sources*: calls whose terminal name contains ``restore`` (``
  restore_latest``, ``restore_latest_for_eval``, ``restore_latest_params``,
  orbax ``StandardRestore`` wrappers) taint their assigned names.
- *Sanitizer*: assignment through a ``device_copy(...)`` call kills taint —
  the copy allocates fresh XLA-owned buffers.
- *Sinks*: argument positions of *donating callees* — names bound from
  ``jax.jit(..., donate_argnums=...)`` in the same module, plus the
  configured cross-module dispatch names (``train_step``/``fused_runner``
  are function parameters at their loop.py call site, invisible to a
  module-local scan).
- Branches union: a name is tainted after an ``if`` when EITHER arm leaves
  it tainted (the hazard only needs one path). Results of ordinary calls
  are treated clean — this pass prefers a miss over a false positive,
  because the gate fails tier-1 and a noisy gate gets baselined into
  uselessness.

Separately, :func:`check_snapshot_before_save` encodes the PR 9 save-side
rule as a lexical-presence check: a function that hands state to orbax
``StandardSave`` must call ``device_copy`` somewhere before the save (the
snapshot that makes an async save immune to later donation). Presence, not
path-sensitivity, on purpose: checkpoint.py legitimately snapshots under a
backend conditional, and a branch-union would false-positive on it.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from distributeddeeplearning_tpu.analysis import finding, iter_py_files

# Dispatch names that donate their first arg but are bound cross-module
# (function parameters at the call site, so a module-local
# jax.jit(donate_argnums=...) scan cannot see them).
DONATING_CALLEES = ("train_step", "fused_runner", "jitted_step")

SANITIZERS = ("device_copy",)


def _terminal_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _shallow_walk(fn: ast.AST):
    """Function-body walk that does NOT descend into nested function
    definitions (they are separate scopes, visited by the module walk)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _is_source_call(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _terminal_name(node.func) or ""
    return "restore" in name.lower()


def _is_sanitizer_call(node: ast.expr) -> bool:
    return (isinstance(node, ast.Call)
            and _terminal_name(node.func) in SANITIZERS)


def module_donating_callees(tree: ast.Module) -> set[str]:
    """Names bound (anywhere in the module) from a ``jax.jit`` /
    ``jit`` call that passes ``donate_argnums``/``donate_argnames``."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = getattr(node, "value", None)
        if not (isinstance(value, ast.Call)
                and _terminal_name(value.func) in ("jit", "pjit")):
            continue
        if not any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in value.keywords):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


class _TaintWalker:
    """One function's worth of flow-insensitive-within-expression,
    flow-sensitive-across-statement taint."""

    def __init__(self, donating: set[str], path: str):
        self.donating = donating
        self.path = path
        self.findings: list[dict] = []

    # -- expression taint ------------------------------------------------
    def _expr_tainted(self, node: ast.expr, taint: set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in taint
        if isinstance(node, ast.Call):
            if _is_sanitizer_call(node):
                return False
            return _is_source_call(node)
        if isinstance(node, ast.IfExp):
            return (self._expr_tainted(node.body, taint)
                    or self._expr_tainted(node.orelse, taint))
        if isinstance(node, ast.BoolOp):
            return any(self._expr_tainted(v, taint) for v in node.values)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._expr_tainted(e, taint) for e in node.elts)
        if isinstance(node, ast.Attribute):
            # state.params of a tainted state aliases the same buffers.
            return self._expr_tainted(node.value, taint)
        if isinstance(node, ast.Subscript):
            return self._expr_tainted(node.value, taint)
        if isinstance(node, ast.NamedExpr):
            return self._expr_tainted(node.value, taint)
        return False

    def _check_sinks(self, node: ast.expr, taint: set[str]) -> None:
        for call in [n for n in ast.walk(node)
                     if isinstance(n, ast.Call)]:
            callee = _terminal_name(call.func)
            if callee not in self.donating:
                continue
            for arg in list(call.args) + [kw.value
                                          for kw in call.keywords]:
                if self._expr_tainted(arg, taint):
                    named = (arg.id if isinstance(arg, ast.Name)
                             else ast.unparse(arg)[:40])
                    self.findings.append(finding(
                        "donation", "donation-hazard",
                        f"restored/aliased value {named!r} reaches "
                        f"donating callee {callee}() without "
                        f"checkpoint.device_copy — the donated-over "
                        f"buffer still aliases restore-owned memory "
                        f"(the PR 5 warm-resume corruption)",
                        file=self.path, line=call.lineno))

    # -- statement walk --------------------------------------------------
    def _assign_targets(self, targets: Iterable[ast.expr],
                        tainted: bool, taint: set[str]) -> None:
        for t in targets:
            if isinstance(t, ast.Name):
                (taint.add if tainted else taint.discard)(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                # A tainted RHS (tuple-returning source) taints every
                # element; a clean RHS cleans them.
                self._assign_targets(t.elts, tainted, taint)
            elif isinstance(t, ast.Starred):
                self._assign_targets([t.value], tainted, taint)

    def walk_body(self, body: Sequence[ast.stmt],
                  taint: set[str]) -> set[str]:
        for stmt in body:
            taint = self._walk_stmt(stmt, taint)
        return taint

    def _walk_stmt(self, stmt: ast.stmt, taint: set[str]) -> set[str]:
        # Sinks first: the RHS executes before the assignment rebinds.
        for value in ast.iter_child_nodes(stmt):
            if isinstance(value, ast.expr):
                self._check_sinks(value, taint)
        if isinstance(stmt, ast.Assign):
            tainted = self._expr_tainted(stmt.value, taint)
            self._assign_targets(stmt.targets, tainted, taint)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign_targets([stmt.target],
                                 self._expr_tainted(stmt.value, taint),
                                 taint)
        elif isinstance(stmt, ast.AugAssign):
            pass  # x += tainted keeps x's prior status; too noisy to flag
        elif isinstance(stmt, ast.If):
            a = self.walk_body(stmt.body, set(taint))
            b = self.walk_body(stmt.orelse, set(taint))
            taint = a | b  # hazard needs only one arm
        elif isinstance(stmt, (ast.For, ast.While)):
            # Two passes approximate the loop fixpoint (taint introduced
            # on iteration 1 reaches sinks on pass 2).
            for _ in range(2):
                taint |= self.walk_body(stmt.body, set(taint))
            taint |= self.walk_body(stmt.orelse, set(taint))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            taint = self.walk_body(stmt.body, taint)
        elif isinstance(stmt, ast.Try):
            t = self.walk_body(stmt.body, set(taint))
            for handler in stmt.handlers:
                t |= self.walk_body(handler.body, set(taint))
            t |= self.walk_body(stmt.orelse, set(t))
            taint = self.walk_body(stmt.finalbody, t)
        return taint


def analyze_tree(tree: ast.Module, path: str, *,
                 donating_callees: Optional[Sequence[str]] = None
                 ) -> list[dict]:
    donating = set(donating_callees if donating_callees is not None
                   else DONATING_CALLEES)
    donating |= module_donating_callees(tree)
    findings: list[dict] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        walker = _TaintWalker(donating, path)
        walker.walk_body(node.body, set())
        findings.extend(walker.findings)
    findings.extend(check_snapshot_before_save(tree, path))
    return findings


def check_snapshot_before_save(tree: ast.Module, path: str) -> list[dict]:
    """Any function handing state to orbax ``StandardSave`` must call
    ``device_copy`` lexically before the save call (the PR 9 async-save
    snapshot). Presence-based on purpose — see module docstring.

    Exemption that tracks the actual hazard: a save the same function
    blocks on (``wait_until_finished`` lexically after it) cannot race a
    later donation — the buffers are fully read before anyone could
    donate them (tools/import_hf.py's one-shot conversion save)."""
    findings: list[dict] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        save_line = None
        copy_line = None
        wait_line = None
        for sub in _shallow_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _terminal_name(sub.func)
            if name in SANITIZERS:
                copy_line = (sub.lineno if copy_line is None
                             else min(copy_line, sub.lineno))
            elif name == "wait_until_finished":
                wait_line = (sub.lineno if wait_line is None
                             else max(wait_line, sub.lineno))
            elif name == "save" and any(
                    isinstance(a, ast.Call)
                    and _terminal_name(a.func) == "StandardSave"
                    for a in list(sub.args)
                    + [kw.value for kw in sub.keywords]):
                save_line = (sub.lineno if save_line is None
                             else min(save_line, sub.lineno))
        if save_line is not None and wait_line is not None \
                and wait_line > save_line:
            continue
        if save_line is not None and (copy_line is None
                                      or copy_line > save_line):
            findings.append(finding(
                "donation", "snapshot-before-save",
                f"{node.name}() hands state to orbax StandardSave with "
                f"no checkpoint.device_copy before it — an async save "
                f"can serialize zero-copy views a later step donates "
                f"over (the PR 9 silent-corruption bug)",
                file=path, line=save_line))
    return findings


def analyze_source(src: str, path: str = "<memory>", *,
                   donating_callees: Optional[Sequence[str]] = None
                   ) -> list[dict]:
    """Entry point for the seeded-violation corpus (no file needed)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as exc:
        return [finding("donation", "unparseable",
                        f"cannot parse: {exc}", file=path,
                        line=exc.lineno)]
    return analyze_tree(tree, path, donating_callees=donating_callees)


def analyze_file(path: str, *,
                 donating_callees: Optional[Sequence[str]] = None
                 ) -> list[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
    except OSError as exc:
        return [finding("donation", "unparseable",
                        f"cannot read: {exc}", file=path)]
    return analyze_source(src, path, donating_callees=donating_callees)


def analyze_paths(roots: Sequence[str]) -> list[dict]:
    findings: list[dict] = []
    for path in iter_py_files(roots):
        findings.extend(analyze_file(path))
    return findings
