"""ddl-lint: static distributed-correctness analysis (docs/static_analysis.md).

Every distributed-correctness bug this repo has shipped was found the hard
way at runtime: PR 5's donated-over orbax-restored arrays corrupted the
heap steps after a warm resume, PR 9's async cadence saves serialized
zero-copy views the next step had already donated over, and a collective
schedule that differs across ranks is the classic SPMD hang (Horovod,
PAPERS.md: arXiv 1802.05799). This package is the compile/lint-time layer
that catches those bug classes before a chip ever runs them:

- :mod:`.collectives` — extract the ordered collective-op schedule from a
  jaxpr or lowered-HLO text, fingerprint it canonically, and verify
  schedule identity across simulated ranks/configs, deterministic bucket
  ordering against ``parallel/collectives.py``'s planner, and the
  AOT-cache pairing (a ``perf/aot.py`` config fingerprint may never map
  to two different schedules).
- :mod:`.donation` — AST taint analysis encoding the invariant PRs 5 and
  9 each rediscovered at runtime: a restored / orbax-aliased / snapshot-
  shared array must pass through ``checkpoint.device_copy`` before it can
  reach a donated argument of a compiled step.
- :mod:`.lints` — repo-invariant AST lints: fsync-before-fire event
  emitters, ``.cache/*.json`` writes routed through
  ``observability/sidecars.py``, telemetry spans actually entered,
  provenance stamps on perf-record writes, and axis-name consistency
  between ``parallel/mesh.py`` and collective call sites.

All passes share one finding shape (:func:`finding`) and run through the
``tools/ddl_lint.py`` CLI, which gates tier-1 via ``@pytest.mark.lint``
tests. Everything here is *analysis*: passes report, they never mutate,
and every reader is tolerant — truncated HLO, unknown custom-call
collectives, and jax-version drift degrade to a reported note, never a
crash (the ``observability/flight.py`` tolerant-reader rule).
"""
from __future__ import annotations

import os
from typing import Any, Iterator, Optional

PASSES = ("collectives", "donation", "lints")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def finding(pass_name: str, rule: str, message: str, *,
            file: Optional[str] = None,
            line: Optional[int] = None) -> dict[str, Any]:
    """The one finding shape every pass emits (and the baseline keys on).

    ``file`` is stored repo-relative when it lives under the repo, so
    baselines and JSON output are stable across checkouts.
    """
    if file:
        root = repo_root()
        absfile = os.path.abspath(file)
        if absfile.startswith(root + os.sep):
            file = os.path.relpath(absfile, root)
    return {"pass": pass_name, "rule": rule, "message": message,
            "file": file, "line": line}


def suppression_matches(finding_rec: dict, suppression: dict) -> bool:
    """A baseline entry suppresses a finding when every key it carries
    matches (``rule`` and/or ``file``; ``file`` matches on suffix so a
    bare basename works). Line numbers are deliberately NOT part of the
    key — they drift with every edit."""
    rule = suppression.get("rule")
    if rule and rule != finding_rec.get("rule"):
        return False
    file = suppression.get("file")
    if file:
        have = finding_rec.get("file") or ""
        if not (have == file or have.endswith(os.sep + file)
                or have.endswith("/" + file)):
            return False
    return bool(rule or file)


def iter_py_files(roots, *, exclude_parts=("tests", "__pycache__",
                                           ".cache")) -> Iterator[str]:
    """Yield .py files under ``roots`` (files yielded as-is), skipping
    test trees and caches — the passes lint the shipping code; the test
    corpus seeds its violations in temp files on purpose."""
    for root in roots:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in exclude_parts]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)
