"""Repo-invariant AST lints — conventions the repo already bled for.

Each rule here encodes a convention an earlier PR introduced for a
concrete failure mode, now checked mechanically so the next subsystem
cannot quietly regress it:

- ``sidecar-direct-write``: every ``.cache/*.json`` run sidecar goes
  through ``observability/sidecars.py`` (atomic rename, envelope with
  ``schema``/``written_at``, never-raise). A direct ``open``/``json.dump``
  is a torn-read and stale-data hazard the sidecar API exists to close.
- ``fsync-before-fire``: a function that kills its own process
  (``os.kill(os.getpid(), ...)`` — the faults.py chaos emitters) must
  have put a flight record / flush on disk first, or the post-mortem
  loses the one event that explains the death.
- ``unpaired-telemetry-span``: ``telemetry.span(...)`` returns a context
  manager; a call whose result is discarded times nothing and silently
  drops the phase from every trace and perf-gate phase-mix check.
- ``perf-record-provenance``: every serialized perf record (a dict with
  a ``"metric"`` key) carries a ``perf_report.annotate`` provenance stamp
  — PR 6's rule that perf claims are dated, attributed, and
  staleness-graded or they don't exist.
- ``page-table-log-before-dispatch``: a serve-engine function that
  stores into a KV ``page_table`` subscript and then launches a
  prefill/decode program must put a flight ``record(...)`` between the
  mutation and the dispatch — the page table is the map to pool state
  a crashed replica cannot otherwise reconstruct.
- ``cow-before-write``: a function that dispatches a KV page copy
  (a call whose name mentions ``page_copy``/``copy_page`` — the
  copy-on-write clone of a shared prefix page) must have flight-logged
  a ``record(...)`` first. The clone changes which physical page a
  slot's writes land in; a replica killed mid-copy with no record of
  it leaves a page table a post-mortem cannot trust.
- ``serve-span-registered``: every telemetry emission whose literal
  name starts with ``serve:`` (span / instant / record_span / flow /
  async begin+end) must use a name registered in
  ``serve/tracing.REGISTERED_PHASES``. The serve trace schema
  (docs/serve_tracing.md) is what tools/trace_report.py and the
  attribution tests key on — an unregistered name is a span the whole
  reporting stack silently ignores.
- ``master-weight-cast``: optimizer / master-weight state must stay
  float32 (ISSUE 20's silent-precision-loss bug class: a bf16 master
  drops every update below ~2^-8 of the weight magnitude and training
  quietly plateaus). Any cast of a value whose name mentions
  ``opt_state`` / ``master`` to a sub-fp32 dtype (``astype``, or a
  ``dtype=``-carrying array constructor) outside the sanctioned
  gather-path helpers in ``parallel/zero.py`` is flagged.
- ``axis-name-consistency``: string axis names at ``psum`` /
  ``psum_scatter`` / ``all_gather`` / ``pmean`` / ... call sites must be
  declared in ``parallel/mesh.py``'s ``MESH_AXES`` — a typo'd axis name
  is an obscure trace error at best and a wrong-group collective at
  worst. Module-level tuple constants (``DATA_AXES``-style) are resolved;
  dynamic values are out of static reach and skipped.

All rules are AST-only (no imports of the linted code, no jax) and are
tuned to zero false positives on this repo — the gate fails tier-1, and
a noisy gate gets baselined into uselessness.
"""
from __future__ import annotations

import ast
import os
from typing import Optional, Sequence

from distributeddeeplearning_tpu.analysis import (finding, iter_py_files,
                                                  repo_root)

# Files exempt from sidecar-direct-write: the sidecar implementation
# itself, and the doctor (read-only display of raw paths).
_SIDECAR_EXEMPT = ("observability/sidecars.py",)

_COLLECTIVE_CALLS = {"psum", "psum_scatter", "all_gather", "pmean",
                     "pmax", "pmin", "all_to_all", "ppermute",
                     "reduce_scatter"}


def _terminal_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _const_str(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _shallow_walk(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested function
    definitions — those are visited as their own scope by the outer
    ``ast.walk`` over the module, and double-visiting them both
    duplicates findings and mixes scopes."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# sidecar-direct-write
# ---------------------------------------------------------------------------

def check_sidecar_writes(tree: ast.Module, path: str) -> list[dict]:
    rel = os.path.relpath(os.path.abspath(path), repo_root())
    if rel.replace(os.sep, "/").endswith(_SIDECAR_EXEMPT):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        consts = [c for c in (_const_str(a) for a in node.args)
                  if c is not None]
        hit = None
        if name == "join" and ".cache" in consts and any(
                c.endswith(".json") for c in consts):
            hit = next(c for c in consts if c.endswith(".json"))
        elif name == "open" and node.args:
            c = _const_str(node.args[0])
            if c and ".cache/" in c.replace(os.sep, "/") \
                    and c.endswith(".json"):
                hit = c
        if hit:
            findings.append(finding(
                "lints", "sidecar-direct-write",
                f"direct .cache sidecar path {hit!r} — route through "
                f"observability/sidecars.py (path_for/write/read) for "
                f"atomic rename + schema/written_at envelope",
                file=path, line=node.lineno))
    return findings


# ---------------------------------------------------------------------------
# fsync-before-fire
# ---------------------------------------------------------------------------

def _is_self_kill(call: ast.Call) -> bool:
    if _terminal_name(call.func) != "kill" or not call.args:
        return False
    first = call.args[0]
    return (isinstance(first, ast.Call)
            and _terminal_name(first.func) == "getpid")


def check_fsync_before_fire(tree: ast.Module, path: str) -> list[dict]:
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        durable_line = None  # earliest record/fsync/flush
        kill_lines: list[int] = []
        for sub in _shallow_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _terminal_name(sub.func)
            if name in ("record", "fsync", "flush"):
                durable_line = (sub.lineno if durable_line is None
                                else min(durable_line, sub.lineno))
            elif _is_self_kill(sub):
                kill_lines.append(sub.lineno)
        for kill_line in sorted(kill_lines):
            if durable_line is None or durable_line > kill_line:
                findings.append(finding(
                    "lints", "fsync-before-fire",
                    f"{node.name}() kills its own process with no "
                    f"flight record / fsync / flush before the kill "
                    f"— the event that explains the death dies "
                    f"with the process",
                    file=path, line=kill_line))
    return findings


# ---------------------------------------------------------------------------
# unpaired-telemetry-span
# ---------------------------------------------------------------------------

def check_unpaired_spans(tree: ast.Module, path: str) -> list[dict]:
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and _terminal_name(node.value.func) == "span"):
            continue
        findings.append(finding(
            "lints", "unpaired-telemetry-span",
            "span(...) result discarded — it is a context manager; a "
            "span never entered times nothing and the phase vanishes "
            "from traces and the perf gate's phase mix "
            "(use `with tele.span(...):`)",
            file=path, line=node.lineno))
    return findings


# ---------------------------------------------------------------------------
# perf-record-provenance
# ---------------------------------------------------------------------------

def _is_metric_dict(node: ast.expr) -> bool:
    return (isinstance(node, ast.Dict)
            and any(_const_str(k) == "metric"
                    for k in node.keys if k is not None))


def check_perf_record_provenance(tree: ast.Module, path: str) -> list[dict]:
    """``json.dump(s)`` of a perf record (dict with a ``"metric"`` key,
    literal or via a local name) must be stamped: either the dumps arg is
    an ``annotate(...)`` call, or ``annotate(<name>, ...)`` ran lexically
    earlier in the same function."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        metric_names: dict[str, int] = {}   # name -> assign line
        annotated: dict[str, int] = {}      # name -> annotate line
        dumps: list[ast.Call] = []
        for sub in _shallow_walk(node):
            if isinstance(sub, ast.Assign) and _is_metric_dict(sub.value):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        metric_names[t.id] = sub.lineno
            elif isinstance(sub, ast.Call):
                name = _terminal_name(sub.func)
                if name == "annotate" and sub.args:
                    a = sub.args[0]
                    if isinstance(a, ast.Name):
                        annotated[a.id] = min(
                            annotated.get(a.id, sub.lineno), sub.lineno)
                elif name in ("dumps", "dump") and sub.args:
                    dumps.append(sub)
        for call in dumps:
            arg = call.args[0]
            if isinstance(arg, ast.Call) \
                    and _terminal_name(arg.func) == "annotate":
                continue
            bad = None
            if _is_metric_dict(arg):
                bad = "a literal perf record"
            elif isinstance(arg, ast.Name) and arg.id in metric_names:
                if arg.id in annotated \
                        and annotated[arg.id] < call.lineno:
                    continue
                bad = f"perf record {arg.id!r}"
            if bad:
                findings.append(finding(
                    "lints", "perf-record-provenance",
                    f"{bad} serialized without a perf_report.annotate "
                    f"provenance stamp — perf claims must carry "
                    f"fresh/stale grading, git rev, and attempt "
                    f"history (PR 6 rule)",
                    file=path, line=call.lineno))
    return findings


# ---------------------------------------------------------------------------
# page-table-log-before-dispatch
# ---------------------------------------------------------------------------

_PAGE_TABLE_NAMES = ("_page_table", "page_table")


def check_page_table_log_before_dispatch(tree: ast.Module,
                                         path: str) -> list[dict]:
    """A serve-engine page-table mutation must hit the flight record
    before the step that consumes it dispatches.

    The page table is the one piece of engine state a post-mortem cannot
    reconstruct after a crash (pool contents die with the process, the
    table is the map to them). The serve-chaos PR's convention: any
    function that stores into a ``page_table``/``_page_table`` subscript
    and then launches a prefill/decode program must ``record(...)``
    between the mutation and the dispatch — otherwise a replica killed
    inside that program leaves a flight record that never mentions the
    mutation the dying step was built on."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stores: list[int] = []
        records: list[int] = []
        dispatches: list[int] = []
        for sub in _shallow_walk(node):
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and _terminal_name(t.value) \
                            in _PAGE_TABLE_NAMES:
                        stores.append(sub.lineno)
            elif isinstance(sub, ast.Call):
                name = _terminal_name(sub.func)
                if name == "record":
                    records.append(sub.lineno)
                elif name is not None and ("prefill" in name.lower()
                                           or "decode" in name.lower()):
                    dispatches.append(sub.lineno)
        for d in sorted(dispatches):
            prior = [s for s in stores if s < d]
            if not prior:
                continue
            if not any(min(prior) <= r < d for r in records):
                findings.append(finding(
                    "lints", "page-table-log-before-dispatch",
                    f"{node.name}() mutates the KV page table (line "
                    f"{max(prior)}) and dispatches a prefill/decode "
                    f"program (line {d}) with no flight record in "
                    f"between — a replica killed inside that program "
                    f"leaves no durable trace of the mapping the dying "
                    f"step was built on",
                    file=path, line=d))
                break  # one finding per function tells the story
    return findings


# ---------------------------------------------------------------------------
# cow-before-write
# ---------------------------------------------------------------------------

def check_cow_before_write(tree: ast.Module, path: str) -> list[dict]:
    """A copy-on-write page clone must be flight-logged before it
    dispatches — same record-then-dispatch discipline as
    ``page-table-log-before-dispatch``, applied to the COW copy.

    The clone rewires a slot's page mapping (its writes start landing in
    the private copy instead of the shared prefix page); a replica
    SIGKILLed inside the copy with no record of it leaves a flight log
    that still describes the OLD mapping. Any call whose terminal name
    mentions ``page_copy``/``copy_page`` counts as the dispatch; a
    ``record(...)`` lexically earlier in the same function satisfies the
    rule."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        record_line = None
        copies: list[int] = []
        for sub in _shallow_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _terminal_name(sub.func)
            if name == "record":
                record_line = (sub.lineno if record_line is None
                               else min(record_line, sub.lineno))
            elif name is not None and ("page_copy" in name.lower()
                                       or "copy_page" in name.lower()):
                copies.append(sub.lineno)
        for c in sorted(copies):
            if record_line is None or record_line > c:
                findings.append(finding(
                    "lints", "cow-before-write",
                    f"{node.name}() dispatches a KV page copy (line {c}) "
                    f"with no flight record before it — a replica killed "
                    f"mid-copy leaves a log that still describes the old "
                    f"page mapping (copy-on-write must be logged before "
                    f"it rewires the table)",
                    file=path, line=c))
                break  # one finding per function tells the story
    return findings


# ---------------------------------------------------------------------------
# axis-name-consistency
# ---------------------------------------------------------------------------

def declared_mesh_axes(mesh_path: Optional[str] = None) -> Optional[set]:
    """``MESH_AXES`` from parallel/mesh.py, by AST (no import)."""
    mesh_path = mesh_path or os.path.join(
        repo_root(), "distributeddeeplearning_tpu", "parallel", "mesh.py")
    try:
        tree = ast.parse(open(mesh_path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "MESH_AXES"
                   for t in targets):
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            axes = {_const_str(e) for e in value.elts}
            if None not in axes:
                return axes
    return None


def _module_tuple_consts(tree: ast.Module) -> dict[str, tuple[str, ...]]:
    """Module-level ``NAME = ("a", "b")`` string-tuple constants —
    resolvable axis aliases like steps.py's ``DATA_AXES``."""
    out: dict[str, tuple[str, ...]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            vals = tuple(_const_str(e) for e in node.value.elts)
            if vals and None not in vals:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = vals
    return out


def check_axis_names(tree: ast.Module, path: str,
                     mesh_axes: Optional[set] = None) -> list[dict]:
    if mesh_axes is None:
        mesh_axes = declared_mesh_axes()
    if not mesh_axes:
        return []  # mesh.py unreadable: tolerate, never guess
    aliases = _module_tuple_consts(tree)
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal_name(node.func) not in _COLLECTIVE_CALLS:
            continue
        axis_arg = None
        if len(node.args) >= 2:
            axis_arg = node.args[1]
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axes", "axis_names"):
                axis_arg = kw.value
        if axis_arg is None:
            continue
        names: list[str] = []
        if _const_str(axis_arg) is not None:
            names = [_const_str(axis_arg)]
        elif isinstance(axis_arg, (ast.Tuple, ast.List)):
            vals = [_const_str(e) for e in axis_arg.elts]
            if None in vals:
                continue  # dynamic element: out of static reach
            names = vals
        elif isinstance(axis_arg, ast.Name) and axis_arg.id in aliases:
            names = list(aliases[axis_arg.id])
        for name in names:
            if name not in mesh_axes:
                findings.append(finding(
                    "lints", "axis-name-consistency",
                    f"axis {name!r} at this "
                    f"{_terminal_name(node.func)}() call is not "
                    f"declared in parallel/mesh.py MESH_AXES "
                    f"{sorted(mesh_axes)} — a typo'd axis is a "
                    f"wrong-group collective",
                    file=path, line=node.lineno))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# serve-span-registered
# ---------------------------------------------------------------------------

_SERVE_EMITTERS = {"span", "instant", "record_span", "flow",
                   "async_begin", "async_end"}


def check_serve_span_registry(tree: ast.Module, path: str) -> list[dict]:
    """Every literal ``serve:*`` name at a telemetry emission site must
    be registered in ``serve/tracing.REGISTERED_PHASES`` — the schema
    the serve trace tooling (trace_report, attribution tests, docs) keys
    on. tracing.py is pure stdlib, so importing the registry here keeps
    the lint and the runtime schema one source of truth."""
    from distributeddeeplearning_tpu.serve.tracing import REGISTERED_PHASES

    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _terminal_name(node.func) in _SERVE_EMITTERS
                and node.args):
            continue
        name = _const_str(node.args[0])
        if name is None or not name.startswith("serve:"):
            continue
        if name not in REGISTERED_PHASES:
            findings.append(finding(
                "lints", "serve-span-registered",
                f"serve trace name {name!r} is not in "
                f"serve/tracing.REGISTERED_PHASES — register it (and "
                f"document it in docs/serve_tracing.md) or the serve "
                f"reporting stack silently ignores this event",
                file=path, line=node.lineno))
    return findings


# ---------------------------------------------------------------------------
# master-weight-cast
# ---------------------------------------------------------------------------

# Identifier fragments that mark a value as optimizer / master-weight
# state. Deliberately narrow (no "mu"/"nu"): the gate fails tier-1 and a
# noisy rule gets baselined into uselessness.
_MASTER_STATE_MARKERS = ("opt_state", "master")
# Sub-fp32 dtypes a master must never land in. fp32 and wider are fine;
# integer casts are shape bookkeeping, not precision loss.
_SUB_FP32_DTYPES = {"bfloat16", "float16", "bf16", "f16", "half"}
# The sanctioned policy helpers: parallel/zero.py's gather path casts
# *gathered params* to the policy's compute dtype on the wire (the
# sharded fp32 masters themselves are never rewritten — _scatter_members
# restores plan dtypes). A new helper that legitimately moves values out
# of fp32 is added here in the same diff that introduces it.
_MASTER_CAST_SANCTIONED = {"_gather_members", "all_gather_chunks",
                           "gather_params_overlapped"}
# Array constructors whose dtype= keyword retypes their first argument.
_DTYPE_KW_CONSTRUCTORS = {"asarray", "array", "full_like", "zeros_like",
                          "ones_like", "empty_like"}


def _dtype_token(node: ast.expr) -> Optional[str]:
    """The dtype a cast targets, as a lowercase token: 'bfloat16' from
    ``jnp.bfloat16`` / ``"bfloat16"`` / ``np.float16``; None when the
    dtype is not a statically readable literal."""
    s = _const_str(node)
    if s is not None:
        return s.lower()
    if isinstance(node, (ast.Attribute, ast.Name)):
        name = _terminal_name(node)
        return name.lower() if name else None
    return None


def _mentions_master_state(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if ident and any(m in ident.lower()
                         for m in _MASTER_STATE_MARKERS):
            return True
    return False


def _master_casts_in_scope(scope: ast.AST, path: str) -> list[dict]:
    out = []
    for node in _shallow_walk(scope):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        tok = target = None
        if (name == "astype" and isinstance(node.func, ast.Attribute)
                and node.args):
            tok = _dtype_token(node.args[0])
            target = node.func.value
        elif name in _DTYPE_KW_CONSTRUCTORS and node.args:
            kw = next((k for k in node.keywords if k.arg == "dtype"), None)
            if kw is not None:
                tok = _dtype_token(kw.value)
                target = node.args[0]
        if (tok in _SUB_FP32_DTYPES and target is not None
                and _mentions_master_state(target)):
            out.append(finding(
                "lints", "master-weight-cast",
                f"optimizer/master state cast to {tok} — master weights "
                f"and optimizer state stay float32 (a bf16 master drops "
                f"every update below ~2^-8 of the weight magnitude; "
                f"docs/mixed_precision.md). Wire-compression belongs in "
                f"the sanctioned parallel/zero.py gather helpers",
                file=path, line=node.lineno))
    return out


def check_master_weight_cast(tree: ast.Module, path: str) -> list[dict]:
    """Flag sub-fp32 casts of optimizer / master-weight state outside the
    sanctioned policy helpers. Scope-aware: each function body is
    scanned once (via ``_shallow_walk``), and bodies of helpers in
    :data:`_MASTER_CAST_SANCTIONED` are skipped entirely."""
    findings = _master_casts_in_scope(tree, path)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in _MASTER_CAST_SANCTIONED:
            continue
        findings.extend(_master_casts_in_scope(node, path))
    return findings


_CHECKS = (check_sidecar_writes, check_fsync_before_fire,
           check_unpaired_spans, check_perf_record_provenance,
           check_page_table_log_before_dispatch, check_cow_before_write,
           check_serve_span_registry, check_master_weight_cast)


def analyze_source(src: str, path: str = "<memory>", *,
                   mesh_axes: Optional[set] = None) -> list[dict]:
    try:
        tree = ast.parse(src)
    except SyntaxError as exc:
        return [finding("lints", "unparseable", f"cannot parse: {exc}",
                        file=path, line=exc.lineno)]
    findings: list[dict] = []
    for check in _CHECKS:
        findings.extend(check(tree, path))
    findings.extend(check_axis_names(tree, path, mesh_axes))
    return findings


def analyze_file(path: str, *, mesh_axes: Optional[set] = None
                 ) -> list[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
    except OSError as exc:
        return [finding("lints", "unparseable", f"cannot read: {exc}",
                        file=path)]
    return analyze_source(src, path, mesh_axes=mesh_axes)


def analyze_paths(roots: Sequence[str]) -> list[dict]:
    mesh_axes = declared_mesh_axes()
    findings: list[dict] = []
    for path in iter_py_files(roots):
        findings.extend(analyze_file(path, mesh_axes=mesh_axes))
    return findings
